"""Thread-fuzz stress test — the -race analogue.

The reference runs `go test -race` (Makefile:136-138); Python has no
TSan, so this drives the whole control plane with every controller on
its own worker threads while a fuzzer thread storms the host with
concurrent creates/updates/deletes and cluster flaps, then asserts the
world converges with no exceptions escaping any worker and no torn
state (placement/propagation invariants hold for every surviving
object)."""

import dataclasses
import random
import threading
import time

from test_e2e_slice import make_deployment, make_node

from kubeadmiral_tpu.runtime import lockcheck

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.clusterctl import (
    FEDERATED_CLUSTERS,
    FederatedClusterController,
    NODES,
)
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
from kubeadmiral_tpu.testing.fakekube import (
    AlreadyExists,
    ClusterFleet,
    Conflict,
    NotFound,
)
from kubeadmiral_tpu.transport.faults import FaultInjector, FaultPolicy, FaultyKube


class TestThreadStress:
    def test_concurrent_controllers_survive_event_storm(self):
        # The -race half (ISSUE 14): the storm runs under the lockcheck
        # harness — every make_lock() in the stack records acquisition
        # order, every _shared_fields_ rebind checks its lock — and the
        # test fails on any inversion or off-lock write the fuzz
        # surfaced, even when the race didn't LOSE this run.
        assert lockcheck.enabled(), "conftest must enable KT_LOCKCHECK"
        lockcheck.reset()
        ftc = dataclasses.replace(
            next(f for f in default_ftcs() if f.name == "deployments.apps"),
            controllers=(("kubeadmiral.io/global-scheduler",),),
        )
        fleet = ClusterFleet()
        # c3 is injectable: mid-storm it FLAPS (partition toggling) so
        # the breaker/dispatch fault-tolerance path runs under the same
        # thread fire as everything else.  Wrapped BEFORE controllers
        # attach their member watches.
        self.injector = FaultInjector()
        controllers = [
            FederatedClusterController(
                fleet, api_resource_probe=["apps/v1/Deployment"],
                resync_seconds=0.2,
            ),
            FederateController(fleet.host, ftc),
            SchedulerController(fleet.host, ftc),
            SyncController(fleet, ftc),
        ]
        for name in ("c1", "c2", "c3"):
            member = fleet.add_member(name)
            member.create(NODES, make_node("n1", "64", "128Gi"))
            if name == "c3":
                fleet.members[name] = FaultyKube(
                    member, name, self.injector, timeout=0.05
                )
            fleet.host.create(
                FEDERATED_CLUSTERS,
                {"apiVersion": "core.kubeadmiral.io/v1alpha1",
                 "kind": "FederatedCluster",
                 "metadata": {"name": name}, "spec": {}},
            )
        fleet.host.create(
            PROPAGATION_POLICIES,
            {"apiVersion": "core.kubeadmiral.io/v1alpha1",
             "kind": "PropagationPolicy",
             "metadata": {"name": "pp", "namespace": "default"},
             "spec": {"schedulingMode": "Divide"}},
        )

        # Every controller on its own threads (2 workers each) — the
        # reference's --worker-count concurrency, actually concurrent.
        # Everything below runs under try/finally: a failing assertion
        # (or a raced divergence probe) must still stop every worker,
        # or 8 live reconcile threads keep storming the process through
        # the REST of the suite (observed as a tail-wide crawl).
        for ctl in controllers:
            ctl.worker.run(workers=2)
        try:
            self._storm_and_converge(fleet, ftc, controllers)
        finally:
            for ctl in controllers:
                ctl.worker.stop()

        # No exceptions escaped any reconcile worker.
        for ctl in controllers:
            panic_count = ctl.metrics.counters.get(f"{ctl.worker.name}.panic", 0)
            assert not panic_count, (
                f"{ctl.worker.name}: {panic_count} reconcile panics"
            )
        # No leaked reconcile threads: every worker thread stop() started
        # joining is actually gone (a flapping member must not strand a
        # reconcile parked on a fault).
        for ctl in controllers:
            leaked = [t.name for t in ctl.worker._threads if t.is_alive()]
            assert not leaked, leaked
        # Zero lock-order inversions, zero declared-shared fields
        # touched lock-free, across everything the storm drove.
        assert lockcheck.violations() == []

    def _storm_and_converge(self, fleet, ftc, controllers):
        fuzz_errors: list[BaseException] = []

        def fuzz(seed: int):
            rng = random.Random(seed)
            try:
                for i in range(120):
                    name = f"app-{seed}-{rng.randint(0, 15)}"
                    action = rng.random()
                    try:
                        if action < 0.5:
                            fleet.host.create(
                                ftc.source.resource,
                                make_deployment(
                                    name=name, replicas=rng.randint(1, 30)
                                ),
                            )
                        elif action < 0.8:
                            obj = fleet.host.try_get(
                                ftc.source.resource, f"default/{name}"
                            )
                            if obj is not None:
                                obj["spec"]["replicas"] = rng.randint(1, 30)
                                fleet.host.update(ftc.source.resource, obj)
                        else:
                            fleet.host.delete(
                                ftc.source.resource, f"default/{name}"
                            )
                    except (AlreadyExists, Conflict, NotFound):
                        pass  # expected races
                    if i % 20 == 19:
                        # Flap a member's health mid-storm.
                        member = fleet.members[f"c{rng.randint(1, 2)}"]
                        member.healthy = False
                        time.sleep(0.002)
                        member.healthy = True
                    if seed == 0 and i == 40:
                        # Mid-storm, c3 starts FLAPPING at the transport
                        # level: partitions toggling every 100 ms for
                        # 1.5 s, then the policy self-expires — the
                        # breaker/shed/requeue machinery must absorb it
                        # and the world must still converge.
                        self.injector.set_fault(
                            "c3",
                            FaultPolicy(partition=True, flap_period_s=0.1,
                                        flap_duty=0.4, duration_s=1.5),
                        )
                    time.sleep(0.001)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                fuzz_errors.append(e)

        threads = [
            threading.Thread(target=fuzz, args=(seed,), daemon=True)
            for seed in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not fuzz_errors, fuzz_errors

        def divergence():
            """None when every invariant holds, else a description."""
            sources = {}
            for key in fleet.host.keys(ftc.source.resource):
                obj = fleet.host.try_get(ftc.source.resource, key)
                if obj is not None:  # tolerate in-flight deletions
                    sources[key] = obj
            for key, src in sources.items():
                fed = fleet.host.try_get(ftc.federated.resource, key)
                if fed is None:
                    return f"{key}: no federated object"
                placed = C.get_placement(fed, C.SCHEDULER)
                if not placed:
                    return f"{key}: never scheduled"
                total = 0
                for cname in placed:
                    member_obj = fleet.member(cname).try_get(
                        ftc.source.resource, key
                    )
                    if member_obj is None:
                        return f"{key}: missing in {cname}"
                    total += member_obj["spec"].get("replicas", 0)
                if total != src["spec"]["replicas"]:
                    return f"{key}: {total} != {src['spec']['replicas']}"
            for member in fleet.members.values():
                for key in member.keys(ftc.source.resource):
                    if key not in sources:
                        return f"orphan {key} in {member.name}"
            return None

        # Converge under live workers (resync timers keep queues busy,
        # so poll the invariant, not queue emptiness).
        deadline = time.monotonic() + 90
        last = "never checked"
        while time.monotonic() < deadline:
            time.sleep(0.5)
            last = divergence()
            if last is None:
                break
        assert last is None, last


class TestLockcheckHarness:
    """The deterministic half of the -race analogue (ISSUE 14): the
    lockcheck harness itself must catch the bug classes the storm can
    only catch probabilistically."""

    def test_lock_order_inversion_detected(self):
        lockcheck.reset()
        a = lockcheck.CheckedLock("order-a")
        b = lockcheck.CheckedLock("order-b")
        with a:
            with b:
                pass
        # Opposite order on ONE thread is enough: the graph remembers.
        with b:
            with a:
                pass
        found = [v for v in lockcheck.violations()
                 if "lock-order-inversion" in v]
        assert found, "A->B then B->A must be reported"
        lockcheck.reset()

    def test_same_name_nesting_is_not_an_inversion(self):
        lockcheck.reset()
        a1 = lockcheck.CheckedLock("same-class")
        a2 = lockcheck.CheckedLock("same-class")
        with a1:
            with a2:
                pass
        with a2:
            with a1:
                pass
        assert lockcheck.violations() == []

    def test_shared_field_guard_detects_offlock_rebind(self):
        lockcheck.reset()

        @lockcheck.shared_field_guard
        class Box:
            _shared_fields_ = {"value": "_lock"}

            def __init__(self):
                self._lock = lockcheck.make_lock("box")
                self.value = 0  # pre-publication: exempt

            def good(self, v):
                with self._lock:
                    self.value = v

            def bad(self, v):
                self.value = v

        box = Box()
        box.good(1)
        assert lockcheck.violations() == []
        box.bad(2)
        found = [v for v in lockcheck.violations()
                 if "shared-field-write" in v and "Box.value" in v]
        assert found, "off-lock rebind of a declared field must report"
        lockcheck.reset()

    def test_assumes_held_verified_at_runtime(self):
        lockcheck.reset()

        class Engineish:
            def __init__(self):
                self._lock = lockcheck.make_lock("engineish")

            @lockcheck.assumes_held("_lock")
            def inner(self):
                return True

        e = Engineish()
        with e._lock:
            e.inner()
        assert lockcheck.violations() == []
        e.inner()
        found = [v for v in lockcheck.violations() if "assumes-held" in v]
        assert found, "entering an @assumes_held method lock-free must report"
        lockcheck.reset()

    def test_streaming_storm_is_lockcheck_clean(self):
        """Widened storm surface: concurrent producers feed the
        streaming front-end while a pump thread flushes through a real
        engine — the PR-3 shape (worker thread persisting through an
        engine tick) under the harness, driving the
        streaming/engine/aot/flightrec lock set the controller storm
        above never touches."""
        from kubeadmiral_tpu.models.types import (
            ClusterState,
            SchedulingUnit,
            parse_resources,
        )
        from kubeadmiral_tpu.scheduler.engine import SchedulerEngine
        from kubeadmiral_tpu.scheduler.streaming import StreamingScheduler

        lockcheck.reset()
        gvk = "apps/v1/Deployment"
        clusters = [
            ClusterState(
                name=f"c{j}",
                allocatable=parse_resources({"cpu": "64"}),
                available=parse_resources({"cpu": "64"}),
                api_resources=frozenset({gvk}),
            )
            for j in range(4)
        ]
        engine = SchedulerEngine(chunk_size=32)
        stream = StreamingScheduler(
            engine, clusters, slab_rows=16, slab_age_ms=5.0, grow_block=32
        )
        stop = threading.Event()
        errors: list[BaseException] = []

        def produce(seed: int):
            rng = random.Random(seed)
            try:
                for _ in range(80):
                    name = f"obj-{seed}-{rng.randint(0, 15)}"
                    if rng.random() < 0.8:
                        stream.offer(SchedulingUnit(
                            gvk=gvk, namespace="storm", name=name,
                            desired_replicas=rng.randint(1, 5),
                            resource_request=parse_resources(
                                {"cpu": "100m"}
                            ),
                        ))
                    else:
                        stream.remove(f"storm/{name}")
                    time.sleep(0.001)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        def pump_loop():
            try:
                while not stop.is_set():
                    stream.pump()
                    time.sleep(0.002)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        producers = [
            threading.Thread(target=produce, args=(s,), daemon=True)
            for s in range(3)
        ]
        pump_thread = threading.Thread(target=pump_loop, daemon=True)
        pump_thread.start()
        for t in producers:
            t.start()
        for t in producers:
            t.join(timeout=60)
        stop.set()
        pump_thread.join(timeout=60)
        assert not errors, errors
        stream.flush()
        assert lockcheck.violations() == []


class TestThreadStressHTTP:
    """The same storm over REAL sockets: watch reader threads deliver
    events asynchronously, sync's member writes flush through the
    BatchSink's pool (thread_registry echo suppression), and the host
    batch carries status/annotation/version writes — the round-4
    threading surface under fire."""

    def test_concurrent_controllers_survive_storm_over_sockets(self):
        from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm

        ftc = dataclasses.replace(
            next(f for f in default_ftcs() if f.name == "deployments.apps"),
            controllers=(("kubeadmiral.io/global-scheduler",),),
        )
        farm = KwokLiteFarm()
        fleet = farm.fleet
        try:
            for name in ("c1", "c2"):
                member = farm.add_member(name)
                member.create(NODES, make_node("n1", "64", "128Gi"))
                fleet.host.create(
                    FEDERATED_CLUSTERS,
                    {"apiVersion": "core.kubeadmiral.io/v1alpha1",
                     "kind": "FederatedCluster",
                     "metadata": {"name": name},
                     "spec": farm.cluster_spec(name)},
                )
            fleet.host.create(
                PROPAGATION_POLICIES,
                {"apiVersion": "core.kubeadmiral.io/v1alpha1",
                 "kind": "PropagationPolicy",
                 "metadata": {"name": "pp", "namespace": "default"},
                 "spec": {"schedulingMode": "Divide"}},
            )
            controllers = [
                FederatedClusterController(
                    fleet, api_resource_probe=["apps/v1/Deployment"],
                    resync_seconds=0.5,
                ),
                FederateController(fleet.host, ftc),
                SchedulerController(fleet.host, ftc),
                SyncController(fleet, ftc),
            ]
            for ctl in controllers:
                ctl.worker.run(workers=2)

            fuzz_errors: list[BaseException] = []

            def fuzz(seed: int):
                rng = random.Random(seed)
                try:
                    for _ in range(40):
                        name = f"app-{seed}-{rng.randint(0, 7)}"
                        action = rng.random()
                        try:
                            if action < 0.55:
                                fleet.host.create(
                                    ftc.source.resource,
                                    make_deployment(
                                        name=name, replicas=rng.randint(1, 20)
                                    ),
                                )
                            elif action < 0.85:
                                obj = fleet.host.try_get(
                                    ftc.source.resource, f"default/{name}"
                                )
                                if obj is not None:
                                    obj["spec"]["replicas"] = rng.randint(1, 20)
                                    fleet.host.update(ftc.source.resource, obj)
                            else:
                                fleet.host.delete(
                                    ftc.source.resource, f"default/{name}"
                                )
                        except (AlreadyExists, Conflict, NotFound):
                            pass  # expected races
                        time.sleep(0.002)
                except BaseException as e:  # noqa: BLE001 — surfaced below
                    fuzz_errors.append(e)

            threads = [
                threading.Thread(target=fuzz, args=(seed,), daemon=True)
                for seed in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads), (
                "fuzz thread wedged mid-storm (transport hang?)"
            )
            if fuzz_errors:
                hs = farm.host_server
                diag = {
                    "listener_thread_alive": hs._thread.is_alive(),
                    "listen_fd": None,
                    "healthy_probe": None,
                }
                try:
                    diag["listen_fd"] = hs._server.socket.fileno()
                except Exception as e:
                    diag["listen_fd"] = f"err {e}"
                try:
                    diag["healthy_probe"] = fleet.host.healthy
                except Exception as e:
                    diag["healthy_probe"] = f"err {e}"
                raise AssertionError(f"fuzz errors {fuzz_errors[:2]} diag={diag}")

            def divergence():
                sources = {}
                for key in fleet.host.keys(ftc.source.resource):
                    obj = fleet.host.try_get(ftc.source.resource, key)
                    if obj is not None:  # tolerate in-flight deletions
                        sources[key] = obj
                for key, src in sources.items():
                    fed = fleet.host.try_get(ftc.federated.resource, key)
                    if fed is None:
                        return f"{key}: no federated object"
                    placed = C.get_placement(fed, C.SCHEDULER)
                    if not placed:
                        return f"{key}: never scheduled"
                    total = 0
                    for cname in placed:
                        member_obj = fleet.member(cname).try_get(
                            ftc.source.resource, key
                        )
                        if member_obj is None:
                            return f"{key}: missing in {cname}"
                        total += member_obj["spec"].get("replicas", 0)
                    if total != src["spec"]["replicas"]:
                        return f"{key}: {total} != {src['spec']['replicas']}"
                return None

            deadline = time.monotonic() + 90
            last = "never checked"
            while time.monotonic() < deadline:
                time.sleep(0.5)
                last = divergence()
                if last is None:
                    break
            assert last is None, last
            for ctl in controllers:
                panic_count = ctl.metrics.counters.get(
                    f"{ctl.worker.name}.panic", 0
                )
                assert not panic_count, (
                    f"{ctl.worker.name}: {panic_count} reconcile panics"
                )
        finally:
            # Workers stop BEFORE the servers close, whatever failed —
            # live reconciles against a closed farm flood the log and
            # hide the real failure.
            for ctl in locals().get("controllers", ()):
                ctl.worker.stop()
            farm.close()
