"""End-to-end observability: after a membersim-driven reconcile round,
the health server serves a populated Prometheus exposition at /metrics
and a nested Chrome trace at /debug/trace (ISSUE 1 acceptance)."""

import json
import re
import urllib.request

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.clusterctl import (
    FEDERATED_CLUSTERS,
    FederatedClusterController,
    NODES,
)
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.monitor import MonitorController
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
from kubeadmiral_tpu.runtime import trace
from kubeadmiral_tpu.runtime.healthcheck import HealthCheckRegistry, HealthServer
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.testing.fakekube import ClusterFleet
from kubeadmiral_tpu.testing.membersim import MemberDeploymentSimulator

from test_e2e_slice import make_deployment, make_node

import dataclasses


def fetch(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, dict(r.headers), r.read()


# A valid exposition line: comment, or name{labels} value.
_PROM_LINE = re.compile(
    r"^(# (TYPE|HELP) .*"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.e+-]+(inf|nan)?)$"
)


class TestObservabilityEndToEnd:
    def setup_method(self):
        trace.get_default().clear()
        # Per-event spans (informer.event, worker.reconcile) are sampled
        # 1-in-KT_TRACE_SAMPLE_N in production; this test asserts the
        # full reconcile-path span tree, so trace everything.
        import os

        self._prev_sample = os.environ.get("KT_TRACE_SAMPLE_N")
        os.environ["KT_TRACE_SAMPLE_N"] = "1"
        trace.reset_sampling()
        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        self.ftc = dataclasses.replace(
            ftc, controllers=(("kubeadmiral.io/global-scheduler",),)
        )
        self.fleet = ClusterFleet()
        self.metrics = Metrics()
        gvk = "apps/v1/Deployment"
        self.clusterctl = FederatedClusterController(
            self.fleet, api_resource_probe=[gvk], metrics=self.metrics
        )
        self.federate = FederateController(
            self.fleet.host, self.ftc, metrics=self.metrics
        )
        self.scheduler = SchedulerController(
            self.fleet.host, self.ftc, metrics=self.metrics
        )
        # The scheduler's engine must report into the shared registry.
        self.scheduler.engine.metrics = self.metrics
        self.sync = SyncController(self.fleet, self.ftc, metrics=self.metrics)
        self.monitor = MonitorController(
            self.fleet.host, self.ftc, metrics=self.metrics, interval=0.0
        )
        self.sim = MemberDeploymentSimulator(self.fleet)
        for name in ("c1", "c2", "c3"):
            member = self.fleet.add_member(name)
            member.create(NODES, make_node("n1", "64", "128Gi"))
            self.fleet.host.create(
                FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": {},
                },
            )
        self.fleet.host.create(
            PROPAGATION_POLICIES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "PropagationPolicy",
                "metadata": {"name": "pp", "namespace": "default"},
                "spec": {"schedulingMode": "Divide"},
            },
        )

    def teardown_method(self):
        import os

        if self._prev_sample is None:
            os.environ.pop("KT_TRACE_SAMPLE_N", None)
        else:
            os.environ["KT_TRACE_SAMPLE_N"] = self._prev_sample
        trace.reset_sampling()

    def reconcile_round(self, max_rounds=60):
        controllers = (
            self.clusterctl, self.federate, self.scheduler, self.sync,
            self.monitor,
        )
        for _ in range(max_rounds):
            progressed = False
            for c in controllers:
                progressed |= c.worker.step()
            progressed |= self.sim.step()
            if not progressed:
                return

    def test_metrics_and_trace_serve_on_health_server(self):
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        self.reconcile_round()
        # The round actually propagated (the telemetry observed real
        # work, not an idle loop).
        fed = self.fleet.host.get(self.ftc.federated.resource, "default/web")
        assert C.get_placement(fed, C.SCHEDULER) == {"c1", "c2", "c3"}

        registry = HealthCheckRegistry()
        registry.add_readiness("controller-manager", lambda: True)
        server = HealthServer(registry, metrics=self.metrics)
        port = server.start()
        try:
            status, headers, body = fetch(port, "/metrics")
            assert status == 200
            assert headers["Content-Type"].startswith("text/plain")
            text = body.decode()
            for line in text.splitlines():
                assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
            # Tick stage-latency histograms.
            assert re.search(
                r'engine_tick_stage_seconds_bucket\{.*stage="device".*\} \d+',
                text,
            ), text
            assert 'engine_tick_stage_seconds_sum{stage="featurize"}' in text
            # Compile-cache hit/miss counters labeled by shape bucket.
            assert re.search(
                r'engine_compile_cache_total\{result="miss",shape="[a-z]+:\d+x\d+"\} \d+',
                text,
            ), text
            # Queue depth gauge + per-controller reconcile counters.
            assert re.search(
                r'worker_queue_depth\{controller="scheduler-deployments\.apps"\} \d+',
                text,
            ), text
            assert re.search(
                r'worker_reconciles_total\{controller="sync-deployments\.apps"\} \d+',
                text,
            ), text
            # Per-item latency histograms, labeled by controller.
            assert re.search(
                r'worker_tick_seconds_count\{controller="scheduler-deployments\.apps"\} \d+',
                text,
            ), text

            status, headers, body = fetch(port, "/debug/trace")
            assert status == 200
            doc = json.loads(body)
            all_x = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
            # Host spans carry span ids; the ledger's device-lane slices
            # (merged below) are id-less.
            events = [e for e in all_x if "span_id" in e.get("args", {})]
            by_id = {e["args"]["span_id"]: e for e in events}
            names = {e["name"] for e in events}
            # The reconcile path is covered informer -> device -> member
            # dispatch.
            assert {"informer.event", "worker.tick", "engine.schedule",
                    "engine.device_dispatch",
                    "dispatch.member_flush"} <= names, names

            def ancestors(e):
                out = []
                while e is not None and "parent_id" in e["args"]:
                    e = by_id.get(e["args"]["parent_id"])
                    if e is not None:
                        out.append(e["name"])
                return out

            # Parent/child nesting intact: the device dispatch nests
            # under the engine tick, which nests under the scheduler's
            # worker tick.
            dispatch = next(
                e for e in events if e["name"] == "engine.device_dispatch"
            )
            chain = ancestors(dispatch)
            assert "engine.schedule" in chain, chain
            assert "worker.tick" in chain, chain

            # Device lanes merged from the dispatch ledger (ISSUE 13):
            # the engine tick's program dispatches render on their own
            # `device <lane>` threads in the SAME trace document, so one
            # load shows host + device timelines correlated by tick id.
            lane_meta = [
                e
                for e in doc["traceEvents"]
                if e.get("ph") == "M"
                and e["name"] == "thread_name"
                and str(e.get("args", {}).get("name", "")).startswith(
                    "device "
                )
            ]
            assert lane_meta, "no device lanes merged into /debug/trace"
            lane_tids = {e["tid"] for e in lane_meta}
            device_slices = [
                e
                for e in doc["traceEvents"]
                if e.get("ph") == "X" and e.get("tid") in lane_tids
            ]
            assert device_slices and any(
                e["args"].get("tick") for e in device_slices
            )

            # Host-only escape hatch: ?device=0 drops the merged lanes.
            _, _, body = fetch(port, "/debug/trace?device=0")
            host_only = json.loads(body)
            assert not [
                e
                for e in host_only["traceEvents"]
                if e.get("ph") == "M"
                and str(e.get("args", {}).get("name", "")).startswith(
                    "device "
                )
            ]
        finally:
            server.stop()

    def test_monitor_reads_real_error_rates(self):
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        self.reconcile_round()
        # The worker-labeled series exist and the monitor re-published
        # them for its FTC.
        assert "monitor.deployments.apps.worker_exceptions" in self.metrics.stores
        assert self.metrics.stores["monitor.deployments.apps.worker_exceptions"] == 0
        # Pipeline-depth gauges parsed from the pending-controllers
        # annotation: after convergence the scheduler has no backlog.
        depth = self.metrics.stores.get(
            "pending_controllers_depth{controller=kubeadmiral.io/global-scheduler,"
            "ftc=deployments.apps}"
        )
        assert depth in (None, 0)
