"""Federate controller: source object -> federated object lifecycle.

Mirrors the behaviors of reference pkg/controllers/federate:
creation with template pruning + annotation/label classification,
idempotent updates, merge-patch bookkeeping, deletion propagation
gated by the source finalizer, and the no-federated-resource opt-out.
"""

import json

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.federate import (
    FEDERATE_FINALIZER,
    FederateController,
    NO_FEDERATED_RESOURCE,
    OBSERVED_ANNOTATION_KEYS,
    OBSERVED_LABEL_KEYS,
    TEMPLATE_GENERATOR_MERGE_PATCH,
    new_federated_object,
    observed_keys,
    update_federated_object,
)
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.testing.fakekube import FakeKube
from kubeadmiral_tpu.utils.jsonpatch import apply_merge_patch


def deployment_ftc():
    return next(f for f in default_ftcs() if f.name == "deployments.apps")


def make_deployment(name="web", namespace="default", replicas=3, **meta_kw):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace, **meta_kw},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {"containers": [{"name": "c", "image": "nginx"}]},
            },
        },
    }


class TestNewFederatedObject:
    def test_template_is_pruned_source(self):
        ftc = deployment_ftc()
        src = make_deployment()
        src["metadata"].update(
            {
                "uid": "u-123",
                "resourceVersion": "42",
                "generation": 7,
                "creationTimestamp": "2026-01-01T00:00:00Z",
                "managedFields": [{"manager": "kubectl"}],
                "finalizers": ["some.io/fin"],
            }
        )
        src["status"] = {"replicas": 3}
        fed = new_federated_object(ftc, src)
        tmpl = fed["spec"]["template"]
        assert tmpl["metadata"] == {"name": "web", "namespace": "default"}
        assert "status" not in tmpl
        assert fed["kind"] == "FederatedDeployment"
        assert fed["apiVersion"] == "types.kubeadmiral.io/v1alpha1"
        assert fed["metadata"]["name"] == "web"
        assert fed["metadata"]["namespace"] == "default"

    def test_annotation_classification(self):
        ftc = deployment_ftc()
        src = make_deployment(
            annotations={
                C.PREFIX + "scheduling-mode": "Divide",  # federated
                "team": "infra",  # template
                C.SOURCE_FEEDBACK_SYNCING: "x",  # ignored
            }
        )
        fed = new_federated_object(ftc, src)
        fa = fed["metadata"]["annotations"]
        assert fa[C.PREFIX + "scheduling-mode"] == "Divide"
        assert "team" not in fa
        assert C.SOURCE_FEEDBACK_SYNCING not in fa
        tmpl_anno = fed["spec"]["template"]["metadata"]["annotations"]
        assert tmpl_anno == {"team": "infra"}
        # observed-keys bookkeeping: fed keys | other keys.  Ignored
        # (feedback) keys are excluded entirely — they are written by
        # this control plane and must not churn the bookkeeping.
        assert fa[OBSERVED_ANNOTATION_KEYS] == (
            C.PREFIX + "scheduling-mode" + "|team"
        )

    def test_label_classification(self):
        ftc = deployment_ftc()
        src = make_deployment(
            labels={
                "kubeadmiral.io/propagation-policy-name": "pp-1",
                "app": "web",
            }
        )
        fed = new_federated_object(ftc, src)
        assert fed["metadata"]["labels"] == {
            "kubeadmiral.io/propagation-policy-name": "pp-1"
        }
        assert fed["spec"]["template"]["metadata"]["labels"] == {"app": "web"}
        assert fed["metadata"]["annotations"][OBSERVED_LABEL_KEYS] == (
            "kubeadmiral.io/propagation-policy-name|app"
        )

    def test_merge_patch_reconstructs_template(self):
        ftc = deployment_ftc()
        src = make_deployment()
        src["metadata"]["uid"] = "u-1"
        src["status"] = {"replicas": 1}
        fed = new_federated_object(ftc, src)
        patch = json.loads(
            fed["metadata"]["annotations"][TEMPLATE_GENERATOR_MERGE_PATCH]
        )
        assert apply_merge_patch(src, patch) == fed["spec"]["template"]

    def test_pending_controllers_initialized(self):
        ftc = deployment_ftc()
        fed = new_federated_object(ftc, make_deployment())
        assert pending.get_pending(fed) == ftc.controller_groups

    def test_deployment_fields(self):
        ftc = deployment_ftc()
        src = make_deployment(annotations={C.RETAIN_REPLICAS: "true"})
        fed = new_federated_object(ftc, src)
        assert fed["spec"]["retainReplicas"] is True
        assert fed["spec"]["revisionHistoryLimit"] == 1


class TestUpdateFederatedObject:
    def test_noop_when_unchanged(self):
        ftc = deployment_ftc()
        src = make_deployment()
        fed = new_federated_object(ftc, src)
        assert update_federated_object(fed, ftc, src) is False

    def test_template_change_restarts_pipeline(self):
        ftc = deployment_ftc()
        src = make_deployment()
        fed = new_federated_object(ftc, src)
        # downstream consumed the pipeline
        pending.update_pending(fed, C.SCHEDULER, True, ftc.controller_groups)
        src["spec"]["replicas"] = 9
        assert update_federated_object(fed, ftc, src) is True
        assert fed["spec"]["template"]["spec"]["replicas"] == 9
        assert pending.get_pending(fed) == ftc.controller_groups

    def test_preserves_foreign_annotations(self):
        ftc = deployment_ftc()
        src = make_deployment()
        fed = new_federated_object(ftc, src)
        fed["metadata"]["annotations"]["other.io/note"] = "keep-me"
        src["spec"]["replicas"] = 5
        update_federated_object(fed, ftc, src)
        assert fed["metadata"]["annotations"]["other.io/note"] == "keep-me"

    def test_removes_stale_federated_annotations(self):
        ftc = deployment_ftc()
        src = make_deployment(annotations={C.PREFIX + "max-clusters": "2"})
        fed = new_federated_object(ftc, src)
        del src["metadata"]["annotations"][C.PREFIX + "max-clusters"]
        assert update_federated_object(fed, ftc, src) is True
        assert C.PREFIX + "max-clusters" not in fed["metadata"]["annotations"]


class TestObservedKeys:
    def test_empty(self):
        assert observed_keys({}, {}) == ""

    def test_sorted_partition(self):
        src = {"b": "1", "a": "2", "z": "3"}
        fed = {"z": "3"}
        assert observed_keys(src, fed) == "z|a,b"


class TestFederateController:
    def setup_method(self):
        self.kube = FakeKube()
        self.ftc = deployment_ftc()
        self.ctl = FederateController(self.kube, self.ftc)
        self.src_res = self.ftc.source.resource
        self.fed_res = self.ftc.federated.resource

    def test_creates_federated_object(self):
        self.kube.create(self.src_res, make_deployment())
        self.ctl.run_until_idle()
        fed = self.kube.get(self.fed_res, "default/web")
        assert fed["kind"] == "FederatedDeployment"
        src = self.kube.get(self.src_res, "default/web")
        assert FEDERATE_FINALIZER in src["metadata"]["finalizers"]

    def test_source_update_propagates(self):
        self.kube.create(self.src_res, make_deployment(replicas=1))
        self.ctl.run_until_idle()
        src = self.kube.get(self.src_res, "default/web")
        src["spec"]["replicas"] = 8
        self.kube.update(self.src_res, src)
        self.ctl.run_until_idle()
        fed = self.kube.get(self.fed_res, "default/web")
        assert fed["spec"]["template"]["spec"]["replicas"] == 8

    def test_no_federated_resource_annotation_skips(self):
        self.kube.create(
            self.src_res,
            make_deployment(annotations={NO_FEDERATED_RESOURCE: "1"}),
        )
        self.ctl.run_until_idle()
        assert self.kube.try_get(self.fed_res, "default/web") is None

    def test_source_deletion_cascades(self):
        self.kube.create(self.src_res, make_deployment())
        self.ctl.run_until_idle()
        # deletion is finalizer-gated on the source
        self.kube.delete(self.src_res, "default/web")
        self.ctl.run_until_idle()
        # federated object deleted (no finalizers on it in this test)
        assert self.kube.try_get(self.fed_res, "default/web") is None
        # source released once the federated object is gone
        assert self.kube.try_get(self.src_res, "default/web") is None

    def test_feedback_annotations_flow_back(self):
        self.kube.create(self.src_res, make_deployment())
        self.ctl.run_until_idle()
        fed = self.kube.get(self.fed_res, "default/web")
        fed["metadata"]["annotations"][C.SOURCE_FEEDBACK_SYNCING] = '{"ok":true}'
        self.kube.update(self.fed_res, fed)
        self.ctl.run_until_idle()
        src = self.kube.get(self.src_res, "default/web")
        assert src["metadata"]["annotations"][C.SOURCE_FEEDBACK_SYNCING] == (
            '{"ok":true}'
        )
