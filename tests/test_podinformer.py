"""Pod informer: pruning, lister semaphore, incremental cache, and the
auto-migration integration (reference: federatedclient/podinformer.go)."""

import json

from kubeadmiral_tpu.runtime.podinformer import PODS, PodInformer, prune_pod
from kubeadmiral_tpu.testing.fakekube import ClusterFleet


def fat_pod(name, ns="default", labels=None, node="n1", unschedulable=False):
    """A pod with the bulk a real pod carries (env/volumes/probes)."""
    conditions = []
    if unschedulable:
        conditions.append(
            {"type": "PodScheduled", "status": "False",
             "reason": "Unschedulable", "lastTransitionTime": 100}
        )
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": ns,
            "labels": labels or {"app": "web"},
            "annotations": {"huge": "x" * 2000},
        },
        "spec": {
            "nodeName": node,
            "containers": [
                {
                    "name": "c",
                    "image": "nginx",
                    "env": [{"name": f"E{i}", "value": "v" * 100} for i in range(20)],
                    "volumeMounts": [{"name": "data", "mountPath": "/data"}],
                    "resources": {"requests": {"cpu": "100m", "memory": "128Mi"}},
                }
            ],
            "volumes": [{"name": "data", "emptyDir": {}}],
        },
        "status": {"phase": "Running", "conditions": conditions},
    }


class TestPrunePod:
    def test_strips_bulk_keeps_scheduling_fields(self):
        pod = fat_pod("p1", unschedulable=True)
        pruned = prune_pod(pod)
        assert "annotations" not in pruned["metadata"]
        assert "env" not in json.dumps(pruned)
        assert "volumes" not in pruned["spec"]
        assert pruned["spec"]["nodeName"] == "n1"
        assert pruned["spec"]["containers"][0]["resources"]["requests"] == {
            "cpu": "100m", "memory": "128Mi",
        }
        assert pruned["status"]["conditions"][0]["reason"] == "Unschedulable"
        # The pruned pod is dramatically smaller.
        assert len(json.dumps(pruned)) < len(json.dumps(pod)) / 5


class TestPodInformer:
    def test_cache_fills_and_tracks_events(self):
        fleet = ClusterFleet()
        m1 = fleet.add_member("c1")
        m1.create(PODS, fat_pod("pre"))
        informer = PodInformer(fleet)
        informer.attach()
        assert informer.cache_size("c1") == 1

        m1.create(PODS, fat_pod("live", labels={"app": "db"}))
        assert informer.cache_size("c1") == 2
        assert len(informer.pods_for("c1", "default", {"app": "db"})) == 1
        m1.delete(PODS, "default/live")
        assert informer.cache_size("c1") == 1

    def test_attach_is_idempotent_and_picks_up_new_members(self):
        fleet = ClusterFleet()
        fleet.add_member("c1").create(PODS, fat_pod("a"))
        informer = PodInformer(fleet)
        informer.attach()
        informer.attach()  # no duplicate handlers
        fleet.member("c1").create(PODS, fat_pod("b"))
        assert informer.cache_size("c1") == 2

        fleet.add_member("c2").create(PODS, fat_pod("c"))
        informer.attach()
        assert informer.cache_size("c2") == 1

    def test_pruning_can_be_disabled(self):
        fleet = ClusterFleet()
        fleet.add_member("c1").create(PODS, fat_pod("a"))
        informer = PodInformer(fleet, enable_pruning=False)
        informer.attach()
        (pod,) = informer.pods_for("c1")
        assert pod["metadata"]["annotations"]["huge"]


class TestAutoMigrationWithInformer:
    def test_estimated_capacity_from_pruned_cache(self):
        """Auto-migration sees the same unschedulable counts through the
        pruned informer as through raw pod scans."""
        import dataclasses

        from kubeadmiral_tpu.federation.automigration import (
            AutoMigrationController,
        )
        from kubeadmiral_tpu.federation import common as C
        from kubeadmiral_tpu.models.ftc import default_ftcs

        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        fleet = ClusterFleet()
        member = fleet.add_member("c1")
        # Workload with 3 pods, 2 unschedulable past any threshold.
        member.create(
            ftc.source.resource,
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": "web", "namespace": "default"},
                "spec": {
                    "replicas": 3,
                    "selector": {"matchLabels": {"app": "web"}},
                },
                "status": {"replicas": 3, "readyReplicas": 1},
            },
        )
        for i, stuck in enumerate((True, True, False)):
            member.create(PODS, fat_pod(f"p{i}", unschedulable=stuck))

        fed = {
            "apiVersion": "types.kubeadmiral.io/v1alpha1",
            "kind": "FederatedDeployment",
            "metadata": {
                "name": "web",
                "namespace": "default",
                "annotations": {
                    C.PREFIX + "pod-unschedulable-threshold": "0.001",
                },
            },
            "spec": {
                "template": {"metadata": {"name": "web"}},
                "placements": [
                    {
                        "controller": C.SCHEDULER,
                        "placement": [{"cluster": "c1"}],
                    }
                ],
            },
        }
        fleet.host.create(ftc.federated.resource, fed)

        informer = PodInformer(fleet)
        ctl = AutoMigrationController(fleet, ftc, pod_informer=informer)
        ctl.run_until_idle()
        got = fleet.host.get(ftc.federated.resource, "default/web")
        info = json.loads(
            got["metadata"]["annotations"][C.PREFIX + "auto-migration-info"]
        )
        assert info["estimatedCapacity"] == {"c1": 1}


class _ReplayObservingFleet:
    """Duck-typed fleet whose single member lets the test observe the
    informer's read surface MID-replay (between replay events)."""

    def __init__(self, pods, observe, fail_watches=0):
        self.pods = pods
        self.observe = observe
        self.fail_watches = fail_watches
        self.members = {"c1": object()}
        self._member = self._Member(self)

    def member(self, name):
        return self._member

    class _Member:
        def __init__(self, fleet):
            self.fleet = fleet

        def watch(self, resource, handler, replay=True):
            if self.fleet.fail_watches > 0:
                self.fleet.fail_watches -= 1
                raise ConnectionError("member down")
            for pod in self.fleet.pods:
                handler("ADDED", pod)
                self.fleet.observe()  # mid-replay: cache must be staged

        def unwatch(self, resource, handler):
            pass


class TestColdReplayStaging:
    def test_partial_replay_is_invisible(self):
        """pods_for returns None for the WHOLE cold-replay window: a
        half-replayed snapshot must never feed auto-migration counts
        (ADVICE r2: podinformer partial-cache hazard)."""
        seen = []
        fleet = _ReplayObservingFleet(
            [fat_pod(f"p{i}") for i in range(5)],
            observe=lambda: seen.append(informer.pods_for("c1")),
        )
        informer = PodInformer(fleet)
        informer.attach()
        assert seen == [None] * 5  # staged during replay
        assert len(informer.pods_for("c1")) == 5  # published after

    def test_watch_failure_contained_and_retried(self):
        """A down member must not abort attach(); the next attach
        retries and succeeds (ADVICE r2: failure containment)."""
        fleet = _ReplayObservingFleet(
            [fat_pod("p0")], observe=lambda: None, fail_watches=1
        )
        informer = PodInformer(fleet)
        informer.attach()  # watch raises inside; must not propagate
        assert informer.pods_for("c1") is None
        informer.attach()  # retried
        assert len(informer.pods_for("c1")) == 1
