import pytest

from kubeadmiral_tpu.utils.quantity import Quantity, cpu_to_millis, parse_quantity, to_int_value


def test_cpu_millis():
    assert cpu_to_millis("100m") == 100
    assert cpu_to_millis("1") == 1000
    assert cpu_to_millis("2.5") == 2500
    assert cpu_to_millis(2) == 2000
    assert cpu_to_millis("1500m") == 1500


def test_value_rounds_away_from_zero():
    # Matches Go Quantity.Value(): "2500m" -> 3
    assert parse_quantity("2500m").value() == 3
    assert parse_quantity("-2500m").value() == -3
    assert parse_quantity("2500m").milli_value() == 2500


def test_binary_and_decimal_suffixes():
    assert to_int_value("1Ki") == 1024
    assert to_int_value("2Gi") == 2 * 1024**3
    assert to_int_value("1G") == 10**9
    assert to_int_value("128Mi") == 128 * 1024**2


def test_scientific_notation():
    assert to_int_value("1e3") == 1000
    assert to_int_value("1.5e2") == 150
    assert cpu_to_millis("1e-3") == 1


def test_arithmetic_and_compare():
    assert Quantity("1") + Quantity("500m") == Quantity("1500m")
    assert Quantity("2Gi") - Quantity("1Gi") == Quantity("1Gi")
    assert Quantity("100m") < Quantity("1")


def test_invalid():
    with pytest.raises(ValueError):
        parse_quantity("abc")
    with pytest.raises(ValueError):
        parse_quantity("1X")


def test_nano_micro_suffixes():
    assert cpu_to_millis("100n") == 1  # rounds up at milli precision
    assert cpu_to_millis("500u") == 1
    assert parse_quantity("1500000n").milli_value() == 2
    assert parse_quantity("2u").raw == parse_quantity("2000n").raw
