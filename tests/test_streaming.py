"""Streaming-scheduler suite (ISSUE 7).

The always-on pipeline's contract is exactness under interleave: row
churn, object arrivals/deletes and cluster-capacity drift streaming
through coalesced slab flushes must land bit-identical to a
stop-the-world engine deciding the same worlds — including when the
drift gate bails (mass drift) mid-stream, and on the sort-free
drift-resolve survivor path.
"""

import dataclasses

import numpy as np

from kubeadmiral_tpu.models.types import (
    ClusterState,
    MODE_DIVIDE,
    SchedulingUnit,
    parse_resources,
)
from kubeadmiral_tpu.runtime.flightrec import FlightRecorder
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine
from kubeadmiral_tpu.scheduler.streaming import (
    StreamingScheduler,
    is_placeholder,
    make_placeholder,
)

from test_engine_cache import make_world, results_equal
from test_engine_vs_sequential import random_cluster, random_unit


def fresh_results(units, clusters, **engine_kw):
    return SchedulerEngine(**engine_kw).schedule(units, clusters)


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


class TestSlabMechanics:
    def test_rows_watermark_triggers_pump(self):
        units, clusters = make_world(b=32, c=8)
        engine = SchedulerEngine(chunk_size=32)
        stream = StreamingScheduler(
            engine, clusters, units, slab_rows=4, slab_age_ms=1e9
        )
        stream.flush()
        for i in range(3):
            stream.offer(
                dataclasses.replace(units[i], desired_replicas=40 + i)
            )
            assert stream.pump() is None  # below both watermarks
        stream.offer(dataclasses.replace(units[3], desired_replicas=50))
        got = stream.pump()
        assert got is not None
        assert stream.flush_stats["rows"] == 1
        results_equal(got, fresh_results(stream.units, clusters,
                                         chunk_size=32))

    def test_age_watermark_triggers_pump(self):
        units, clusters = make_world(b=16, c=8)
        clock = FakeClock()
        engine = SchedulerEngine(chunk_size=32)
        stream = StreamingScheduler(
            engine, clusters, units, slab_rows=1024, slab_age_ms=50,
            clock=clock,
        )
        stream.flush()
        stream.offer(dataclasses.replace(units[0], desired_replicas=33))
        assert stream.pump() is None
        clock.t += 0.06  # 60ms > the 50ms age watermark
        assert stream.pump() is not None
        assert stream.flush_stats["age"] == 1
        assert stream.oldest_age() == 0.0
        # Latency accounting saw the wait.
        assert stream.latencies and stream.latencies[-1] >= 0.059

    def test_arrivals_fill_placeholders_then_grow(self):
        units, clusters = make_world(b=8, c=8)
        engine = SchedulerEngine(chunk_size=32)
        stream = StreamingScheduler(
            engine, clusters, units, slab_rows=64, slab_age_ms=1e9,
            grow_block=4,
        )
        stream.flush()
        world0 = len(stream.units)
        arrivals = [
            SchedulingUnit(
                gvk="apps/v1/Deployment",
                namespace="arr",
                name=f"new-{i}",
                scheduling_mode=MODE_DIVIDE,
                desired_replicas=3,
                resource_request=parse_resources({"cpu": "100m"}),
            )
            for i in range(6)
        ]
        for a in arrivals:
            stream.offer(a)
        got = stream.flush()
        assert len(stream.units) == world0 + 8  # two 4-row blocks
        assert sum(1 for u in stream.units if is_placeholder(u)) == 2
        results_equal(got, fresh_results(stream.units, clusters,
                                         chunk_size=32))
        # Every arrival landed somewhere real.
        for a in arrivals:
            res = stream.result_of(a.key)
            assert res is not None and res.clusters

    def test_delete_reverts_to_placeholder(self):
        units, clusters = make_world(b=8, c=8)
        engine = SchedulerEngine(chunk_size=32)
        stream = StreamingScheduler(engine, clusters, units,
                                    slab_rows=64, slab_age_ms=1e9)
        stream.flush()
        key = units[2].key
        stream.remove(key)
        got = stream.flush()
        assert stream.result_of(key) is None
        assert is_placeholder(stream.units[2])
        assert not got[2].clusters  # the placeholder row schedules nowhere
        results_equal(got, fresh_results(stream.units, clusters,
                                         chunk_size=32))

    def test_placeholder_rows_schedule_nowhere(self):
        units, clusters = make_world(b=6, c=8)
        padded = units + [make_placeholder(i) for i in range(6, 10)]
        got = fresh_results(padded, clusters, chunk_size=32)
        for r in got[6:]:
            assert not r.clusters

    def test_capacity_event_rides_drift_gate(self):
        units, clusters = make_world(b=64, c=12)
        engine = SchedulerEngine(chunk_size=32)
        stream = StreamingScheduler(engine, clusters, units,
                                    slab_rows=64, slab_age_ms=1e9)
        stream.flush()
        stream.flush()  # device prev planes armed
        drifted = dataclasses.replace(
            clusters[0],
            available={k: max(0, v // 2)
                       for k, v in clusters[0].available.items()},
        )
        stream.update_cluster(drifted)
        got = stream.flush()
        assert engine.drift_stats["gated"] >= 1, engine.drift_stats
        assert stream.clusters[0].available == drifted.available
        results_equal(got, fresh_results(stream.units, stream.clusters,
                                         chunk_size=32))


class TestStreamingInterleaveDifferential:
    def test_randomized_event_log_bit_identical_to_stop_the_world(self):
        """The satellite differential: concurrent capacity drift + row
        churn + arrivals/deletes through the streaming loop, flushed on
        watermarks, versus a stop-the-world replay (fresh engine per
        flush point) — placements, reason summaries and flight-recorder
        records bit-identical.  Step 6 forces the mass-drift gate bail
        mid-stream."""
        rng = np.random.default_rng(11)
        clusters = [random_cluster(rng, j) for j in range(14)]
        names = [c.name for c in clusters]
        units = [random_unit(rng, i, names) for i in range(64)]

        rec = FlightRecorder(max_ticks=2, max_bytes=1 << 26)
        engine = SchedulerEngine(chunk_size=32, min_bucket=16,
                                 min_cluster_bucket=8, flight_recorder=rec)
        stream = StreamingScheduler(engine, clusters, units,
                                    slab_rows=6, slab_age_ms=1e9)
        stream.flush()
        stream.flush()

        arrivals = 0
        for step in range(10):
            kind = step % 5
            if kind == 0:  # updates
                for r in rng.integers(0, 64, 4):
                    u = stream.units[int(r)]
                    if is_placeholder(u):
                        continue
                    stream.offer(dataclasses.replace(
                        u, desired_replicas=int(rng.integers(1, 60))))
            elif kind == 1:  # arrivals
                for _ in range(3):
                    stream.offer(random_unit(
                        rng, 1000 + arrivals, names))
                    arrivals += 1
            elif kind == 2:  # deletes + updates
                live = [u for u in stream.units if not is_placeholder(u)]
                for r in rng.integers(0, len(live), 2):
                    stream.remove(live[int(r)].key)
            elif kind == 3:  # single-column capacity drift + churn
                j = int(rng.integers(0, len(clusters)))
                base = stream.clusters[j]
                stream.update_cluster(dataclasses.replace(
                    base,
                    available={k: max(0, v // 2)
                               for k, v in base.available.items()},
                ))
                u = stream.units[int(rng.integers(0, 64))]
                if not is_placeholder(u):
                    stream.offer(dataclasses.replace(
                        u, desired_replicas=int(rng.integers(1, 60))))
            else:  # mass drift: every column moves -> gate bails
                fleet = [
                    dataclasses.replace(
                        c,
                        available={k: max(0, v - v // 7)
                                   for k, v in c.available.items()},
                    )
                    for c in stream.clusters
                ]
                stream.offer_capacity(fleet)

            got = stream.flush()
            changed = engine.last_changed
            oracle_rec = FlightRecorder(max_ticks=2, max_bytes=1 << 26)
            oracle = SchedulerEngine(
                chunk_size=32, min_bucket=16, min_cluster_bucket=8,
                flight_recorder=oracle_rec,
            )
            want = oracle.schedule(stream.units, stream.clusters)
            results_equal(got, want)
            # Flight-recorder parity for the rows this flush actually
            # re-decided (skipped rows keep their prior records, by
            # design): placements, per-reason rejection counts,
            # feasible counts, and the recorded top-k — bit-identical.
            # Exception (ISSUE 10): rows settled by the selection-known
            # replan carry top-k from the LAST SOLVED score plane (the
            # kernel skips the score recompute by design — staleness is
            # provably decision-free for those kinf rows), so only
            # their top-k comparison is skipped.
            for row in (changed or []):
                u = stream.units[row]
                if is_placeholder(u):
                    continue
                a = rec.lookup(u.key)
                b = oracle_rec.lookup(u.key)
                assert a is not None and b is not None, u.key
                assert a.placements == b.placements, u.key
                assert np.array_equal(a.reason_counts, b.reason_counts), (
                    u.key, a.reason_counts, b.reason_counts,
                )
                assert a.feasible_n == b.feasible_n, u.key
                if a.program.endswith(":replan"):
                    continue
                assert np.array_equal(a.topk_idx, b.topk_idx), u.key
                assert np.array_equal(a.topk_scores, b.topk_scores), u.key
        # The log must actually have exercised the paths under test.
        assert engine.drift_stats["gated"] >= 1, engine.drift_stats
        assert engine.fetch_stats["full"] >= 1  # mass-drift bail ran
        assert stream.flushes >= 10


class TestDriftResolvePath:
    def _world(self, b=96, c=24):
        """Finite-K rows over ample capacity: score drift moves top-K
        membership, fit never flips — the sort-free resolve's home
        turf."""
        gvk = "apps/v1/Deployment"
        clusters = [
            ClusterState(
                name=f"m-{j:03d}",
                labels={},
                taints=(),
                allocatable=parse_resources(
                    {"cpu": "256", "memory": "1024Gi"}
                ),
                available=parse_resources(
                    {"cpu": f"{40 + 7 * j}", "memory": f"{200 + 13 * j}Gi"}
                ),
                api_resources=frozenset({gvk}),
            )
            for j in range(c)
        ]
        units = [
            SchedulingUnit(
                gvk=gvk,
                namespace="ns",
                name=f"w-{i:04d}",
                scheduling_mode=MODE_DIVIDE if i % 4 else "Duplicate",
                desired_replicas=(i % 30) + 2,
                resource_request=parse_resources({"cpu": "50m"}),
                max_clusters=3 + i % 4,
                weights={f"m-{j:03d}": 10 + (i + j) % 7 for j in range(c)}
                if i % 2
                else {},
            )
            for i in range(b)
        ]
        return units, clusters

    def test_resolve_settles_score_drift_exactly(self):
        units, clusters = self._world()
        engine = SchedulerEngine(chunk_size=128, min_bucket=32,
                                 min_cluster_bucket=8, narrow_m=16)
        # This class exercises the PR-7 sort-free resolve, kept behind
        # KT_SURVIVOR_UNIFIED=0 (the unified kernel owns the default
        # path — tests/test_survivor_unified.py).
        engine.survivor_unified = False
        engine.schedule(units, clusters)
        engine.schedule(list(units), clusters)
        # One column goes fully free: its resource scores jump to the
        # top, finite-K memberships flip, nobody's fit changes.
        drifted = [
            dataclasses.replace(c, available=dict(c.allocatable))
            if j == 5
            else c
            for j, c in enumerate(clusters)
        ]
        got = engine.schedule(units, drifted)
        assert engine.drift_stats["gated"] >= 1, engine.drift_stats
        assert engine.drift_stats["resolve"] > 0, engine.drift_stats
        want = fresh_results(units, drifted, chunk_size=128,
                             min_bucket=32, min_cluster_bucket=8,
                             narrow_m=16)
        results_equal(got, want)

    def test_resolve_chain_stays_exact_across_consecutive_drifts(self):
        """The gate scatters refreshed totals and the resolve repairs
        the prev planes in place — a CHAIN of drifts must stay exact
        (stale state would compound)."""
        units, clusters = self._world(b=64, c=20)
        engine = SchedulerEngine(chunk_size=64, min_bucket=32,
                                 min_cluster_bucket=8, narrow_m=16)
        engine.survivor_unified = False
        engine.schedule(units, clusters)
        engine.schedule(list(units), clusters)
        world = list(clusters)
        rng = np.random.default_rng(3)
        for step in range(5):
            j = int(rng.integers(0, len(world)))
            world = [
                dataclasses.replace(
                    c,
                    available={
                        "cpu": int(c.available["cpu"] * (0.5 + 0.2 * step)),
                        "memory": c.available["memory"],
                    },
                )
                if i == j
                else c
                for i, c in enumerate(world)
            ]
            got = engine.schedule(units, world)
            want = fresh_results(units, world, chunk_size=64,
                                 min_bucket=32, min_cluster_bucket=8,
                                 narrow_m=16)
            results_equal(got, want)
        assert engine.drift_stats["resolve"] > 0, engine.drift_stats

    def test_resolve_disabled_falls_back_to_slabs(self):
        units, clusters = self._world(b=48, c=20)
        engine = SchedulerEngine(chunk_size=64, min_bucket=32,
                                 min_cluster_bucket=8, narrow_m=16)
        engine.drift_resolve = False
        engine.survivor_unified = False
        engine.schedule(units, clusters)
        engine.schedule(list(units), clusters)
        drifted = [
            dataclasses.replace(
                c, available={"cpu": 180_000, "memory": c.available["memory"]}
            )
            if j == 2
            else c
            for j, c in enumerate(clusters)
        ]
        got = engine.schedule(units, drifted)
        assert engine.drift_stats["resolve"] == 0
        want = fresh_results(units, drifted, chunk_size=64, min_bucket=32,
                             min_cluster_bucket=8, narrow_m=16)
        results_equal(got, want)
