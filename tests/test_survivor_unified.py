"""Unified survivor kernel + cached nfeas + eager stale repair (ISSUE 11).

The drift gate's survivors — whatever their classification (no-fit-flip
"resolve" rows, kinf fit-flip "replan" rows, finite-K fit-flip
"score_only" rows) — now ride ONE greedy-grouped ``drift_survivor``
stream per chunk (``engine_drift_rows_total{kind="unified"}``), the
gate reads a CACHED per-row feasible-count vector instead of running a
[B, C] pf.sum pass, and stale device inputs are repaired inside the
churn tick that creates them.  Contract (same as every survivor path
before it): certified rows are bit-identical to a stop-the-world dense
re-solve; cert failures drop to the slab path — counted, never
silently wrong.
"""

import dataclasses

import numpy as np
import pytest

from kubeadmiral_tpu.models.types import (
    ClusterState,
    MODE_DIVIDE,
    SchedulingUnit,
    parse_resources,
)
from kubeadmiral_tpu.runtime.flightrec import FlightRecorder
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

from test_drift_replan import (
    _clusters,
    _fitflip_world,
    _quarter_cpu,
    GVK,
)
from test_engine_cache import results_equal


def _engine(**kw):
    kw.setdefault("chunk_size", 128)
    kw.setdefault("min_bucket", 32)
    kw.setdefault("min_cluster_bucket", 8)
    kw.setdefault("narrow_m", 16)
    return SchedulerEngine(**kw)


def _warm(eng, units, clusters):
    eng.schedule(units, clusters)
    eng.schedule(list(units), clusters)


class TestUnifiedSurvivor:
    def test_unified_replaces_all_three_streams(self):
        """A fit-flip drift (the replan/score-only home turf) settles
        every survivor through kind=unified — the three-stream kinds
        stay at zero — bit-identical to a fresh dense engine, with
        flight-recorder parity INCLUDING top-k on every row (the
        unified kernel recomputes scores, so even would-be-replan rows
        carry exact fresh score planes — strictly stronger than the
        replan path's documented fresh-as-of-last-solve staleness)."""
        units, clusters = _fitflip_world()
        rec = FlightRecorder(max_ticks=2, max_bytes=1 << 26)
        eng = _engine(flight_recorder=rec)
        _warm(eng, units, clusters)
        drifted = _quarter_cpu(clusters, 3)
        got = eng.schedule(units, drifted)
        changed = eng.last_changed
        assert eng.drift_stats["gated"] >= 1, eng.drift_stats
        assert eng.drift_stats["unified"] > 0, eng.drift_stats
        for kind in ("resolve", "replan", "score_only"):
            assert eng.drift_stats[kind] == 0, eng.drift_stats
        assert eng.survivor_stats["rows"] > 0, eng.survivor_stats
        assert eng.survivor_stats["groups"] > 0, eng.survivor_stats
        assert (
            eng.survivor_stats["padded_rows"]
            >= eng.survivor_stats["rows"]
        )

        oracle_rec = FlightRecorder(max_ticks=2, max_bytes=1 << 26)
        oracle = _engine(flight_recorder=oracle_rec)
        oracle.survivor_unified = False
        oracle.narrow = False
        want = oracle.schedule(units, drifted)
        results_equal(got, want)
        assert changed, "drift re-decided no rows"
        for row in changed:
            u = units[row]
            a = rec.lookup(u.key)
            b = oracle_rec.lookup(u.key)
            assert a is not None and b is not None, u.key
            assert a.placements == b.placements, u.key
            assert np.array_equal(a.reason_counts, b.reason_counts), u.key
            assert a.feasible_n == b.feasible_n, u.key
            # No replan exemption: unified rows' top-k is exact.
            assert np.array_equal(a.topk_idx, b.topk_idx), u.key
            assert np.array_equal(a.topk_scores, b.topk_scores), u.key

    def test_mixed_modes_ride_one_stream(self):
        """A drift that simultaneously flips fit at one member AND
        moves finite-K score rankings at another mixes all three
        would-be modes in the same chunk; every survivor still lands in
        kind=unified (one group stream), outputs exact."""
        units, clusters = _fitflip_world(b=96, c=24)
        eng = _engine()
        _warm(eng, units, clusters)
        world = _quarter_cpu(clusters, 3)  # fit flips at member 3
        world = [
            dataclasses.replace(c, available=dict(c.allocatable))
            if j == 7  # member 7 fully free: rankings move, fit doesn't
            else c
            for j, c in enumerate(world)
        ]
        got = eng.schedule(units, world)
        assert eng.drift_stats["unified"] > 0, eng.drift_stats
        for kind in ("resolve", "replan", "score_only"):
            assert eng.drift_stats[kind] == 0, eng.drift_stats
        want = _engine().schedule(units, world)
        results_equal(got, want)

    def test_wide_delta_rides_unified(self):
        """Drifts wider than the gate's rank-refinement bound (D > 8
        changed columns) made the old resolve path ineligible — its
        candidate completeness is O(D).  The unified kernel consults no
        delta info, so wide drifts settle through it too (exactly)."""
        units, clusters = _fitflip_world(b=96, c=48)
        eng = _engine()
        _warm(eng, units, clusters)
        world = [
            dataclasses.replace(
                c,
                available={
                    "cpu": max(1, int(c.available["cpu"] * 0.6)),
                    "memory": c.available["memory"],
                },
            )
            if j < 10  # 10 changed columns: > DRIFT_REFINE_MAX_COLS,
            else c     # < C/4 (the mass-change bail)
            for j, c in enumerate(clusters)
        ]
        got = eng.schedule(units, world)
        assert eng.drift_stats["gated"] >= 1, eng.drift_stats
        assert eng.drift_stats["unified"] > 0, eng.drift_stats
        want = _engine().schedule(units, world)
        results_equal(got, want)

    def test_planner_spill_forces_unified_fallback_exactly(self):
        """Adversarial: Divide rows whose weighted cascade touches more
        members than the narrow slot budget — the phantom-tail cert
        fails, rows drop to the slab path (kind=unified_fallback,
        survivor_stats.fallback_rows), outputs still exact."""
        c = 40
        clusters = _clusters(c, cpu=256, avail_fn=lambda j: {
            "cpu": "200", "memory": "400Gi",
        })
        units = [
            SchedulingUnit(
                gvk=GVK,
                namespace="ns",
                name=f"wide-{i:04d}",
                scheduling_mode=MODE_DIVIDE,
                desired_replicas=400,
                resource_request=parse_resources({"cpu": f"{2 + i % 3}"}),
            )
            for i in range(48)
        ]
        eng = _engine(chunk_size=64)
        _warm(eng, units, clusters)
        drifted = _quarter_cpu(clusters, 1)
        drifted[1] = dataclasses.replace(
            drifted[1],
            available=parse_resources({"cpu": "1", "memory": "400Gi"}),
        )
        got = eng.schedule(units, drifted)
        assert eng.drift_stats["unified_fallback"] > 0, eng.drift_stats
        assert eng.survivor_stats["fallback_rows"] > 0, eng.survivor_stats
        want = _engine(chunk_size=64).schedule(units, drifted)
        results_equal(got, want)

    def test_kt_survivor_unified_off_reverts_to_three_streams(self):
        units, clusters = _fitflip_world(b=64, c=20)
        eng = _engine(chunk_size=64)
        eng.survivor_unified = False
        _warm(eng, units, clusters)
        drifted = _quarter_cpu(clusters, 3)
        got = eng.schedule(units, drifted)
        assert eng.drift_stats["unified"] == 0
        legacy = (
            eng.drift_stats["replan"] + eng.drift_stats["score_only"]
            + eng.drift_stats["resolve"]
            + eng.drift_stats["replan_fallback"]
            + eng.drift_stats["score_only_fallback"]
        )
        assert legacy > 0, eng.drift_stats
        want = _engine(chunk_size=64).schedule(units, drifted)
        results_equal(got, want)


def _cached_nfeas_consistent(eng) -> None:
    """Every cached chunk's nfeas vector must equal the row sum of its
    feasibility plane — the invariant every store/patch site keeps."""
    checked = 0
    for entry in eng._chunk_cache.values():
        if entry.prev_feas is None or entry.prev_nfeas is None:
            continue
        want = (np.asarray(entry.prev_feas) != 0).sum(axis=1)
        got = np.asarray(entry.prev_nfeas)
        assert np.array_equal(got, want.astype(np.int32)), (
            got, want,
        )
        checked += 1
    assert checked > 0, "no cached chunk carried an nfeas vector"


class TestCachedNfeas:
    def test_nfeas_stays_exact_across_churn_drift_chain(self):
        """churn -> drift -> churn -> drift: the cached nfeas vector is
        patched by the slab repair and the survivor repair, consumed by
        every gate — the chain must stay consistent with prev_feas AND
        keep classification exact (results match fresh engines)."""
        rng = np.random.default_rng(5)
        units, clusters = _fitflip_world(b=96, c=24)
        eng = _engine()
        _warm(eng, units, clusters)
        _cached_nfeas_consistent(eng)
        world = list(clusters)
        cur_units = list(units)
        for step in range(4):
            if step % 2 == 0:
                # Churn: replace a handful of rows (patch + slab path).
                cur_units = list(cur_units)
                for i in rng.integers(0, len(cur_units), 7):
                    u = cur_units[int(i)]
                    cur_units[int(i)] = dataclasses.replace(
                        u,
                        desired_replicas=int(rng.integers(1, 40)),
                        resource_request=parse_resources(
                            {"cpu": f"{1 + int(rng.integers(0, 6))}"}
                        ),
                    )
                got = eng.schedule(cur_units, world)
            else:
                # Drift: quarter one member's cpu (fit flips).
                world = _quarter_cpu(world, int(rng.integers(0, len(world))))
                got = eng.schedule(cur_units, world)
                assert eng.drift_stats["gated"] >= 1, eng.drift_stats
            want = _engine().schedule(cur_units, world)
            results_equal(got, want)
            _cached_nfeas_consistent(eng)

    def test_nfeas_snapshot_roundtrip(self):
        """A restored snapshot derives nfeas host-side; the first drift
        tick after restore gates off it exactly."""
        import pickle

        units, clusters = _fitflip_world(b=64, c=20)
        eng = _engine(chunk_size=64)
        _warm(eng, units, clusters)
        snap = pickle.loads(pickle.dumps(eng.snapshot_state()))
        assert snap is not None

        e2 = _engine(chunk_size=64)
        e2.stage_restore(snap, assume_fresh=True)
        drifted = _quarter_cpu(clusters, 3)
        got = e2.schedule(units, drifted)
        assert e2.restore_info["result"].startswith("loaded"), e2.restore_info
        assert e2.drift_stats["gated"] >= 1, e2.drift_stats
        want = _engine(chunk_size=64).schedule(units, drifted)
        results_equal(got, want)
        _cached_nfeas_consistent(e2)

    def test_missing_nfeas_rederives_lazily(self):
        """Dropping the cached vector (e.g. a revert knob flip) must
        not break the gate: _ensure_nfeas re-derives it."""
        units, clusters = _fitflip_world(b=64, c=20)
        eng = _engine(chunk_size=64)
        _warm(eng, units, clusters)
        for entry in eng._chunk_cache.values():
            entry.prev_nfeas = None
        drifted = _quarter_cpu(clusters, 3)
        got = eng.schedule(units, drifted)
        assert eng.drift_stats["gated"] >= 1, eng.drift_stats
        want = _engine(chunk_size=64).schedule(units, drifted)
        results_equal(got, want)
        _cached_nfeas_consistent(eng)


class TestEagerStaleRepair:
    def test_churn_tick_repairs_its_own_stale_rows(self):
        """A churn tick's sub-batch pass leaves NO stale device-input
        rows behind: the eager repair runs in the same tick (counted
        phase=churn) and the next drift gate sees zero (phase=drift
        stays 0) — results exact throughout."""
        units, clusters = _fitflip_world(b=96, c=24)
        eng = _engine()
        _warm(eng, units, clusters)
        churned = list(units)
        for i in (3, 17, 40, 66):
            churned[i] = dataclasses.replace(
                units[i], desired_replicas=(units[i].desired_replicas or 1) + 9
            )
        eng.schedule(churned, clusters)
        assert eng.stale_repair_rows["churn"] > 0, eng.stale_repair_rows
        for entry in eng._chunk_cache.values():
            assert not entry.stale_rows, entry.stale_rows
        drifted = _quarter_cpu(clusters, 3)
        got = eng.schedule(churned, drifted)
        assert eng.stale_repair_rows["drift"] == 0, eng.stale_repair_rows
        assert eng.drift_stats["gated"] >= 1, eng.drift_stats
        want = _engine().schedule(churned, drifted)
        results_equal(got, want)

    def test_stale_counter_emitted(self):
        from kubeadmiral_tpu.runtime.metrics import Metrics

        units, clusters = _fitflip_world(b=64, c=20)
        m = Metrics()
        eng = _engine(chunk_size=64, metrics=m)
        _warm(eng, units, clusters)
        churned = list(units)
        churned[5] = dataclasses.replace(units[5], desired_replicas=99)
        eng.schedule(churned, clusters)
        snap = m.snapshot()
        assert any(
            k.startswith("engine_stale_rows_total") and "churn" in k
            for k in snap["counters"]
        ), [k for k in snap["counters"] if "stale" in k]
