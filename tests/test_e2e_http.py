"""The end-to-end slice over REAL transport.

Runs the exact tests from test_e2e_slice.py, but with every apiserver a
real HTTP server (kwok-lite farm): the host and three members serve
Kubernetes-style REST + chunked watch streams with bearer-token auth,
member clients are built from FederatedCluster join secrets via
FederatedClientFactory, and the cluster-join handshake's service-account
token is minted by the member server — the full
credentials-to-propagation path of the reference
(pkg/controllers/util/federatedclient/client.go,
test/e2e/resourcepropagation/framework.go:91) over sockets.
"""

import time

from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm

# Aliased so pytest doesn't re-collect the FakeKube variant here.
from test_e2e_slice import TestEndToEndSlice as _BaseSlice


class TestEndToEndSliceHTTP(_BaseSlice):
    def make_fleet(self):
        self.farm = KwokLiteFarm()
        return self.farm.fleet

    def add_member(self, name):
        return self.farm.add_member(name)

    def cluster_spec(self, name):
        return self.farm.cluster_spec(name)

    def settle(self, *controllers, rounds=20, timeout=60.0, grace=12):
        """Watch events arrive asynchronously over HTTP, so quiescence
        needs a grace window: keep stepping until no controller has made
        progress for `grace` consecutive idle polls."""
        deadline = time.monotonic() + timeout
        idle = 0
        while time.monotonic() < deadline and idle < grace:
            progressed = False
            for c in controllers:
                while c.worker.step():
                    progressed = True
            if progressed:
                idle = 0
            else:
                idle += 1
                time.sleep(0.05)

    def teardown_method(self):
        self.farm.close()


from test_e2e_slice import TestMultiKindPropagation as _BaseKinds


class TestMultiKindPropagationHTTP(_BaseKinds):
    """The parameterized propagation suite over REAL sockets."""

    def make_fleet(self):
        self.farm = KwokLiteFarm()
        return self.farm.fleet

    def add_member(self, name):
        return self.farm.add_member(name)

    def cluster_spec(self, name):
        return self.farm.cluster_spec(name)

    def settle(self, *controllers, rounds=30, timeout=60.0, grace=12):
        deadline = time.monotonic() + timeout
        idle = 0
        while time.monotonic() < deadline and idle < grace:
            progressed = False
            for c in controllers:
                while c.worker.step():
                    progressed = True
            if progressed:
                idle = 0
            else:
                idle += 1
                time.sleep(0.05)

    def teardown_method(self):
        self.farm.close()
