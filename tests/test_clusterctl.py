"""FederatedCluster controller: join handshake, heartbeat, resource
aggregation, removal — mirrors reference
pkg/controllers/federatedcluster behaviors."""

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.clusterctl import (
    CLUSTER_UID_ANNOTATION,
    FED_SYSTEM_NAMESPACE,
    FEDERATED_CLUSTERS,
    FederatedClusterController,
    JOINED,
    NAMESPACES,
    NODES,
    OFFLINE,
    PODS,
    READY,
    SECRETS,
    aggregate_resources,
    get_condition,
    pod_resource_requests,
)
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.testing.fakekube import ClusterFleet


def make_cluster_obj(name):
    return {
        "apiVersion": "core.kubeadmiral.io/v1alpha1",
        "kind": "FederatedCluster",
        "metadata": {"name": name},
        "spec": {"apiEndpoint": f"https://{name}", "secretRef": {"name": f"{name}-secret"}},
    }


def make_node(name, cpu="8", memory="32Gi", ready=True, unschedulable=False, taints=()):
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name},
        "spec": {
            "unschedulable": unschedulable,
            "taints": [dict(t) for t in taints],
        },
        "status": {
            "allocatable": {"cpu": cpu, "memory": memory, "pods": "110"},
            "conditions": [{"type": "Ready", "status": "True" if ready else "False"}],
        },
    }


def make_pod(name, cpu="500m", memory="1Gi", phase="Running", init_cpu=None):
    spec = {
        "containers": [
            {"name": "c", "resources": {"requests": {"cpu": cpu, "memory": memory}}}
        ]
    }
    if init_cpu:
        spec["initContainers"] = [
            {"name": "i", "resources": {"requests": {"cpu": init_cpu}}}
        ]
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
        "status": {"phase": phase},
    }


class TestAggregation:
    def test_sums_schedulable_nodes_only(self):
        nodes = [
            make_node("n1", cpu="4"),
            make_node("n2", cpu="4", unschedulable=True),
            make_node("n3", cpu="4", ready=False),
            make_node("n4", cpu="4", taints=({"key": "k", "effect": "NoSchedule"},)),
        ]
        alloc, avail, count = aggregate_resources(nodes, [])
        assert count == 1
        assert alloc["cpu"] == 4000
        assert "pods" not in alloc
        assert avail == alloc

    def test_available_subtracts_running_pod_requests(self):
        nodes = [make_node("n1", cpu="4", memory="8Gi")]
        pods = [
            make_pod("p1", cpu="1"),
            make_pod("p2", cpu="500m", phase="Succeeded"),  # not counted
        ]
        alloc, avail, _ = aggregate_resources(nodes, pods)
        assert avail["cpu"] == 3000
        assert alloc["cpu"] == 4000

    def test_init_container_max_semantics(self):
        # request = max(sum(containers), initContainers)
        pod = make_pod("p", cpu="250m", init_cpu="2")
        reqs = pod_resource_requests(pod)
        assert reqs["cpu"] == 2000


class TestJoinAndHeartbeat:
    def setup_method(self):
        self.fleet = ClusterFleet()
        self.ctl = FederatedClusterController(
            self.fleet, api_resource_probe=["apps/v1/Deployment"]
        )

    def test_join_creates_member_artifacts(self):
        member = self.fleet.add_member("c1")
        self.fleet.host.create(FEDERATED_CLUSTERS, make_cluster_obj("c1"))
        self.ctl.run_until_idle()

        cluster = self.fleet.host.get(FEDERATED_CLUSTERS, "c1")
        assert get_condition(cluster, JOINED)["status"] == "True"
        ns = member.get(NAMESPACES, FED_SYSTEM_NAMESPACE)
        assert ns["metadata"]["annotations"][CLUSTER_UID_ANNOTATION] == (
            cluster["metadata"]["uid"]
        )
        secret = self.fleet.host.get(
            SECRETS, f"{FED_SYSTEM_NAMESPACE}/c1-secret"
        )
        assert secret["data"]["token"]

    def test_unjoinable_when_owned_by_other_control_plane(self):
        member = self.fleet.add_member("c1")
        member.create(
            NAMESPACES,
            {
                "apiVersion": "v1",
                "kind": "Namespace",
                "metadata": {
                    "name": FED_SYSTEM_NAMESPACE,
                    "annotations": {CLUSTER_UID_ANNOTATION: "someone-else"},
                },
            },
        )
        self.fleet.host.create(FEDERATED_CLUSTERS, make_cluster_obj("c1"))
        self.ctl.run_until_idle()
        cluster = self.fleet.host.get(FEDERATED_CLUSTERS, "c1")
        cond = get_condition(cluster, JOINED)
        assert cond["status"] == "False"
        assert cond["reason"] == "ClusterUnjoinable"

    def test_heartbeat_collects_resources(self):
        member = self.fleet.add_member("c1")
        member.create(NODES, make_node("n1", cpu="16", memory="64Gi"))
        member.create(PODS, make_pod("p1", cpu="2"))
        self.fleet.host.create(FEDERATED_CLUSTERS, make_cluster_obj("c1"))
        self.ctl.run_until_idle()

        cluster = self.fleet.host.get(FEDERATED_CLUSTERS, "c1")
        assert get_condition(cluster, READY)["status"] == "True"
        assert get_condition(cluster, OFFLINE)["status"] == "False"
        res = cluster["status"]["resources"]
        assert res["schedulableNodes"] == 1
        assert res["allocatable"]["cpu"] == "16000m"
        assert res["available"]["cpu"] == "14000m"
        assert cluster["status"]["apiResourceTypes"] == ["apps/v1/Deployment"]

    def test_unhealthy_member_goes_not_ready(self):
        member = self.fleet.add_member("c1")
        self.fleet.host.create(FEDERATED_CLUSTERS, make_cluster_obj("c1"))
        self.ctl.run_until_idle()
        member.healthy = False
        self.ctl.worker.enqueue("c1")
        self.ctl.run_until_idle()
        cluster = self.fleet.host.get(FEDERATED_CLUSTERS, "c1")
        assert get_condition(cluster, READY)["status"] == "False"
        assert get_condition(cluster, OFFLINE)["status"] == "False"

    def test_unreachable_member_goes_offline(self):
        # Joined once, then the member disappears entirely.
        self.fleet.add_member("c1")
        self.fleet.host.create(FEDERATED_CLUSTERS, make_cluster_obj("c1"))
        self.ctl.run_until_idle()
        del self.fleet.members["c1"]
        self.ctl.worker.enqueue("c1")
        self.ctl.run_until_idle()
        cluster = self.fleet.host.get(FEDERATED_CLUSTERS, "c1")
        assert get_condition(cluster, OFFLINE)["status"] == "True"
        assert get_condition(cluster, READY)["status"] == "Unknown"

    def test_removal_cleans_member_and_releases(self):
        member = self.fleet.add_member("c1")
        self.fleet.host.create(FEDERATED_CLUSTERS, make_cluster_obj("c1"))
        self.ctl.run_until_idle()
        assert member.try_get(NAMESPACES, FED_SYSTEM_NAMESPACE)

        self.fleet.host.delete(FEDERATED_CLUSTERS, "c1")
        self.ctl.run_until_idle()
        assert self.fleet.host.try_get(FEDERATED_CLUSTERS, "c1") is None
        assert member.try_get(NAMESPACES, FED_SYSTEM_NAMESPACE) is None


class TestSyncClusterFinalizer:
    def test_finalizer_added_and_cascading_delete_waits(self):
        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        fleet = ClusterFleet()
        member = fleet.add_member("c1")
        clusterctl = FederatedClusterController(fleet)
        sync = SyncController(fleet, ftc)
        fleet.host.create(
            FEDERATED_CLUSTERS,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "FederatedCluster",
                "metadata": {
                    "name": "c1",
                    "annotations": {C.PREFIX + "cascading-delete": ""},
                },
                "spec": {},
            },
        )
        clusterctl.run_until_idle()
        for _ in range(5):
            if not sync.worker.step():
                break
        cluster = fleet.host.get(FEDERATED_CLUSTERS, "c1")
        assert sync.cluster_finalizer in cluster["metadata"]["finalizers"]

        # A managed object lives in the member; deletion must wait for it.
        member.create(
            ftc.source.resource,
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {
                    "name": "web",
                    "namespace": "default",
                    "labels": {C.MANAGED_LABEL: "true"},
                },
                "spec": {},
            },
        )
        fleet.host.delete(FEDERATED_CLUSTERS, "c1")
        for _ in range(5):
            sync.worker.step()
            clusterctl.worker.step()
        assert fleet.host.try_get(FEDERATED_CLUSTERS, "c1") is not None

        # Managed object removed -> sync finalizer released -> cluster
        # controller finishes the removal.
        member.delete(ftc.source.resource, "default/web")
        sync.worker.enqueue("cluster::c1")
        for _ in range(10):
            sync.worker.step()
            clusterctl.worker.step()
        assert fleet.host.try_get(FEDERATED_CLUSTERS, "c1") is None


class TestJoinTimeout:
    def test_join_failure_becomes_terminal_after_timeout(self):
        fleet = ClusterFleet()  # member never appears
        now = [0.0]
        ctl = FederatedClusterController(
            fleet, join_timeout=5.0, clock=lambda: now[0]
        )
        fleet.host.create(FEDERATED_CLUSTERS, make_cluster_obj("ghost"))
        ctl.run_until_idle()
        cluster = fleet.host.get(FEDERATED_CLUSTERS, "ghost")
        assert get_condition(cluster, JOINED)["reason"] == "TokenNotObtained"

        now[0] = 10.0  # past the timeout; retry lands terminal
        ctl.worker.enqueue("ghost")
        ctl.run_until_idle()
        cluster = fleet.host.get(FEDERATED_CLUSTERS, "ghost")
        cond = get_condition(cluster, JOINED)
        assert cond["status"] == "False"
        assert cond["reason"] == "JoinTimeoutExceeded"

        # Terminal: no further retries enqueue work.
        ctl.worker.enqueue("ghost")
        ctl.run_until_idle()
        cluster2 = fleet.host.get(FEDERATED_CLUSTERS, "ghost")
        assert get_condition(cluster2, JOINED)["reason"] == "JoinTimeoutExceeded"
