"""End-to-end scheduler engine tests at the API-object level."""

import numpy as np

from kubeadmiral_tpu.models.types import (
    AutoMigrationSpec,
    ClusterAffinity,
    ClusterState,
    MODE_DIVIDE,
    PreferredSchedulingTerm,
    SelectorRequirement,
    SelectorTerm,
    SchedulingUnit,
    Taint,
    Toleration,
    parse_resources,
)
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

GVK = "apps/v1/Deployment"


def mk_cluster(name, cpu="100", mem="100Gi", cpu_free=None, mem_free=None, **kw):
    alloc = parse_resources({"cpu": cpu, "memory": mem})
    avail = parse_resources(
        {"cpu": cpu_free if cpu_free is not None else cpu,
         "memory": mem_free if mem_free is not None else mem}
    )
    return ClusterState(
        name=name,
        allocatable=alloc,
        available=avail,
        api_resources=frozenset({GVK}),
        **kw,
    )


def mk_unit(name, **kw):
    kw.setdefault("gvk", GVK)
    kw.setdefault("namespace", "default")
    return SchedulingUnit(name=name, **kw)


ENGINE = SchedulerEngine()


def test_duplicate_mode_selects_all_feasible():
    clusters = [mk_cluster("a"), mk_cluster("b"), mk_cluster("c")]
    [res] = ENGINE.schedule([mk_unit("web")], clusters)
    assert res.clusters == {"a": None, "b": None, "c": None}


def test_placement_filter():
    clusters = [mk_cluster("a"), mk_cluster("b"), mk_cluster("c")]
    [res] = ENGINE.schedule(
        [mk_unit("web", cluster_names=frozenset({"a", "c"}))], clusters
    )
    assert res.cluster_set == {"a", "c"}


def test_api_resources_filter():
    missing = mk_cluster("b")
    missing.api_resources = frozenset({"batch/v1/Job"})
    [res] = ENGINE.schedule([mk_unit("web")], [mk_cluster("a"), missing])
    assert res.cluster_set == {"a"}


def test_taints_and_tolerations():
    tainted = mk_cluster("b", taints=(Taint("dedicated", "infra", "NoSchedule"),))
    clusters = [mk_cluster("a"), tainted]
    [plain] = ENGINE.schedule([mk_unit("web")], clusters)
    assert plain.cluster_set == {"a"}
    [tolerant] = ENGINE.schedule(
        [mk_unit("web", tolerations=(Toleration(key="dedicated", operator="Exists"),))],
        clusters,
    )
    assert tolerant.cluster_set == {"a", "b"}


def test_required_affinity():
    eu = mk_cluster("eu-1", labels={"region": "eu"})
    us = mk_cluster("us-1", labels={"region": "us"})
    aff = ClusterAffinity(
        required=(
            SelectorTerm(
                match_expressions=(SelectorRequirement("region", "In", ("eu",)),)
            ),
        )
    )
    [res] = ENGINE.schedule([mk_unit("web", affinity=aff)], [eu, us])
    assert res.cluster_set == {"eu-1"}


def test_preferred_affinity_orders_selection():
    fast = mk_cluster("fast", labels={"tier": "gold"})
    slow = mk_cluster("slow", labels={"tier": "bronze"})
    aff = ClusterAffinity(
        preferred=(
            PreferredSchedulingTerm(
                weight=50,
                preference=SelectorTerm(
                    match_expressions=(SelectorRequirement("tier", "In", ("gold",)),)
                ),
            ),
        )
    )
    [res] = ENGINE.schedule(
        [mk_unit("web", affinity=aff, max_clusters=1)], [slow, fast]
    )
    assert res.cluster_set == {"fast"}


def test_resource_fit():
    small = mk_cluster("small", cpu="1", mem="1Gi")
    big = mk_cluster("big", cpu="64", mem="256Gi")
    su = mk_unit(
        "heavy", resource_request=parse_resources({"cpu": "8", "memory": "32Gi"})
    )
    [res] = ENGINE.schedule([su], [small, big])
    assert res.cluster_set == {"big"}


def test_divide_static_weights():
    clusters = [mk_cluster("a"), mk_cluster("b")]
    su = mk_unit(
        "api",
        scheduling_mode=MODE_DIVIDE,
        desired_replicas=10,
        weights={"a": 3, "b": 1},
        avoid_disruption=False,
    )
    [res] = ENGINE.schedule([su], clusters)
    assert sum(res.clusters.values()) == 10
    assert res.clusters["a"] > res.clusters["b"]


def test_divide_dynamic_weights_follow_available_cpu():
    # b has far more free CPU; dynamic weights should favor it.
    a = mk_cluster("a", cpu="100", cpu_free="10")
    b = mk_cluster("b", cpu="100", cpu_free="90")
    su = mk_unit(
        "api",
        scheduling_mode=MODE_DIVIDE,
        desired_replicas=10,
        avoid_disruption=False,
    )
    [res] = ENGINE.schedule([su], [a, b])
    assert sum(res.clusters.values()) == 10
    assert res.clusters.get("b", 0) > res.clusters.get("a", 0)


def test_sticky_cluster_short_circuits():
    clusters = [mk_cluster("a"), mk_cluster("b")]
    su = mk_unit(
        "db",
        sticky_cluster=True,
        current_clusters={"a": 5},
        scheduling_mode=MODE_DIVIDE,
        desired_replicas=9,
    )
    [res] = ENGINE.schedule([su], clusters)
    assert res.clusters == {"a": 5}


def test_automigration_capacity_spills_replicas():
    clusters = [mk_cluster("a"), mk_cluster("b")]
    su = mk_unit(
        "api",
        scheduling_mode=MODE_DIVIDE,
        desired_replicas=10,
        weights={"a": 1000, "b": 1},
        avoid_disruption=False,
        auto_migration=AutoMigrationSpec(estimated_capacity={"a": 3}),
    )
    [res] = ENGINE.schedule([su], clusters)
    # a is capped at 3; the rest lands on b. keep_unschedulable defaults to
    # False but avoid_disruption=False forces keep, so the overflow stays
    # attached to a as "nice to have" replicas.
    assert res.clusters["b"] >= 7
    assert res.clusters["a"] >= 3


def test_chunking_large_batch():
    clusters = [mk_cluster(f"c{i}") for i in range(7)]
    engine = SchedulerEngine(chunk_size=16, min_bucket=8)
    units = [
        mk_unit(
            f"obj-{i}",
            scheduling_mode=MODE_DIVIDE,
            desired_replicas=i % 13,
            avoid_disruption=False,
        )
        for i in range(50)
    ]
    results = engine.schedule(units, clusters)
    assert len(results) == 50
    for i, res in enumerate(results):
        assert sum(res.clusters.values()) == i % 13


def test_pipelined_chunks_match_sequential():
    """KT_PIPELINE_DEPTH=2 keeps chunks in flight while the host
    featurizes/decodes; outputs must be identical to the strictly
    sequential dispatch, across cold and churn ticks."""
    clusters = [mk_cluster(f"c{i}") for i in range(7)]
    units = [
        mk_unit(
            f"obj-{i}",
            scheduling_mode=MODE_DIVIDE,
            desired_replicas=(i % 13) + 1,
            avoid_disruption=False,
        )
        for i in range(50)
    ]
    seq = SchedulerEngine(chunk_size=16, min_bucket=8)
    # Pin the reference engine to the strictly sequential per-chunk
    # drain: with the pipelined default both sides would take the
    # batched window path and a bug there would cancel out.
    seq.pipeline_depth = 1
    piped = SchedulerEngine(chunk_size=16, min_bucket=8)
    piped.pipeline_depth = 3
    assert seq.schedule(units, clusters) == piped.schedule(units, clusters)
    import dataclasses

    churned = list(units)
    churned[5] = dataclasses.replace(churned[5], desired_replicas=40)
    churned[30] = dataclasses.replace(churned[30], desired_replicas=2)
    assert seq.schedule(churned, clusters) == piped.schedule(churned, clusters)


def test_empty_inputs():
    assert ENGINE.schedule([], [mk_cluster("a")]) == []
    [res] = ENGINE.schedule([mk_unit("web")], [])
    assert res.clusters == {}


def test_dynamic_weight_total_overflow_rejected():
    import pytest

    clusters = [mk_cluster("a"), mk_cluster("b")]
    su = mk_unit(
        "huge", scheduling_mode=MODE_DIVIDE, desired_replicas=5_000_000,
        avoid_disruption=False,
    )
    with pytest.raises(OverflowError):
        ENGINE.schedule([su], clusters)


def test_divide_negative_current_nil_entry_counted_correctly():
    # A sticky object whose current entries are nil keeps nil (None) in the
    # result rather than a fake count.
    clusters = [mk_cluster("a")]
    su = mk_unit(
        "st", sticky_cluster=True, current_clusters={"a": None},
        scheduling_mode=MODE_DIVIDE, desired_replicas=4,
    )
    [res] = ENGINE.schedule([su], clusters)
    assert res.clusters == {"a": None}
