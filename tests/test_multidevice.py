"""Multi-device engine scale-out: tier-1 parity at N ∈ {2, 4} (ISSUE 12).

Promotes the ``dryrun_multichip`` engine-parity blocks into the tier-1
suite: a meshed engine over N forced host devices must be bit-identical
to the single-device engine through the full chain — cold, no-op replay,
churn sub-batch, capacity-drift gate, the unified survivor stream
(rows-sharded groups under KT_SURVIVOR_ROWSHARD), fit-flip replans —
including the flight recorder's reason counts.  Plus the ISSUE 12
satellites: the sharded snapshot round-trip, the per-device-safe
adaptive-K aggregation, the f16 score-plane compression contract, the
AOT topology guard, and a forced-device-count subprocess proving the
pre-import env path (auto mesh, per-device pipeline windows).

The ambient test harness forces 8 virtual CPU devices (conftest.py), so
N ∈ {2, 4} meshes build in-process from explicit device subsets; only
the auto-resolution test needs a subprocess (device count binds at jax
backend init)."""

from __future__ import annotations

import dataclasses
import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

from kubeadmiral_tpu.models.types import parse_resources  # noqa: E402
from kubeadmiral_tpu.parallel import mesh as M  # noqa: E402
from kubeadmiral_tpu.runtime import census  # noqa: E402
from kubeadmiral_tpu.runtime.flightrec import FlightRecorder  # noqa: E402
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine  # noqa: E402

from __graft_entry__ import _example_units_clusters  # noqa: E402

REPO = pathlib.Path(__file__).resolve().parent.parent


def _world(b=96, c=16):
    units, clusters = _example_units_clusters(b, c)
    # Mix in finite-K rows so the drift gate's top-K machinery and the
    # unified survivor kernel both engage (the dryrun's flip_units mix).
    units = [
        dataclasses.replace(u, max_clusters=None if i % 2 else 2 + i % 3)
        for i, u in enumerate(units)
    ]
    return units, clusters


def _mesh(n):
    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"need {n} devices, have {len(devices)}")
    return M.make_mesh(devices[:n])


def _engine(mesh, rec=None, **kw):
    return SchedulerEngine(
        mesh=mesh, min_bucket=32, narrow_m=8,
        flight_recorder=rec if rec is not None else None,
        **kw,
    )


def _drifts(clusters):
    halved = list(clusters)
    halved[0] = dataclasses.replace(
        halved[0],
        available={k: max(0, v // 2) for k, v in halved[0].available.items()},
    )
    # Column 1 keeps only 700m cpu free: a real fit flip for a fraction
    # of rows — the unified survivor stream's regime.
    squeezed = [
        dataclasses.replace(
            cl, available={**cl.available, **parse_resources({"cpu": "700m"})}
        )
        if j == 1
        else cl
        for j, cl in enumerate(clusters)
    ]
    boosted = [
        dataclasses.replace(cl, available=dict(cl.allocatable))
        if j == 3
        else cl
        for j, cl in enumerate(clusters)
    ]
    return halved, squeezed, boosted


def _rec_state(rec: FlightRecorder) -> dict:
    """Per-key (placements, reason_counts, feasible_n) — the recorder
    fields that must match between meshed and single-device engines."""
    return {
        k: (
            dict(r.placements),
            None
            if r.reason_counts is None
            else tuple(np.asarray(r.reason_counts).tolist()),
            None if r.feasible_n is None else int(r.feasible_n),
        )
        for k, r in rec._index.items()
    }


@pytest.mark.parametrize("n", [2, 4])
def test_parity_chain_vs_single_device(n):
    """steady / noop / churn / drift / survivor chains at N devices are
    bit-identical to N=1, flight-recorder reason counts included."""
    units, clusters = _world()
    rec_m = FlightRecorder(enabled=True)
    rec_s = FlightRecorder(enabled=True)
    meshed = _engine(_mesh(n), rec=rec_m)
    single = _engine(None, rec=rec_s)
    assert meshed.pipeline_depth == meshed.pipeline_depth_per_device * n

    # Cold + no-op replay.
    assert meshed.schedule(units, clusters) == single.schedule(units, clusters)
    cold_dispatches = meshed.dispatches_total
    assert meshed.schedule(units, clusters) == single.schedule(units, clusters)
    assert meshed.dispatches_total == cold_dispatches, "noop re-dispatched"
    assert meshed.fetch_stats["noop"] >= 1

    # Churn sub-batch.
    churned = list(units)
    churned[0] = dataclasses.replace(
        churned[0], desired_replicas=(units[0].desired_replicas or 1) + 7
    )
    assert meshed.schedule(churned, clusters) == single.schedule(
        churned, clusters
    )
    assert meshed.fetch_stats["subbatch"] >= 1

    # Capacity drift -> gate; cpu squeeze -> fit-flip survivors through
    # the (rows-sharded) unified kernel; boost -> top-K membership flip.
    halved, squeezed, boosted = _drifts(clusters)
    for world in (halved, squeezed, boosted):
        assert meshed.schedule(churned, world) == single.schedule(
            churned, world
        ), "drift parity"
    assert meshed.drift_stats["gated"] >= 1
    assert (
        meshed.drift_stats["unified"] + meshed.drift_stats["unified_fallback"]
        > 0
    ), meshed.drift_stats
    # Same drift classification on both sides (the gate is exact).
    for k in ("skip", "unified", "recompute", "wcheck_changed"):
        assert meshed.drift_stats[k] == single.drift_stats[k], (
            k, meshed.drift_stats, single.drift_stats,
        )

    # Flight recorder: identical per-key placements + reason counts.
    assert _rec_state(rec_m) == _rec_state(rec_s)


def test_snapshot_round_trip_sharded():
    """A sharded engine's snapshot restores bit-identical into a fresh
    sharded engine — prev planes gathered at capture, re-device_put with
    the mesh shardings at restore, zero-dispatch no-op replay preserved."""
    units, clusters = _world(b=64)
    src = _engine(_mesh(4))
    want = src.schedule(units, clusters)
    payload = src.snapshot_state()
    assert payload is not None and payload["config"]["mesh"] == (4, 1)

    dst = _engine(_mesh(4))
    dst.stage_restore(payload, assume_fresh=True)
    before = dst.dispatches_total
    got = dst.schedule(units, clusters)
    assert got == want
    assert dst.restore_info["result"] == "loaded", dst.restore_info
    assert dst.restore_info["fresh"] is True, dst.restore_info
    assert dst.dispatches_total == before, "fresh resume dispatched"
    # The restored planes live under the mesh shardings: a drift tick
    # rides the gate path on sharded buffers, parity-exact.
    halved, _, _ = _drifts(clusters)
    single = _engine(None)
    single.schedule(units, clusters)
    assert dst.schedule(units, halved) == single.schedule(units, halved)
    assert dst.drift_stats["gated"] >= 1


def test_snapshot_topology_mismatch_rejected():
    """A 4-device snapshot must not restore into a 2-device engine (the
    plane shardings and geometry differ): rejected -> cold, never a
    reinterpretation."""
    units, clusters = _world(b=64)
    src = _engine(_mesh(4))
    want = src.schedule(units, clusters)
    payload = src.snapshot_state()
    dst = _engine(_mesh(2))
    dst.stage_restore(payload, assume_fresh=True)
    assert dst.schedule(units, clusters) == want  # cold solve, same answer
    assert dst.restore_info["result"] == "rejected"


def test_observe_nsel_aggregates_per_tick():
    """The adaptive-K hint casts ONE vote per tick on the aggregated
    observations: two device-local wire pieces of one batch must not
    double-count shrink votes (the regression: piecewise observation
    halved K after a single narrow tick)."""
    eng = _engine(None)
    entry = type("E", (), {"pack_k_hint": 64, "pack_shrink_votes": 0})()
    narrow = np.ones(32, np.int64)  # rows selecting 1 cluster each
    # Old behavior: each piece votes shrink -> two consecutive votes ->
    # hint halves within one tick.  New behavior: one aggregated vote.
    eng._observe_nsel(entry, narrow, 256)
    eng._observe_nsel(entry, narrow, 256)
    eng._flush_nsel()
    assert entry.pack_shrink_votes == 1, entry.pack_shrink_votes
    assert entry.pack_k_hint == 64, entry.pack_k_hint
    # The second tick's aggregate casts the second vote -> decay engages
    # exactly as the hysteresis contract documents.
    eng._observe_nsel(entry, narrow, 256)
    eng._observe_nsel(entry, narrow, 256)
    eng._flush_nsel()
    assert entry.pack_shrink_votes == 0
    assert entry.pack_k_hint == 32


def test_score_f16_parity(monkeypatch):
    """KT_SCORE_F16: compressed resident score planes stay bit-identical
    through steady/churn/drift — lossy rows are forced into recompute by
    the exactness guard, never trusted."""
    units, clusters = _world()
    monkeypatch.setenv("KT_SCORE_F16", "1")
    packed = _engine(_mesh(4))
    monkeypatch.delenv("KT_SCORE_F16")
    plain = _engine(None)
    assert packed.score_f16 and not plain.score_f16

    assert packed.schedule(units, clusters) == plain.schedule(units, clusters)
    entry = packed._chunk_cache[0]
    assert entry.prev_out[3].dtype == np.float16
    assert entry.prev_sco_exact is not None
    churned = list(units)
    churned[5] = dataclasses.replace(churned[5], desired_replicas=83)
    assert packed.schedule(churned, clusters) == plain.schedule(
        churned, clusters
    )
    halved, squeezed, _ = _drifts(clusters)
    for world in (halved, squeezed):
        assert packed.schedule(churned, world) == plain.schedule(
            churned, world
        )
    assert packed.drift_stats["gated"] >= 1
    # The snapshot carries the compressed plane + exactness vector and
    # round-trips into a compressed engine.
    payload = packed.snapshot_state()
    assert payload["config"]["score_f16"] is True
    monkeypatch.setenv("KT_SCORE_F16", "1")
    fresh = _engine(_mesh(4))
    fresh.stage_restore(payload, assume_fresh=True)
    assert fresh.schedule(churned, squeezed) == plain.schedule(
        churned, squeezed
    )


def test_census_model_validates_against_live_engine():
    """The c6 census model predicts the live engine's resident prev
    planes at a small shape (the honesty check bench --scenario census
    gates on), and the decision cascade engages compression/sharding."""
    v = census.validate(512, 64)
    assert v["ok"], v
    # c6 at 4 devices with a tight budget: the decision must resolve to
    # a finite configuration, and the f16 projection must actually be
    # smaller than i32.
    d = census.decide(1_000_000, 10_000, 4, budget_bytes=16 << 30)
    assert d["per_device_f16"] < d["per_device_i32"]
    assert d["verdict"] in ("fits", "compress", "shard")
    if d["verdict"] == "shard":
        assert d["min_devices"] > 4
        resolved = census.project(
            1_000_000, 10_000, d["min_devices"], score_f16=True
        )
        assert resolved["per_device"] <= 16 << 30
    # Geometry is device-count-aware: more devices, bigger megachunks.
    g1 = census.project(1_000_000, 10_000, 1)["geometry"]
    g4 = census.project(1_000_000, 10_000, 4)["geometry"]
    assert g4["eff_chunk"] > g1["eff_chunk"]


def test_aot_live_trace_under_mesh():
    """Meshed engines run the AOT store in live-trace-only mode: honest
    ``traced`` counts, zero preloads, no manifest writes — and the
    manifest guard carries the device topology."""
    eng = _engine(_mesh(2))
    assert eng._aot.live_trace_only
    units, clusters = _world(b=32)
    eng.schedule(units, clusters)
    assert eng._aot.stats["traced"] > 0
    assert eng._aot.stats["loaded"] == 0
    assert eng._aot.preload_all() == 0
    guard = eng._aot._guard()
    assert guard["devices"] == jax.device_count()


@pytest.mark.slow
def test_forced_device_count_subprocess():
    """The pre-import env path (the one a real deployment uses): a fresh
    process with XLA_FLAGS forcing 2 host devices auto-resolves a 2x1
    objects mesh, scales the per-device pipeline window, and schedules
    bit-identically to an explicit single-device engine in the same
    process."""
    code = (
        "import dataclasses, json\n"
        "import jax\n"
        "assert len(jax.devices()) == 2, jax.devices()\n"
        "from kubeadmiral_tpu.scheduler.engine import SchedulerEngine\n"
        "from __graft_entry__ import _example_units_clusters\n"
        "units, clusters = _example_units_clusters(64, 16)\n"
        "auto = SchedulerEngine(min_bucket=32)\n"
        "assert auto.mesh is not None and auto.mesh.devices.shape == (2, 1)\n"
        "assert auto.pipeline_depth == auto.pipeline_depth_per_device * 2\n"
        "single = SchedulerEngine(mesh=None, min_bucket=32)\n"
        "assert auto.schedule(units, clusters) == "
        "single.schedule(units, clusters)\n"
        "drifted = list(clusters)\n"
        "drifted[0] = dataclasses.replace(drifted[0], available={k: max(0, "
        "v // 2) for k, v in drifted[0].available.items()})\n"
        "assert auto.schedule(units, drifted) == "
        "single.schedule(units, drifted)\n"
        "print(json.dumps({'ok': True, 'aot': dict(auto._aot.stats)}))\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    import json

    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    assert doc["ok"] and doc["aot"]["loaded"] == 0
