"""Transport scale smoke: 12 real member apiservers over sockets.

The 3-member HTTP e2e proves correctness; this proves the transport's
structure holds at wider fan-out — per-member reflector streams, the
join handshake's token upgrade on every member, divided replicas across
the full fleet, and clean teardown of ~dozens of HTTP servers/threads.
"""

import dataclasses
import time

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.clusterctl import (
    FEDERATED_CLUSTERS,
    FederatedClusterController,
    NODES,
)
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm

from test_e2e_slice import make_deployment, make_node

N_MEMBERS = 12
N_OBJECTS = 20


def settle(controllers, timeout=120.0, grace=15):
    deadline = time.monotonic() + timeout
    idle = 0
    while time.monotonic() < deadline and idle < grace:
        progressed = False
        for c in controllers:
            while c.worker.step():
                progressed = True
        if progressed:
            idle = 0
        else:
            idle += 1
            time.sleep(0.05)


def test_wide_fanout_over_http():
    farm = KwokLiteFarm()
    try:
        ftc = dataclasses.replace(
            next(f for f in default_ftcs() if f.name == "deployments.apps"),
            controllers=(("kubeadmiral.io/global-scheduler",),),
        )
        controllers = (
            FederatedClusterController(
                farm.fleet, api_resource_probe=["apps/v1/Deployment"]
            ),
            FederateController(farm.fleet.host, ftc),
            SchedulerController(farm.fleet.host, ftc),
            SyncController(farm.fleet, ftc),
        )
        for i in range(N_MEMBERS):
            name = f"m{i:02d}"
            member = farm.add_member(name)
            member.create(NODES, make_node("n1", str(16 + i), "64Gi"))
            farm.host.create(
                FEDERATED_CLUSTERS,
                {"apiVersion": "core.kubeadmiral.io/v1alpha1",
                 "kind": "FederatedCluster",
                 "metadata": {"name": name},
                 "spec": farm.cluster_spec(name)},
            )
        farm.host.create(
            PROPAGATION_POLICIES,
            {"apiVersion": "core.kubeadmiral.io/v1alpha1",
             "kind": "PropagationPolicy",
             "metadata": {"name": "pp", "namespace": "default"},
             "spec": {"schedulingMode": "Divide"}},
        )
        for i in range(N_OBJECTS):
            farm.host.create(
                ftc.source.resource,
                make_deployment(name=f"app-{i:02d}", replicas=24 + i),
            )
        settle(controllers)

        # Every member joined with an upgraded (minted) SA token.
        for i in range(N_MEMBERS):
            secret = farm.host.get(
                "v1/secrets", f"kube-admiral-system/m{i:02d}-secret"
            )
            assert not secret["data"]["token"].startswith("admin-")

        # Every object fully propagated; replica totals preserved.
        for i in range(N_OBJECTS):
            key = f"default/app-{i:02d}"
            fed = farm.host.get(ftc.federated.resource, key)
            placed = C.get_placement(fed, C.SCHEDULER)
            assert placed, key
            total = 0
            for cname in placed:
                obj = farm.fleet.member(cname).get(ftc.source.resource, key)
                total += obj["spec"]["replicas"]
            assert total == 24 + i, (key, total)
    finally:
        farm.close()
