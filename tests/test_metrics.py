"""Label-aware metrics registry + Prometheus text exposition
(runtime/metrics.py; reference: pkg/stats/stats.go)."""

import re

from kubeadmiral_tpu.runtime.metrics import (
    Histogram,
    Metrics,
    series_key,
)
from kubeadmiral_tpu.runtime.metric_catalog import CATALOG, is_cataloged


class TestLabeledSeries:
    def test_tags_make_distinct_series(self):
        m = Metrics()
        m.counter("worker_retries", cluster="c1")
        m.counter("worker_retries", cluster="c1")
        m.counter("worker_retries", cluster="c2")
        assert m.get_counter("worker_retries", cluster="c1") == 2
        assert m.get_counter("worker_retries", cluster="c2") == 1
        # The legacy dict view keys incorporate the sorted label pairs.
        assert m.counters["worker_retries{cluster=c1}"] == 2

    def test_untagged_call_sites_keep_plain_keys(self):
        """The pre-exposition contract: monitor/stress tests read
        metrics.counters/stores/durations by bare name."""
        m = Metrics()
        m.counter("scheduler-x.panic")
        m.store("monitor.clusters.ready", 3)
        m.duration("monitor.x.sync_latency", 1.5)
        assert m.counters["scheduler-x.panic"] == 1
        assert m.stores["monitor.clusters.ready"] == 3
        assert m.durations["monitor.x.sync_latency"] == [1.5]

    def test_tag_order_is_irrelevant(self):
        m = Metrics()
        m.counter("c", a="1", b="2")
        m.counter("c", b="2", a="1")
        assert m.get_counter("c", b="2", a="1") == 2
        assert series_key("c", {"b": "2", "a": "1"}) == "c{a=1,b=2}"

    def test_timer_feeds_histogram(self):
        m = Metrics()
        with m.timer("op.latency", controller="x"):
            pass
        key = series_key("op.latency", {"controller": "x"})
        assert m.histograms[key].count == 1
        assert len(m.durations[key]) == 1

    def test_counter_family_readback(self):
        m = Metrics()
        m.counter("worker_exceptions_total", controller="sync-a")
        m.counter("worker_exceptions_total", 2, controller="sync-b")
        fam = m.counter_family("worker_exceptions_total")
        assert fam == {
            (("controller", "sync-a"),): 1,
            (("controller", "sync-b"),): 2,
        }
        assert m.sum_counter("worker_exceptions_total") == 3


# One exposition line: name{labels} value  (or a # comment).
_LINE = re.compile(
    r"^(# (TYPE|HELP) .*"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.e+-]+(inf|nan)?)$"
)


def render_lines(m):
    text = m.render_prometheus()
    lines = text.splitlines()
    for line in lines:
        assert _LINE.match(line), f"invalid exposition line: {line!r}"
    return lines


class TestPrometheusExposition:
    def test_name_sanitization(self):
        m = Metrics()
        m.store("monitor.clusters.ready", 2)
        m.counter("scheduler-web.panic")
        lines = render_lines(m)
        assert "monitor_clusters_ready 2" in lines
        assert "scheduler_web_panic 1" in lines

    def test_label_escaping(self):
        m = Metrics()
        m.store("g", 1, path='a"b\\c\nd')
        text = m.render_prometheus()
        assert 'path="a\\"b\\\\c\\nd"' in text

    def test_label_ordering_deterministic(self):
        m1 = Metrics()
        m1.counter("c", a="1", z="2")
        m2 = Metrics()
        m2.counter("c", z="2", a="1")
        assert m1.render_prometheus() == m2.render_prometheus()
        # Multiple series render sorted by label set, independent of
        # emission order.
        m1.counter("c", a="0", z="9")
        first = m1.render_prometheus()
        assert first.index('a="0"') < first.index('a="1"')

    def test_histogram_bucket_cumulativity(self):
        m = Metrics()
        for v in (0.0005, 0.003, 0.003, 0.2, 7.0, 100.0):
            m.histogram("lat", v, stage="device")
        lines = render_lines(m)
        buckets = [
            (line.rsplit(" ", 1)[0], int(line.rsplit(" ", 1)[1]))
            for line in lines
            if line.startswith("lat_bucket")
        ]
        counts = [n for _, n in buckets]
        # Cumulative and non-decreasing, ending at the total count.
        assert counts == sorted(counts)
        assert buckets[-1][0].endswith('le="+Inf"}')
        assert counts[-1] == 6
        assert any(line == "lat_count{stage=\"device\"} 6" for line in lines)
        total = next(
            float(line.rsplit(" ", 1)[1])
            for line in lines
            if line.startswith("lat_sum")
        )
        assert abs(total - 107.2065) < 1e-9

    def test_type_lines(self):
        m = Metrics()
        m.counter("a_total")
        m.store("b", 1)
        m.histogram("c_seconds", 0.1)
        lines = render_lines(m)
        assert "# TYPE a_total counter" in lines
        assert "# TYPE b gauge" in lines
        assert "# TYPE c_seconds histogram" in lines

    def test_mixed_tagged_and_untagged(self):
        m = Metrics()
        m.counter("hits")
        m.counter("hits", shape="64x256")
        lines = render_lines(m)
        assert "hits 1" in lines
        assert 'hits{shape="64x256"} 1' in lines


class TestHistogram:
    def test_bucket_assignment(self):
        h = Histogram(buckets=(1.0, 2.0))
        for v in (0.5, 1.5, 99.0):
            h.observe(v)
        assert h.cumulative() == [(1.0, 1), (2.0, 2), (float("inf"), 3)]
        assert h.count == 3


class TestHistogramQuantiles:
    """Interpolated percentile extraction (ISSUE 13 satellite): the
    shared helper the SLO evaluator and /debug/slo read, verified
    against known distributions."""

    def test_empty_histogram_returns_none(self):
        assert Histogram().quantile(0.5) is None

    def test_single_bucket_interpolates_linearly(self):
        # 100 observations all in the (0.025, 0.05] bucket: the pXX
        # estimate walks linearly across the bucket, exactly as
        # PromQL's histogram_quantile.
        h = Histogram()
        for _ in range(100):
            h.observe(0.03)
        assert h.quantile(0.5) == 0.025 + 0.025 * 0.5
        assert h.quantile(0.99) == 0.025 + 0.025 * 0.99

    def test_uniform_distribution_hits_bucket_edges(self):
        # One observation per bucket of (1, 2, 3, 4): p50 falls at the
        # upper edge of the second bucket, p25 at the first.
        h = Histogram(buckets=(1.0, 2.0, 3.0, 4.0))
        for v in (0.5, 1.5, 2.5, 3.5):
            h.observe(v)
        assert h.quantile(0.5) == 2.0
        assert h.quantile(0.25) == 1.0
        assert h.quantile(1.0) == 4.0

    def test_skewed_distribution(self):
        # 90 fast + 10 slow: p50 interpolates inside the fast bucket,
        # p99 inside the slow one.
        h = Histogram(buckets=(0.1, 1.0, 10.0))
        for _ in range(90):
            h.observe(0.05)
        for _ in range(10):
            h.observe(5.0)
        assert h.quantile(0.5) == (50 / 90) * 0.1
        assert h.quantile(0.99) == 1.0 + 9.0 * ((99 - 90) / 10)

    def test_inf_bucket_clamps_to_top_finite_bound(self):
        h = Histogram(buckets=(1.0, 2.0))
        for _ in range(10):
            h.observe(50.0)  # all land in +Inf
        assert h.quantile(0.99) == 2.0

    def test_registry_snapshot_helper(self):
        m = Metrics()
        for _ in range(100):
            m.histogram("slo_event_to_written_seconds", 0.03, stage="total")
        qs = m.histogram_quantiles(
            "slo_event_to_written_seconds", (0.5, 0.99), stage="total"
        )
        assert qs[0.5] == 0.025 + 0.025 * 0.5
        assert m.histogram_count(
            "slo_event_to_written_seconds", stage="total"
        ) == 100
        # Missing series: all-None, zero count.
        missing = m.histogram_quantiles("nope", (0.5,), stage="x")
        assert missing[0.5] is None
        assert m.histogram_count("nope") == 0


class TestCatalog:
    def test_new_vocabulary_is_cataloged(self):
        for name in (
            "worker_reconciles_total",
            "engine_tick_stage_seconds",
            "engine_compile_cache_total",
        ):
            assert name in CATALOG

    def test_legacy_patterns_cover_dotted_names(self):
        assert is_cataloged("scheduler-deployments.apps.scheduled")
        assert is_cataloged("monitor.deployments.apps.sync_latency")
        assert is_cataloged("sync-x.plan_panic")
        assert not is_cataloged("made_up_metric_total")

    def test_snapshot_shares_vocabulary(self):
        m = Metrics()
        m.counter("engine_ticks_total")
        m.histogram("engine_tick_seconds", 0.5)
        snap = m.snapshot()
        assert snap["counters"]["engine_ticks_total"] == 1
        assert snap["histograms"]["engine_tick_seconds"]["count"] == 1
