"""Decision-audit differential tests: the reason bitmask carried by
TickOutputs.reasons must agree BIT-EXACTLY with the sequential oracle's
per-filter rejection reasons (ops/pipeline_oracle.explain_one) on
seeded rounds, and the flight recorder must explain every infeasible
(object, cluster) pair."""

import numpy as np
import pytest

from test_pipeline import R, random_problem, to_tick_inputs

from kubeadmiral_tpu.ops import pipeline as dev
from kubeadmiral_tpu.ops import reasons as RSN
from kubeadmiral_tpu.ops.pipeline_oracle import explain_one, schedule_one


def seeded_problems(seed, c, n=80):
    rng = np.random.default_rng(seed)
    names = [f"member-{j}" for j in range(c)]
    shared_alloc = [[int(x) for x in rng.integers(5, 50, R)] for _ in range(c)]
    shared_used = [[int(x) for x in rng.integers(0, 40, R)] for _ in range(c)]
    shared_cpu_a = [int(x) for x in rng.integers(0, 30, c)]
    shared_cpu_v = [int(x) for x in rng.integers(-3, 25, c)]
    problems = []
    for i in range(n):
        p = random_problem(rng, c, f"ns-{i}/workload-{i}", names)
        p.alloc, p.used = shared_alloc, shared_used
        p.cpu_alloc, p.cpu_avail = shared_cpu_a, shared_cpu_v
        problems.append(p)
    return problems


@pytest.mark.parametrize("c", [3, 8, 19])
def test_reasons_match_oracle_bit_exactly(c):
    problems = seeded_problems(1000 + c, c)
    out = dev.schedule_tick(to_tick_inputs(problems, c))
    reasons = np.asarray(out.reasons)
    selected = np.asarray(out.selected)

    for i, p in enumerate(problems):
        want = explain_one(p)
        got = reasons[i].tolist()
        assert got == want, (
            f"case {i}: reasons {got} != oracle {want}\n{p}\n"
            f"selected={selected[i].tolist()}"
        )
        # The invariant the flight recorder serves: mask 0 exactly on
        # the selected clusters.
        for j in range(c):
            assert (got[j] == 0) == bool(selected[i, j]), (i, j, got[j])


@pytest.mark.parametrize("c", [3, 8, 19])
def test_every_infeasible_pair_names_its_rejector(c):
    """Acceptance: for every infeasible (object, cluster) pair the mask
    names the rejecting filter (or the select-stage cut), and the slugs
    stay inside the cataloged vocabulary."""
    problems = seeded_problems(2000 + c, c)
    out = dev.schedule_tick(to_tick_inputs(problems, c))
    reasons = np.asarray(out.reasons)
    feasible = np.asarray(out.feasible)

    for i, p in enumerate(problems):
        placed = set(schedule_one(p))
        for j in range(c):
            mask = int(reasons[i, j])
            slugs = RSN.describe(mask)
            if j in placed:
                assert mask == 0, (i, j, slugs)
                continue
            assert slugs, f"case {i} cluster {j}: unexplained rejection"
            assert set(slugs) <= set(RSN.REASON_NAMES.values())
            if not feasible[i, j] and not (mask & RSN.REASON_STICKY):
                # Infeasible pairs must carry a FILTER-stage reason.
                assert mask & RSN.FILTER_REASON_MASK, (i, j, slugs)


def test_reasons_cover_select_and_replica_cuts():
    """Deterministic corner pins: maxClusters cut, zero-replica drop,
    sticky short-circuit, per-plugin filter bits."""
    c = 4
    names = [f"m-{j}" for j in range(c)]

    def base(**kw):
        p = random_problem(np.random.default_rng(0), c, "ns/base", names)
        p.filter_enabled = [True] * 5
        p.score_enabled = [False] * 5
        p.api_ok = [True] * c
        p.taint_ok_new = [True] * c
        p.taint_ok_cur = [True] * c
        p.selector_ok = [True] * c
        p.placement_ok = [True] * c
        p.placement_has = False
        p.request = [0] * R
        p.taint_counts = [0] * c
        p.affinity_scores = [0] * c
        p.max_clusters = None
        p.mode_divide = False
        p.sticky = False
        p.current = {}
        p.total = 8
        p.weights = {j: 1 for j in range(c)}
        p.min_replicas = {}
        p.max_replicas = {}
        p.capacity = {}
        for k, v in kw.items():
            setattr(p, k, v)
        return p

    cases = [
        # maxClusters=2: two feasible clusters cut by rank.
        base(max_clusters=2),
        # api filter rejects cluster 1.
        base(api_ok=[True, False, True, True]),
        # sticky with current on cluster 0 only.
        base(sticky=True, current={0: 3}, mode_divide=True),
        # Divide with zero total: every selection planner-zeroed.
        base(mode_divide=True, total=0, weights={j: 1 for j in range(c)}),
    ]
    out = dev.schedule_tick(to_tick_inputs(cases, c))
    reasons = np.asarray(out.reasons)

    # maxClusters cut: 2 selected, 2 cut with the max_clusters bit.
    cut = [j for j in range(c) if reasons[0, j] & RSN.REASON_MAX_CLUSTERS]
    assert len(cut) == 2
    # api rejection names the plugin.
    assert reasons[1, 1] & RSN.REASON_API_RESOURCES
    assert reasons[1, 0] == 0
    # sticky: current cluster clean, everything else cut by stickiness.
    assert reasons[2, 0] == 0
    for j in range(1, c):
        assert reasons[2, j] & RSN.REASON_STICKY
    # zero-replica drop.
    assert all(
        reasons[3, j] & RSN.REASON_ZERO_REPLICAS for j in range(c)
    ), reasons[3]
    # All four agree with the oracle bit-exactly.
    for i, p in enumerate(cases):
        assert reasons[i].tolist() == explain_one(p), i


class TestEngineFlightRecorder:
    """Engine-level: records are populated from the existing fetch paths
    and explain() answers for every scheduled object."""

    def _schedule(self, n_units=40, n_clusters=12, seed=7, fetch_format="packed"):
        from test_engine_vs_sequential import random_cluster, random_unit

        from kubeadmiral_tpu.runtime.flightrec import FlightRecorder
        from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

        rng = np.random.default_rng(seed)
        clusters = [random_cluster(rng, j) for j in range(n_clusters)]
        names = [cl.name for cl in clusters]
        units = [random_unit(rng, i, names) for i in range(n_units)]
        rec = FlightRecorder(max_ticks=4, max_bytes=64 << 20, topk=4)
        engine = SchedulerEngine(
            chunk_size=16, min_bucket=8, min_cluster_bucket=8, mesh=None,
            flight_recorder=rec, fetch_format=fetch_format,
        )
        results = engine.schedule(units, clusters)
        return engine, rec, units, clusters, results

    @pytest.mark.parametrize("fetch_format", ["packed", "dense"])
    def test_cold_tick_records_every_object(self, fetch_format):
        engine, rec, units, clusters, results = self._schedule(
            fetch_format=fetch_format
        )
        for su, res in zip(units, results):
            record = rec.lookup(su.key)
            assert record is not None, su.key
            explained = rec.explain(su.key)
            assert explained["placements"] == {
                cl: (None if reps is None else int(reps))
                for cl, reps in res.clusters.items()
            }
            if fetch_format == "dense":
                # Full fidelity: every non-selected cluster names its
                # rejection individually.
                assert set(explained["clusters"]) == {
                    cl.name for cl in clusters
                }
                for name, verdict in explained["clusters"].items():
                    if name in res.clusters:
                        assert verdict["reasons"] == []
                    else:
                        assert verdict["reasons"], (su.key, name, verdict)
            else:
                # Packed: selected clusters individually, everything
                # else summarized under "rejected" by reason slug.
                assert set(explained["clusters"]) == set(res.clusters)
                rejected_total = sum(explained["rejected"].values())
                if len(res.clusters) < len(clusters):
                    assert rejected_total > 0, (su.key, explained)

    def test_churn_rows_get_fresh_records(self):
        from test_engine_vs_sequential import random_unit

        engine, rec, units, clusters, _ = self._schedule()
        names = [cl.name for cl in clusters]
        rng = np.random.default_rng(99)
        units2 = list(units)
        units2[5] = random_unit(rng, 500, names)
        results2 = engine.schedule(units2, clusters)
        record = rec.lookup(units2[5].key)
        assert record is not None
        assert rec.explain(units2[5].key)["placements"] == {
            cl: (None if reps is None else int(reps))
            for cl, reps in results2[5].clusters.items()
        }

    def test_ring_eviction_is_bounded(self):
        from kubeadmiral_tpu.runtime.flightrec import FlightRecorder

        rec = FlightRecorder(max_ticks=2, max_bytes=1 << 30, topk=2)
        names = ("a", "b")
        for tick in range(5):
            rec.begin_tick(1, 2)
            rec.record_rows(
                [f"ns/obj-{tick}"], [{"a": None}],
                np.zeros((1, 2), np.int32), None, names,
            )
            rec.end_tick()
        stats = rec.stats()
        assert stats["ring_ticks"] == 2
        assert rec.lookup("ns/obj-4") is not None
        assert rec.lookup("ns/obj-0") is None  # evicted with its tick

    def test_disabled_recorder_records_nothing(self):
        from kubeadmiral_tpu.runtime.flightrec import FlightRecorder

        rec = FlightRecorder(enabled=False)
        rec.begin_tick(1, 1)
        rec.record_rows(["k"], [{}], np.zeros((1, 1), np.int32), None, ("a",))
        rec.end_tick()
        assert rec.stats()["records"] == 0
