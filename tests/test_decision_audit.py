"""End-to-end decision audit (ISSUE 2 acceptance): after a
membersim-driven reconcile round, /debug/explain returns a populated
record whose chosen clusters match the dispatched placement and
/debug/drift is empty; mutating one member object then reports drift.
Plus: scheduling events on the source object, /debug/decisions, and the
eventsink concurrent count-bump regression."""

import dataclasses
import json
import threading
import urllib.request

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.clusterctl import (
    FEDERATED_CLUSTERS,
    FederatedClusterController,
    NODES,
)
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.monitor import MonitorController
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
from kubeadmiral_tpu.runtime import trace
from kubeadmiral_tpu.runtime.eventsink import EVENTS, EventRecorder
from kubeadmiral_tpu.runtime.flightrec import FlightRecorder
from kubeadmiral_tpu.runtime.healthcheck import HealthCheckRegistry, HealthServer
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine
from kubeadmiral_tpu.testing.fakekube import ClusterFleet, FakeKube
from kubeadmiral_tpu.testing.membersim import DEPLOYMENTS, MemberDeploymentSimulator

from test_e2e_slice import make_deployment, make_node


def fetch(port, path):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestDecisionAuditEndToEnd:
    def setup_method(self):
        trace.get_default().clear()
        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        self.ftc = dataclasses.replace(
            ftc, controllers=(("kubeadmiral.io/global-scheduler",),)
        )
        self.fleet = ClusterFleet()
        self.metrics = Metrics()
        self.flightrec = FlightRecorder(max_ticks=8, max_bytes=64 << 20)
        gvk = "apps/v1/Deployment"
        self.clusterctl = FederatedClusterController(
            self.fleet, api_resource_probe=[gvk], metrics=self.metrics
        )
        self.federate = FederateController(
            self.fleet.host, self.ftc, metrics=self.metrics
        )
        engine = SchedulerEngine(flight_recorder=self.flightrec)
        self.scheduler = SchedulerController(
            self.fleet.host, self.ftc, engine=engine, metrics=self.metrics
        )
        self.scheduler.engine.metrics = self.metrics
        self.sync = SyncController(self.fleet, self.ftc, metrics=self.metrics)
        self.monitor = MonitorController(
            self.fleet.host, self.ftc, metrics=self.metrics, interval=0.0,
            fleet=self.fleet, flight_recorder=self.flightrec,
        )
        self.sim = MemberDeploymentSimulator(self.fleet)
        for name in ("c1", "c2", "c3"):
            member = self.fleet.add_member(name)
            member.create(NODES, make_node("n1", "64", "128Gi"))
            self.fleet.host.create(
                FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": {},
                },
            )
        self.fleet.host.create(
            PROPAGATION_POLICIES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "PropagationPolicy",
                "metadata": {"name": "pp", "namespace": "default"},
                "spec": {"schedulingMode": "Divide"},
            },
        )

    def reconcile_round(self, max_rounds=60):
        controllers = (
            self.clusterctl, self.federate, self.scheduler, self.sync,
            self.monitor,
        )
        for _ in range(max_rounds):
            progressed = False
            for c in controllers:
                progressed |= c.worker.step()
            progressed |= self.sim.step()
            if not progressed:
                return

    def test_explain_matches_dispatch_then_drift_on_mutation(self):
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        self.reconcile_round()
        fed = self.fleet.host.get(self.ftc.federated.resource, "default/web")
        placed = C.get_placement(fed, C.SCHEDULER)
        assert placed == {"c1", "c2", "c3"}

        # The flight recorder holds the decision for the scheduled key;
        # its chosen clusters match the persisted/dispatched placement.
        registry = HealthCheckRegistry()
        server = HealthServer(
            registry, metrics=self.metrics, flightrec=self.flightrec,
            drift=None,
        )
        port = server.start()
        try:
            status, body = fetch(port, "/debug/explain?key=default/web")
            assert status == 200, body
            doc = json.loads(body)
            assert set(doc["placements"]) == placed
            # Divide mode: the replica split is recorded per cluster.
            assert sum(doc["placements"].values()) == 9
            for name, verdict in doc["clusters"].items():
                assert (verdict["reasons"] == []) == (name in placed)
            # And each member actually holds its dispatched object.
            for name in placed:
                member_obj = self.fleet.members[name].try_get(
                    DEPLOYMENTS, "default/web"
                )
                assert member_obj is not None
                assert member_obj["spec"]["replicas"] == doc["placements"][name]

            # /debug/decisions shows the recording tick.
            status, body = fetch(port, "/debug/decisions")
            assert status == 200
            decisions = json.loads(body)
            assert decisions["records"] >= 1
            assert any(t["recorded_rows"] >= 1 for t in decisions["ticks"])

            # Unknown keys 404.
            status, _ = fetch(port, "/debug/explain?key=default/nope")
            assert status == 404
            status, _ = fetch(port, "/debug/explain")
            assert status == 400

            # Converged state: no drift (the monitor registered itself
            # as the drift provider at construction).
            self.monitor._report()
            status, body = fetch(port, "/debug/drift")
            assert status == 200
            drift = json.loads(body)
            assert f"monitor-{self.ftc.name}" in drift["providers"]
            assert drift["drifted_total"] == 0, drift
            series = self.metrics.stores.get(
                "placement_drift_objects{ftc=deployments.apps,kind=missing}"
            )
            assert series == 0

            # Mutate ONE member object: drift must be reported.
            self.fleet.members["c1"].delete(DEPLOYMENTS, "default/web")
            self.monitor._report()
            status, body = fetch(port, "/debug/drift")
            drift = json.loads(body)
            assert drift["drifted_total"] == 1, drift
            entry = drift["drifted"][0]
            assert entry == {
                "key": "default/web", "cluster": "c1", "kind": "missing",
                "detail": "desired placement not present in member",
            }
            assert self.metrics.stores[
                "placement_drift_objects{ftc=deployments.apps,kind=missing}"
            ] == 1
        finally:
            server.stop()

    def test_scheduled_event_reaches_source_object(self):
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        self.reconcile_round()
        events = list(self.fleet.host.list(EVENTS))
        scheduled = [e for e in events if e.get("reason") == "Scheduled"]
        assert scheduled, [e.get("reason") for e in events]
        # The defederating mux records on the federated object AND the
        # de-federated source, so `kubectl describe deployment` shows it.
        kinds = {e["involvedObject"]["kind"] for e in scheduled}
        assert "Deployment" in kinds, kinds
        msg = scheduled[0]["message"]
        assert "scheduled to 3 cluster(s)" in msg
        for cl in ("c1", "c2", "c3"):
            assert cl in msg


class TestEventSinkConcurrency:
    def test_concurrent_count_bumps_are_not_dropped(self):
        """Regression: the Conflict path used to drop the bump; with the
        bounded retry loop N concurrent recorders produce an exact
        count."""
        host = FakeKube("host")
        obj = {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "default"},
        }
        recorders = [EventRecorder(host, f"worker-{i}") for i in range(8)]
        per_thread = 25
        barrier = threading.Barrier(len(recorders))

        def hammer(rec):
            barrier.wait()
            for _ in range(per_thread):
                rec.event(obj, "Normal", "Scheduled", "same message")

        threads = [
            threading.Thread(target=hammer, args=(rec,)) for rec in recorders
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        events = list(host.list(EVENTS))
        assert len(events) == 1
        assert events[0]["count"] == len(recorders) * per_thread
