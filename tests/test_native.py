"""Native C++ hashing library: bit parity with the pure-Python (and
Go-compatible) implementations."""

import numpy as np
import pytest

from kubeadmiral_tpu import native
from kubeadmiral_tpu.utils import hashing


def _pure_fnv32(data: bytes) -> int:
    h = 2166136261
    for b in data:
        h = ((h * 16777619) & 0xFFFFFFFF) ^ b
    return h


def _pure_fnv32a(data: bytes) -> int:
    h = 2166136261
    for b in data:
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


@pytest.fixture(scope="module")
def lib():
    lib = native.load()
    if lib is None:
        pytest.skip("native library unavailable (no compiler)")
    return lib


CASES = [b"", b"a", b"hello", b"cluster-1/default/web", bytes(range(256)) * 7]


class TestNativeParity:
    def test_fnv32_matches_pure(self, lib):
        for data in CASES:
            assert lib.kadm_fnv32(data, len(data)) == _pure_fnv32(data)

    def test_fnv32a_matches_pure(self, lib):
        for data in CASES:
            assert lib.kadm_fnv32a(data, len(data)) == _pure_fnv32a(data)

    def test_go_reference_vectors(self, lib):
        # Known FNV vectors (matching Go's hash/fnv): fnv32("a"), fnv32a("a").
        assert lib.kadm_fnv32(b"a", 1) == 0x050C5D7E
        assert lib.kadm_fnv32a(b"a", 1) == 0xE40C292C

    def test_batch_matches_scalar(self, lib):
        prefixes = [f"member-{i:04d}" for i in range(257)]
        out = hashing.fnv32_batch(prefixes, "default/web")
        expected = np.array(
            [_pure_fnv32((p + "default/web").encode()) for p in prefixes],
            dtype=np.uint32,
        )
        np.testing.assert_array_equal(out, expected)

    def test_extend_matches_streaming_property(self, lib):
        prefixes = ["c1", "longer-cluster-name", ""]
        states = np.array(
            [_pure_fnv32(p.encode()) for p in prefixes], dtype=np.uint32
        )
        out = hashing.fnv32_extend(states, b"/suffix")
        expected = np.array(
            [_pure_fnv32((p + "/suffix").encode()) for p in prefixes],
            dtype=np.uint32,
        )
        np.testing.assert_array_equal(out, expected)

    def test_stable_json_hash_unchanged_by_native(self, lib):
        # The canonical encoding is Python's; only the byte loop is
        # native — the resulting hashes must be identical either way.
        value = {"b": [1, 2, {"x": None}], "a": "str", "s": (3, 1)}
        assert hashing.stable_json_hash(value) == hashing.fnv32a(
            b'{"a":"str","b":[1,2,{"x":null}],"s":[3,1]}'
        )
