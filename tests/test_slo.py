"""End-to-end SLO layer (runtime/slo.py; ISSUE 13).

Covers the provenance-token lifecycle (mint/mark/expect/written/settle,
generation-gated echo suppression, exact stage decomposition), the
freshness gauges, the multi-window burn-rate evaluator, the /debug/slo
surface, a full membersim round (decomposition sums to the measured
end-to-end latency per event), and the chaos acceptance: a hard-down
member under the kwok-lite farm makes the freshness gauge rise and the
burn rate flip red, with per-member write attribution separating the
sick member from healthy ones, then recover green after the fault
clears.
"""

import dataclasses
import json
import threading
import time
import urllib.request

import pytest

from test_e2e_slice import make_deployment, make_node

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.clusterctl import (
    FEDERATED_CLUSTERS,
    FederatedClusterController,
    NODES,
)
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
from kubeadmiral_tpu.runtime import slo
from kubeadmiral_tpu.runtime.healthcheck import HealthCheckRegistry, HealthServer
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.testing.fakekube import ClusterFleet, FakeKube
from kubeadmiral_tpu.transport import breaker as B
from kubeadmiral_tpu.transport.faults import FaultPolicy


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def fresh_default():
    """Install a fresh default recorder for the test, restore after —
    the process default accumulates tracked stores across the suite."""
    rec = slo.SLORecorder(enabled=True)
    prev = slo.set_default(rec)
    try:
        yield rec
    finally:
        if prev is not None:
            slo.set_default(prev)


def _meta(name, gen=None, ns="default"):
    meta = {"namespace": ns, "name": name}
    if gen is not None:
        meta["generation"] = gen
    return {"metadata": meta}


# -- recorder unit tests ---------------------------------------------------
class TestProvenanceToken:
    def setup_method(self):
        self.clock = FakeClock()
        self.rec = slo.SLORecorder(
            enabled=True, clock=self.clock, windows=(1.0, 3.0)
        )
        self.store = FakeKube("host")
        self.rec.track(self.store, "apps/v1/deployments")

    def ingest(self, event, obj):
        self.rec.ingest(self.store, "apps/v1/deployments", event, obj)

    def test_decomposition_sums_exactly_to_total(self):
        self.ingest("ADDED", _meta("web", gen=1))
        bounds = {}
        for stage in ("queued", "slab", "engine", "fetch", "dispatch"):
            self.clock.advance(0.1)
            self.rec.mark("default/web", stage)
            bounds[stage] = self.clock.t
        self.rec.expect("default/web", {"c1", "c2"})
        self.clock.advance(0.2)
        self.rec.written("default/web", "c1")
        assert self.rec.pending_count() == 1  # c2 still unacked
        self.clock.advance(0.3)
        self.rec.written("default/web", "c2")
        assert self.rec.pending_count() == 0
        summary = self.rec.summary()
        (exemplar,) = summary["slowest"]
        assert exemplar["key"] == "default/web"
        assert exemplar["total_s"] == pytest.approx(1.0)
        assert sum(exemplar["stages_s"].values()) == pytest.approx(
            exemplar["total_s"]
        )
        # Each marked stage closed a 0.1s interval; write closed the
        # 0.5s ack tail.
        for stage in ("queued", "slab", "engine", "fetch", "dispatch"):
            assert exemplar["stages_s"][stage] == pytest.approx(0.1)
        assert exemplar["stages_s"]["write"] == pytest.approx(0.5)
        # Histograms observed every stage plus the total.
        for stage in slo.STAGES + ("total",):
            assert (
                self.rec.metrics.histogram_count(
                    "slo_event_to_written_seconds", stage=stage
                )
                == 1
            )

    def test_generation_gating_suppresses_own_write_echoes(self):
        self.ingest("ADDED", _meta("web", gen=1))
        assert self.rec.pending_count() == 1
        self.rec.written("default/web", "c1")  # no expect: first ack closes
        assert self.rec.pending_count() == 0
        # Finalizer/annotation/status echoes keep generation 1: no token.
        self.ingest("MODIFIED", _meta("web", gen=1))
        assert self.rec.pending_count() == 0
        assert (
            self.rec.metrics.get_counter("slo_events_total", result="echo")
            == 1
        )
        # A real spec change bumps generation: new token.
        self.ingest("MODIFIED", _meta("web", gen=2))
        assert self.rec.pending_count() == 1

    def test_delete_forgets_and_rearms_generation(self):
        self.ingest("ADDED", _meta("web", gen=1))
        self.ingest("DELETED", _meta("web", gen=1))
        assert self.rec.pending_count() == 0
        assert (
            self.rec.metrics.get_counter(
                "slo_events_total", result="forgotten"
            )
            == 1
        )
        # Re-creation at generation 1 mints again (the gen memory reset).
        self.ingest("ADDED", _meta("web", gen=1))
        assert self.rec.pending_count() == 1

    def test_settle_emits_partial_acks_and_drops_noops(self):
        # Partial ack + version-skips: the sample must not be lost.
        self.ingest("ADDED", _meta("a", gen=1))
        self.rec.expect("default/a", {"c1", "c2"})
        self.clock.advance(0.4)
        self.rec.written("default/a", "c1")
        self.rec.settle("default/a")
        assert self.rec.pending_count() == 0
        assert (
            self.rec.metrics.histogram_count(
                "slo_event_to_written_seconds", stage="total"
            )
            == 1
        )
        # Pure no-op round: dropped without a sample.
        self.ingest("ADDED", _meta("b", gen=1))
        self.rec.settle("default/b")
        assert self.rec.pending_count() == 0
        assert (
            self.rec.metrics.histogram_count(
                "slo_event_to_written_seconds", stage="total"
            )
            == 1
        )
        assert (
            self.rec.metrics.get_counter("slo_events_total", result="settled")
            == 1
        )

    def test_untracked_stores_and_resources_mint_nothing(self):
        other = FakeKube("member")
        self.rec.ingest(other, "apps/v1/deployments", "ADDED", _meta("web"))
        self.rec.ingest(self.store, "v1/configmaps", "ADDED", _meta("cm"))
        assert self.rec.pending_count() == 0

    def test_freshness_counts_unacked_placements(self):
        self.ingest("ADDED", _meta("a", gen=1))
        self.clock.advance(1.0)
        self.ingest("ADDED", _meta("b", gen=1))
        self.rec.expect("default/a", {"c1", "c2", "c3"})
        self.rec.written("default/a", "c1")
        assert self.rec.unwritten_placements() == 2 + 1  # a: 2 left, b: 1
        assert self.rec.oldest_pending_seconds() == pytest.approx(1.0)
        m = Metrics()
        self.rec.publish(extra=m)
        assert m.stores["slo_oldest_pending_event_seconds"] == pytest.approx(
            1.0
        )
        assert m.stores["slo_unwritten_placements"] == 3

    def test_disabled_recorder_is_inert(self):
        rec = slo.SLORecorder(enabled=False, clock=self.clock)
        rec.track(self.store, "apps/v1/deployments")
        rec.ingest(self.store, "apps/v1/deployments", "ADDED", _meta("web"))
        rec.mark("default/web", "queued")
        rec.written("default/web", "c1")
        assert rec.pending_count() == 0
        assert rec.summary() == {"enabled": False}

    def test_exemplar_ring_keeps_slowest_n(self):
        rec = slo.SLORecorder(enabled=True, clock=self.clock, exemplars=3)
        for i, dt in enumerate((0.1, 0.9, 0.3, 0.7, 0.5)):
            key = f"default/o{i}"
            rec.mint(key)
            self.clock.advance(dt)
            rec.written(key, "c1")
        slowest = rec.summary()["slowest"]
        assert [e["total_s"] for e in slowest] == pytest.approx(
            [0.9, 0.7, 0.5]
        )


class TestBurnRateEvaluator:
    def test_ratio_objective_red_then_green(self):
        clock = FakeClock()
        ev = slo.SLOEvaluator(clock=clock, windows=(1.0, 3.0))
        threshold = ev.thresholds["event_to_written_p99"]
        # A burst of threshold breaches: both windows burn hot → red.
        for _ in range(10):
            ev.observe("event_to_written_p99", threshold + 1.0)
            clock.advance(0.1)
            ev.evaluate()
        status = ev.status()["event_to_written_p99"]
        assert status["red"], status
        assert all(b >= 1.0 for b in status["burn"].values())
        # Healthy traffic after the burst: the fast window clears first
        # (multi-window semantics), then the slow one.
        for _ in range(40):
            ev.observe("event_to_written_p99", 0.001)
            clock.advance(0.2)
            ev.evaluate()
        status = ev.status()["event_to_written_p99"]
        assert not status["red"], status

    def test_gauge_objective_tracks_freshness(self):
        clock = FakeClock()
        ev = slo.SLOEvaluator(clock=clock, windows=(1.0, 3.0))
        threshold = ev.thresholds["freshness"]
        ev.sample_gauge("freshness", threshold * 2)
        status = ev.evaluate()["freshness"]
        assert status["red"]
        assert status["burn"]["1s"] == pytest.approx(2.0)
        # Recovery: the windowed max holds red until the breach ages out.
        ev.sample_gauge("freshness", 0.0)
        clock.advance(0.5)
        status = ev.evaluate()["freshness"]
        assert status["red"]  # breach still inside both windows
        clock.advance(4.0)
        status = ev.evaluate()["freshness"]
        assert not status["red"]

    def test_objectives_match_catalog(self):
        from kubeadmiral_tpu.runtime import metric_catalog as MC

        ev = slo.SLOEvaluator()
        assert set(ev.objectives) == set(MC.SLO_OBJECTIVES)
        assert tuple(slo.STAGES) == MC.SLO_STAGES


# -- membersim integration -------------------------------------------------
class TestSLOEndToEnd:
    """A full reconcile round closes every token, the decomposition sums
    to the measured end-to-end latency per event (ISSUE 13 acceptance:
    within 10%; exact by construction), and /debug/slo serves it."""

    def _build(self, fresh_default):
        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        self.ftc = dataclasses.replace(
            ftc, controllers=(("kubeadmiral.io/global-scheduler",),)
        )
        self.fleet = ClusterFleet()
        self.metrics = Metrics()
        fresh_default.attach(self.metrics)
        gvk = "apps/v1/Deployment"
        self.controllers = [
            FederatedClusterController(
                self.fleet, api_resource_probe=[gvk], metrics=self.metrics
            ),
            FederateController(self.fleet.host, self.ftc, metrics=self.metrics),
            SchedulerController(self.fleet.host, self.ftc, metrics=self.metrics),
            SyncController(self.fleet, self.ftc, metrics=self.metrics),
        ]
        for name in ("c1", "c2", "c3"):
            member = self.fleet.add_member(name)
            member.create(NODES, make_node("n1", "64", "128Gi"))
            self.fleet.host.create(
                FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": {},
                },
            )
        self.fleet.host.create(
            PROPAGATION_POLICIES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "PropagationPolicy",
                "metadata": {"name": "pp", "namespace": "default"},
                "spec": {"schedulingMode": "Divide"},
            },
        )
        self._settle()

    def _settle(self, max_rounds=200):
        for _ in range(max_rounds):
            if not any(c.worker.step() for c in self.controllers):
                return

    def test_round_closes_tokens_with_exact_decomposition(
        self, fresh_default
    ):
        self._build(fresh_default)
        for i in range(5):
            self.fleet.host.create(
                self.ftc.source.resource,
                make_deployment(name=f"app-{i}", replicas=2 + i),
            )
        self._settle()
        rec = fresh_default
        assert rec.pending_count() == 0, "tokens left pending after a round"
        assert rec.unwritten_placements() == 0
        summary = rec.summary()
        total = summary["stages"]["total"]
        assert total["count"] == 5
        assert summary["slowest"], "no exemplars retained"
        for exemplar in summary["slowest"]:
            stage_sum = sum(exemplar["stages_s"].values())
            assert stage_sum == pytest.approx(
                exemplar["total_s"], rel=0.10, abs=1e-6
            )
            # The pipeline stages all closed: the decomposition is real,
            # not one undifferentiated "write" bucket.
            for stage in ("queued", "slab", "engine", "fetch", "dispatch"):
                assert stage in exemplar["stages_s"], exemplar
            assert exemplar["acked"], exemplar
        # member_write_seconds carries per-member attribution.
        assert any(
            rec.metrics.histogram_count("member_write_seconds", cluster=c)
            for c in ("c1", "c2", "c3")
        )

    def test_debug_slo_and_metrics_exposition(self, fresh_default):
        self._build(fresh_default)
        self.fleet.host.create(
            self.ftc.source.resource, make_deployment(name="web", replicas=3)
        )
        self._settle()
        registry = HealthCheckRegistry()
        server = HealthServer(
            registry, metrics=self.metrics, slo=fresh_default
        )
        port = server.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/slo", timeout=10
            ) as r:
                doc = json.loads(r.read())
            assert doc["enabled"] is True
            assert doc["stages"]["total"]["count"] >= 1
            assert set(doc["objectives"]) == {
                "event_to_written_p99", "member_write_p99", "freshness",
            }
            assert doc["red"] == []
            assert doc["slowest"][0]["stages_s"]
            # The shared registry exposition carries the SLO families
            # (recorder attached + monitor-style publish).
            fresh_default.evaluate(extra=self.metrics)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ) as r:
                text = r.read().decode()
            assert "slo_event_to_written_seconds_bucket" in text
            assert "slo_oldest_pending_event_seconds" in text
            assert 'slo_burn_rate{objective="freshness"' in text
            assert "member_write_seconds_bucket" in text
        finally:
            server.stop()


# -- chaos: the fault-injection acceptance ---------------------------------
def _settle(named, deadline_s=20.0, idle_rounds=3):
    deadline = time.monotonic() + deadline_s
    idle = 0
    while time.monotonic() < deadline and idle < idle_rounds:
        progressed = False
        for _, ctl in named:
            while ctl.worker.step():
                progressed = True
        if progressed:
            idle = 0
        else:
            idle += 1
            time.sleep(0.03)


class TestSLOUnderChaos:
    """ISSUE 13 acceptance: during a hard-down member window the
    freshness gauges rise and the burn rate flips red; shed writes show
    in the sick member's attribution while healthy members keep serving
    write latencies; after recovery the gauges drop and the burn flips
    back green."""

    N_MEMBERS = 4
    N_OBJECTS = 6

    def test_freshness_and_burn_flip_red_then_green(self, monkeypatch):
        monkeypatch.setenv("KT_DISPATCH_DEADLINE_S", "1.0")
        monkeypatch.setenv("KT_BREAKER_OPEN_S", "2.0")
        monkeypatch.setenv("KT_BREAKER_STALL_S", "0.4")
        monkeypatch.setenv("KT_BREAKER_FAILURES", "2")
        monkeypatch.setenv("KT_RETRY_BASE_S", "0.02")
        monkeypatch.setenv("KT_RETRY_CAP_S", "0.05")
        monkeypatch.setenv("KT_RETRY_MAX", "1")
        monkeypatch.setenv("KT_SLO_FRESHNESS_S", "0.5")
        monkeypatch.setenv("KT_SLO_WINDOWS_S", "1,4")

        from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm

        rec = slo.SLORecorder(enabled=True)
        prev = slo.set_default(rec)
        ftc = dataclasses.replace(
            next(f for f in default_ftcs() if f.name == "deployments.apps"),
            controllers=(("kubeadmiral.io/global-scheduler",),),
        )
        farm = KwokLiteFarm()
        farm.fleet.factory.timeout = 0.6
        fleet = farm.fleet
        try:
            for i in range(self.N_MEMBERS):
                name = f"m{i}"
                member = farm.add_member(name)
                member.create(NODES, make_node("n1", "64", "128Gi"))
                fleet.host.create(
                    FEDERATED_CLUSTERS,
                    {"apiVersion": "core.kubeadmiral.io/v1alpha1",
                     "kind": "FederatedCluster",
                     "metadata": {"name": name},
                     "spec": farm.cluster_spec(name)},
                )
            fleet.host.create(
                PROPAGATION_POLICIES,
                {"apiVersion": "core.kubeadmiral.io/v1alpha1",
                 "kind": "PropagationPolicy",
                 "metadata": {"name": "pp", "namespace": "default"},
                 "spec": {"schedulingMode": "Divide"}},
            )
            named = [
                ("cluster", FederatedClusterController(
                    fleet, api_resource_probe=["apps/v1/Deployment"],
                    resync_seconds=2.0,
                )),
                ("federate", FederateController(fleet.host, ftc)),
                ("schedule", SchedulerController(fleet.host, ftc)),
                ("sync", SyncController(fleet, ftc)),
            ]
            clusterctl = named[0][1]
            sync = named[-1][1]
            _settle(named)

            for i in range(self.N_OBJECTS):
                fleet.host.create(
                    ftc.source.resource,
                    make_deployment(name=f"app-{i}", replicas=3 + i),
                )
            _settle(named)
            # Converged baseline: every token closed, freshness flat.
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline and rec.pending_count():
                _settle(named, deadline_s=5.0)
                time.sleep(0.1)
            assert rec.pending_count() == 0, "baseline never converged"
            assert not rec.evaluate()["freshness"]["red"]

            placements = {}
            for key in fleet.host.keys(ftc.federated.resource):
                fed = fleet.host.get(ftc.federated.resource, key)
                placements[key] = set(C.get_placement(fed, C.SCHEDULER))
            down = sorted({c for p in placements.values() for c in p})[0]
            down_keys = [k for k, p in placements.items() if down in p]
            assert down_keys

            # -- hard-down window ----------------------------------------
            farm.set_fault(down, FaultPolicy(partition=True))
            breaker = B.for_fleet(fleet).for_member(down)
            registry = B.for_fleet(fleet)

            # Churn the down member's objects: new tokens whose expected
            # placements include the dead member.
            for key in down_keys:
                obj = fleet.host.get(ftc.source.resource, key)
                obj["spec"]["replicas"] = obj["spec"].get("replicas", 1) + 1
                fleet.host.update(ftc.source.resource, obj)

            went_red = False
            peak = 0.0
            deadline = time.monotonic() + 25.0
            while time.monotonic() < deadline:
                sync.worker.enqueue_all(
                    fleet.host.keys(ftc.federated.resource)
                )
                _settle(named, deadline_s=4.0)
                status = rec.evaluate()
                peak = max(peak, rec.oldest_pending_seconds())
                if status["freshness"]["red"] and peak > 0.5:
                    went_red = True
                    break
                time.sleep(0.2)
            assert went_red, (
                f"freshness never flipped red (peak {peak:.2f}s, "
                f"status {rec.evaluate()['freshness']})"
            )
            assert rec.unwritten_placements() > 0
            assert breaker.state != B.CLOSED

            # Per-member attribution separates sick from healthy: the
            # down member shed writes; healthy members kept serving
            # (write-latency reservoirs populated, nothing shed).
            snapshot = registry.snapshot()
            assert snapshot[down]["shed_writes"] > 0
            healthy = [n for n in snapshot if n != down]
            assert any(
                snapshot[n].get("write_latency", {}).get("flushes", 0) > 0
                for n in healthy
            ), snapshot
            assert rec.metrics.histogram_count(
                "member_write_seconds",
                cluster=[n for n in healthy
                         if snapshot[n].get("write_latency")][0],
            ) > 0

            # -- recovery ------------------------------------------------
            farm.clear_fault(down)
            deadline = time.monotonic() + 25.0
            while time.monotonic() < deadline and breaker.state != B.CLOSED:
                clusterctl.worker.enqueue(down)  # heartbeat = probe
                while clusterctl.worker.step():
                    pass
                time.sleep(0.2)
            assert breaker.state == B.CLOSED

            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline and rec.unwritten_placements():
                sync.worker.enqueue_all(
                    fleet.host.keys(ftc.federated.resource)
                )
                _settle(named, deadline_s=5.0)
                time.sleep(0.2)
            assert rec.unwritten_placements() == 0, (
                "shed writes never converged after recovery"
            )
            assert rec.oldest_pending_seconds() < 0.5

            # The gauge recovered; the windowed burn drains back green.
            deadline = time.monotonic() + 15.0
            green = False
            while time.monotonic() < deadline:
                if not rec.evaluate()["freshness"]["red"]:
                    green = True
                    break
                time.sleep(0.3)
            assert green, rec.evaluate()["freshness"]
        finally:
            farm.close()
            slo.set_default(prev or slo.SLORecorder())


# -- streaming bucket regression (ISSUE 13 satellite) ----------------------
class TestStreamStageBuckets:
    def test_seconds_scale_queued_stage_lands_in_finite_bucket(self):
        """The queued stage can legitimately reach seconds under
        slab-age coalescing: a 2s (and even a 30s) observation must land
        in a finite bucket, not saturate +Inf."""
        from kubeadmiral_tpu.scheduler.streaming import STREAM_STAGE_BUCKETS

        m = Metrics()
        for value in (2.0, 30.0):
            m.histogram(
                "engine_stream_stage_seconds", value,
                buckets=STREAM_STAGE_BUCKETS, stage="queued",
            )
        hist = m.histograms['engine_stream_stage_seconds{stage=queued}']
        assert hist.counts[-1] == 0, "observation saturated the +Inf bucket"
        assert hist.count == 2
        # And the quantile estimate stays finite/meaningful.
        assert hist.quantile(0.99) <= STREAM_STAGE_BUCKETS[-1]

    def test_streaming_flush_emits_rebucketed_family(self):
        """The live streaming path emits its stage family with the
        extended ladder (a 2s-old event's queued observation is finite)
        and closes slab/engine marks on pending tokens."""
        from kubeadmiral_tpu.models import types as T
        from kubeadmiral_tpu.scheduler.streaming import StreamingScheduler

        class _Engine:
            metrics = None
            tick_seq = 0
            last_tick_id = 0

            def schedule(self, units, clusters, **kw):
                self.tick_seq += 1
                return [None] * len(units)

        clock = FakeClock()
        metrics = Metrics()
        rec = slo.SLORecorder(enabled=True, clock=clock)
        prev = slo.set_default(rec)
        try:
            s = StreamingScheduler(
                _Engine(), clusters=[], units=[], slab_rows=64,
                slab_age_ms=1.0, grow_block=8, metrics=metrics, clock=clock,
            )
            unit = T.SchedulingUnit(
                gvk="apps/v1/Deployment", namespace="default", name="web",
                scheduling_mode=T.MODE_DUPLICATE,
            )
            rec.mint(unit.key)
            s.offer(unit)
            clock.advance(2.0)  # the event coalesces for 2s
            s.flush()
            hist = metrics.histograms[
                "engine_stream_stage_seconds{stage=queued}"
            ]
            assert hist.count == 1
            assert hist.counts[-1] == 0, "2s queued saturated +Inf"
            # The token's slab/engine stages closed in the flush.
            entry = rec._pending[unit.key]
            assert {s_ for s_, _ in entry.marks} == {"slab", "engine"}
        finally:
            slo.set_default(prev or slo.SLORecorder())
