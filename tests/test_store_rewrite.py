"""Store/notify hot-path rewrite (ISSUE 18): COW version nodes, columnar
batch commits, coalesced watch fan-out.

Covers:

* copy-on-write semantics — retained views survive later writes
  unchanged, unchanged subtrees are shared by reference between
  version nodes, delivered events ARE the stored nodes (no snapshot
  copy);
* columnar ``batch``: event ordering across interleaved
  create/update/delete, per-op rv allocation identical to the per-op
  loop, error isolation mid-chunk;
* the coalesced delivery protocol — ``kt_batch`` watchers get ONE call
  per committed flush, ``kt_predicate`` filters batch-wise, replay
  batches, ``watch_all`` batch observers, ``_NamedHandler`` keeps
  ``unwatch_owner`` working;
* KT_STORE_COALESCE=0 A/B: the per-op baseline and the columnar path
  produce BIT-identical watch streams (rv and uid included), per-op
  results, and final store dumps — and a full sync world propagates
  bit-identical member objects and statuses in both modes;
* echo suppression holds under batched delivery (sync's own flushes do
  not re-enqueue; foreign batched writes do);
* the SLO stage decomposition stays exact (sums to total within 10%)
  in both modes;
* the store's ``_shared_fields_`` lock-discipline declaration is live:
  the suite-wide lockcheck guard stays clean through batched commits
  and flags unguarded rebinds.
"""

from __future__ import annotations

import dataclasses
import json
import threading

import pytest

from test_e2e_slice import make_deployment, make_node

from kubeadmiral_tpu.federation.clusterctl import (
    FEDERATED_CLUSTERS,
    FederatedClusterController,
    NODES,
)
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
from kubeadmiral_tpu.runtime import lockcheck, slo
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.testing.fakekube import (
    ADDED,
    DELETED,
    MODIFIED,
    ClusterFleet,
    FakeKube,
)


def _mkobj(name, replicas=1, ns="default", **meta):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": ns, **meta},
        "spec": {"replicas": replicas},
    }


RES = "apps/v1/deployments"


class _Recorder:
    """Per-event watcher that freezes each delivered object."""

    def __init__(self):
        self.events: list[tuple[str, str]] = []

    def __call__(self, event, obj):
        self.events.append((event, json.dumps(obj, sort_keys=True)))


class _BatchRecorder:
    """Coalesced watcher: records one entry per flush.  Direct verbs
    (no flush) legitimately use the per-event callable — recorded
    separately so tests can assert which path delivered."""

    def __init__(self, predicate=None):
        self.flushes: list[list[tuple[str, str]]] = []
        self.direct: list[tuple[str, str]] = []
        if predicate is not None:
            self.kt_predicate = predicate

    def __call__(self, event, obj):
        self.direct.append((event, json.dumps(obj, sort_keys=True)))

    def kt_batch(self, events):
        self.flushes.append(
            [(e, json.dumps(o, sort_keys=True)) for e, o in events]
        )


# -- COW semantics ---------------------------------------------------------
class TestCopyOnWrite:
    def test_retained_view_survives_later_writes(self):
        k = FakeKube("m")
        k.create(RES, _mkobj("a", replicas=1))
        view = k.try_get_view(RES, "default/a")
        frozen = json.dumps(view, sort_keys=True)
        k.update(RES, _mkobj("a", replicas=9))
        k.update_status(RES, {"metadata": {"name": "a", "namespace": "default"},
                              "status": {"ready": 9}})
        k.delete(RES, "default/a")
        # The retained node never moved underneath us.
        assert json.dumps(view, sort_keys=True) == frozen
        assert view["spec"]["replicas"] == 1

    def test_metadata_only_update_shares_spec_subtree(self):
        k = FakeKube("m")
        k.create(RES, _mkobj("a", replicas=3))
        before = k.try_get_view(RES, "default/a")
        obj = k.get(RES, "default/a")
        obj["metadata"]["labels"] = {"tier": "web"}
        k.update(RES, obj)
        after = k.try_get_view(RES, "default/a")
        assert after is not before
        assert after["spec"] is before["spec"]  # structural sharing
        assert after["metadata"]["generation"] == before["metadata"]["generation"]

    def test_status_update_shares_everything_but_status(self):
        k = FakeKube("m")
        k.create(RES, _mkobj("a", replicas=3))
        before = k.try_get_view(RES, "default/a")
        k.update_status(RES, {"metadata": {"name": "a", "namespace": "default"},
                              "status": {"ready": 3}})
        after = k.try_get_view(RES, "default/a")
        assert after["spec"] is before["spec"]
        assert after["status"] == {"ready": 3}
        assert "status" not in before

    def test_delivered_event_is_the_stored_node(self):
        """Fan-out hands watchers the version node itself — the copy
        that used to be taken per event per watcher is gone."""
        k = FakeKube("m")
        seen = []
        k.watch(RES, lambda e, o: seen.append(o))
        k.create(RES, _mkobj("a"))
        assert seen[0] is k.try_get_view(RES, "default/a")

    def test_batch_results_are_version_nodes(self):
        k = FakeKube("m")
        (res,) = k.batch([{"verb": "create", "resource": RES,
                           "object": _mkobj("a")}])
        assert res["code"] == 201
        assert res["object"] is k.try_get_view(RES, "default/a")


# -- columnar batch: ordering + protocol -----------------------------------
class TestBatchOrdering:
    def _script(self, k):
        """Interleaved create/update/delete/update_status + error ops,
        split over two chunks."""
        out = []
        out += k.batch([
            {"verb": "create", "resource": RES, "object": _mkobj("a", 1)},
            {"verb": "create", "resource": RES, "object": _mkobj("b", 1)},
            {"verb": "update", "resource": RES, "object": _mkobj("a", 5)},
            {"verb": "create", "resource": RES, "object": _mkobj("a", 1)},  # 409
            {"verb": "delete", "resource": RES, "key": "default/b"},
            {"verb": "update_status", "resource": RES,
             "object": {"metadata": {"name": "a", "namespace": "default"},
                        "status": {"ready": 5}}},
        ])
        out += k.batch([
            {"verb": "get", "resource": RES, "key": "default/a"},
            {"verb": "update", "resource": RES, "object": _mkobj("gone", 1)},  # 404
            {"verb": "create", "resource": RES, "object": _mkobj("c", 2)},
            {"verb": "frobnicate", "resource": RES},  # 400
            {"verb": "delete", "resource": RES, "key": "default/a"},
        ])
        return out

    def test_event_order_and_codes(self):
        k = FakeKube("m")
        rec = _Recorder()
        k.watch(RES, rec)
        results = self._script(k)
        assert [r["code"] for r in results] == [
            201, 201, 200, 409, 200, 200, 200, 404, 201, 400, 200]
        events = [e for e, _ in rec.events]
        assert events == [ADDED, ADDED, MODIFIED, DELETED, MODIFIED,
                          ADDED, DELETED]
        # rv strictly increases along the stream (per-op allocation is
        # retained inside the columnar lock pass).
        rvs = [int(json.loads(o)["metadata"]["resourceVersion"])
               for _, o in rec.events]
        assert rvs == sorted(rvs) and len(set(rvs)) == len(rvs)

    def test_failed_ops_do_not_emit_events_or_burn_rv(self):
        k = FakeKube("m")
        rec = _Recorder()
        k.watch(RES, rec)
        k.batch([
            {"verb": "create", "resource": RES, "object": _mkobj("a")},
            {"verb": "update", "resource": RES, "object": _mkobj("nope")},
            {"verb": "create", "resource": RES, "object": _mkobj("b")},
        ])
        assert len(rec.events) == 2
        assert k.current_rv() == 2  # the 404 allocated nothing

    def test_conflict_mid_batch_isolated(self):
        k = FakeKube("m")
        k.create(RES, _mkobj("a"))
        stale = k.get(RES, "default/a")
        k.update(RES, _mkobj("a", 7))
        res = k.batch([
            {"verb": "update", "resource": RES, "object": stale},  # stale rv
            {"verb": "create", "resource": RES, "object": _mkobj("b")},
        ])
        assert res[0]["code"] == 409
        assert res[0]["status"]["reason"] == "Conflict"
        assert res[1]["code"] == 201
        assert k.try_get_view(RES, "default/a")["spec"]["replicas"] == 7

    def test_finalizer_gated_delete_through_batch(self):
        k = FakeKube("m")
        rec = _Recorder()
        k.watch(RES, rec)
        k.batch([{"verb": "create", "resource": RES,
                  "object": _mkobj("a", finalizers=["keep"])}])
        k.batch([{"verb": "delete", "resource": RES, "key": "default/a"}])
        # Finalizer present: MODIFIED with deletionTimestamp, not DELETED.
        assert [e for e, _ in rec.events] == [ADDED, MODIFIED]
        node = k.try_get_view(RES, "default/a")
        assert node["metadata"]["deletionTimestamp"]
        # Second delete while pending: silent (no event).
        k.batch([{"verb": "delete", "resource": RES, "key": "default/a"}])
        assert len(rec.events) == 2
        # Removing the finalizer through batch completes the deletion.
        obj = k.get(RES, "default/a")
        obj["metadata"]["finalizers"] = []
        k.batch([{"verb": "update", "resource": RES, "object": obj}])
        assert [e for e, _ in rec.events] == [ADDED, MODIFIED, DELETED]
        assert k.try_get_view(RES, "default/a") is None


class TestCoalescedDelivery:
    def test_one_batch_call_per_flush(self):
        k = FakeKube("m")
        b = _BatchRecorder()
        k.watch(RES, b)
        k.batch([{"verb": "create", "resource": RES, "object": _mkobj(f"o{i}")}
                 for i in range(5)])
        k.batch([{"verb": "update", "resource": RES, "object": _mkobj("o0", 9)},
                 {"verb": "delete", "resource": RES, "key": "default/o1"}])
        assert [len(f) for f in b.flushes] == [5, 2]
        assert [e for e, _ in b.flushes[1]] == [MODIFIED, DELETED]

    def test_direct_verbs_use_per_event_callable(self):
        """Direct verbs have no flush: a batch-capable watcher still
        receives them through its per-event callable (exactly how
        sync's _on_member_event / _on_member_events pair works)."""
        k = FakeKube("m")
        b = _BatchRecorder()
        k.watch(RES, b)
        k.create(RES, _mkobj("a"))
        assert b.flushes == []
        assert [e for e, _ in b.direct] == [ADDED]
        # A bulk commit then lands on kt_batch, not the callable.
        k.batch([{"verb": "update", "resource": RES,
                  "object": _mkobj("a", 2)}])
        assert [e for e, _ in b.direct] == [ADDED]
        assert [[e for e, _ in f] for f in b.flushes] == [[MODIFIED]]

    def test_predicate_filters_batchwise(self):
        only_mod = _BatchRecorder(predicate=lambda e, o: e == MODIFIED)
        k = FakeKube("m")
        k.watch(RES, only_mod)
        k.batch([
            {"verb": "create", "resource": RES, "object": _mkobj("a")},
            {"verb": "update", "resource": RES, "object": _mkobj("a", 4)},
            {"verb": "create", "resource": RES, "object": _mkobj("b")},
        ])
        # One flush, predicate applied before delivery: only the update.
        assert len(only_mod.flushes) == 1
        assert [e for e, _ in only_mod.flushes[0]] == [MODIFIED]
        # All-filtered flushes are not delivered at all.
        k.batch([{"verb": "create", "resource": RES, "object": _mkobj("c")}])
        assert len(only_mod.flushes) == 1

    def test_replay_batches(self):
        k = FakeKube("m")
        for i in range(3):
            k.create(RES, _mkobj(f"o{i}"))
        b = _BatchRecorder()
        k.watch(RES, b, replay=True)
        assert len(b.flushes) == 1
        assert [e for e, _ in b.flushes[0]] == [ADDED] * 3

    def test_watch_all_batch_observer(self):
        k = FakeKube("m")
        per_event, flushes = [], []
        k.watch_all(lambda r, e, o, s: per_event.append((r, e, s)),
                    batch=lambda fl: flushes.append(list(fl)))
        k.batch([{"verb": "create", "resource": RES, "object": _mkobj("a")},
                 {"verb": "create", "resource": RES, "object": _mkobj("b")}])
        assert per_event == []  # batch observer replaces per-event calls
        assert len(flushes) == 1
        assert [(r, e) for r, e, _, _ in flushes[0]] == [(RES, ADDED)] * 2
        # seqs are the events' resourceVersions.
        assert [s for _, _, _, s in flushes[0]] == [1, 2]
        # Direct verbs keep the per-event shape.
        k.create(RES, _mkobj("c"))
        assert per_event == [(RES, ADDED, 3)]

    def test_named_fleet_batch_and_unwatch_owner(self):
        fleet = ClusterFleet()
        fleet.add_member("m-1")
        fleet.add_member("m-2")

        class Ctl:
            def __init__(self):
                self.calls = []

            def on_event(self, cluster, event, obj):
                raise AssertionError("per-event path used")

            def on_flush(self, cluster, events):
                self.calls.append((cluster, [e for e, _ in events]))

        ctl = Ctl()
        fleet.watch_members(RES, ctl.on_event, named=True,
                            batch=ctl.on_flush)
        fleet.member("m-1").batch(
            [{"verb": "create", "resource": RES, "object": _mkobj("a")},
             {"verb": "create", "resource": RES, "object": _mkobj("b")}])
        fleet.member("m-2").batch(
            [{"verb": "create", "resource": RES, "object": _mkobj("a")}])
        assert ctl.calls == [("m-1", [ADDED, ADDED]), ("m-2", [ADDED])]
        # handler_owner sees through _NamedHandler: a dynamic-stop
        # detaches every wrapped registration.
        fleet.unwatch_owner(ctl)
        fleet.member("m-1").batch(
            [{"verb": "create", "resource": RES, "object": _mkobj("c")}])
        assert len(ctl.calls) == 2


# -- KT_STORE_COALESCE=0 A/B ----------------------------------------------
def _drive(kube: FakeKube):
    """One deterministic op script exercising every verb, every error
    path, finalizers, and multi-chunk interleaving."""
    streams = {"watch": [], "all": []}
    kube.watch(RES, lambda e, o: streams["watch"].append(
        (e, json.dumps(o, sort_keys=True))))
    kube.watch_all(lambda r, e, o, s: streams["all"].append(
        (r, e, s, json.dumps(o, sort_keys=True))))
    results = []
    results += kube.batch([
        {"verb": "create", "resource": RES, "object": _mkobj("a", 1)},
        {"verb": "create", "resource": RES, "object": _mkobj("b", 2)},
        {"verb": "create", "resource": RES,
         "object": _mkobj("f", 1, finalizers=["keep"])},
        {"verb": "update", "resource": RES, "object": _mkobj("a", 3)},
        {"verb": "create", "resource": RES, "object": _mkobj("b", 9)},  # 409
        {"verb": "update_status", "resource": RES,
         "object": {"metadata": {"name": "b", "namespace": "default"},
                    "status": {"ready": 2}}},
        {"verb": "delete", "resource": RES, "key": "default/f"},
        {"verb": "get", "resource": RES, "key": "default/a"},
    ])
    results += kube.batch([
        {"verb": "delete", "resource": RES, "key": "default/b"},
        {"verb": "update", "resource": RES, "object": _mkobj("missing")},  # 404
        {"verb": "nonsense", "resource": RES},  # 400
        {"verb": "update", "resource": RES,
         "object": {"metadata": {"name": "a", "namespace": "default",
                                 "labels": {"x": "y"}},
                    "spec": {"replicas": 3}}},  # metadata-only
        {"verb": "delete", "resource": RES, "key": "default/f"},  # pending: silent
        {"verb": "get", "resource": RES, "key": "default/gone"},  # 404
    ])
    # Complete the finalizer-gated deletion through the bulk verb.
    obj = kube.get(RES, "default/f")
    obj["metadata"]["finalizers"] = []
    results += kube.batch([{"verb": "update", "resource": RES, "object": obj}])
    return streams, results, kube.dump()


class TestStoreAB:
    """The columnar path must reproduce the per-op baseline
    BIT-identically — rv allocation, uids, event streams, observer
    seqs, per-op results, and the final store image."""

    def _run(self, monkeypatch, coalesce):
        monkeypatch.setenv("KT_STORE_COALESCE", coalesce)
        kube = FakeKube("ab")  # knob resolved at construction
        assert kube._coalesce is (coalesce == "1")
        streams, results, dump = _drive(kube)
        # Normalize result objects for comparison (both modes return
        # live nodes for write verbs).
        norm = [
            {"code": r["code"],
             **({"object": json.dumps(r["object"], sort_keys=True)}
                if "object" in r else {"status": r.get("status")})}
            for r in results
        ]
        return streams, norm, dump

    def test_bit_identity(self, monkeypatch):
        on = self._run(monkeypatch, "1")
        off = self._run(monkeypatch, "0")
        assert on[0]["watch"] == off[0]["watch"]  # handler stream
        assert on[0]["all"] == off[0]["all"]      # observer stream + seqs
        assert on[1] == off[1]                    # per-op results
        assert on[2] == off[2]                    # final store image
        # Sanity: the script exercised real traffic.
        assert len(on[0]["watch"]) >= 8
        assert any(e == DELETED for e, _ in on[0]["watch"])


class TestWorldAB:
    """A full sync world (BatchSink member writes -> member.batch ->
    coalesced flush -> batched watch intake) propagates bit-identical
    member objects, statuses, and member watch streams with the knob
    off."""

    def _world(self, monkeypatch, coalesce):
        monkeypatch.setenv("KT_STORE_COALESCE", coalesce)
        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        ftc = dataclasses.replace(ftc, controllers=(), revision_history=False)
        fleet = ClusterFleet()
        streams = {}
        for name in ("m-1", "m-2"):
            member = fleet.add_member(name)
            streams[name] = []
            member.watch(
                ftc.source.resource,
                (lambda n: lambda e, o: streams[n].append(
                    (e, json.dumps(_strip_volatile(o), sort_keys=True))))(name),
            )
            fleet.host.create(
                FEDERATED_CLUSTERS,
                {"apiVersion": "core.kubeadmiral.io/v1alpha1",
                 "kind": "FederatedCluster",
                 "metadata": {"name": name}, "spec": {},
                 "status": {"conditions": [
                     {"type": "Joined", "status": "True"},
                     {"type": "Ready", "status": "True"}]}},
            )
        metrics = Metrics()
        ctl = SyncController(fleet, ftc, metrics=metrics)
        for i in range(6):
            fleet.host.create(ftc.federated.resource, {
                "apiVersion": ftc.federated.api_version,
                "kind": ftc.federated.kind,
                "metadata": {
                    "name": f"web-{i}", "namespace": "default",
                    "annotations": {
                        "kubeadmiral.io/pending-controllers": "[]"},
                },
                "spec": {
                    "template": {
                        "apiVersion": "apps/v1", "kind": "Deployment",
                        "metadata": {"name": f"web-{i}",
                                     "namespace": "default"},
                        "spec": {"replicas": i + 1},
                    },
                    "placements": [{
                        "controller": "kubeadmiral.io/global-scheduler",
                        "placement": [{"cluster": "m-1"},
                                      {"cluster": "m-2"}],
                    }],
                },
            })
        while ctl.worker.step():
            pass
        dump = {
            name: {
                key: _strip_volatile(fleet.member(name).get(
                    ftc.source.resource, key))
                for key in sorted(fleet.member(name).keys(
                    ftc.source.resource))
            }
            for name in ("m-1", "m-2")
        }
        statuses = {
            key: (fleet.host.get(ftc.federated.resource, key)
                  .get("status") or {}).get("clusters")
            for key in sorted(fleet.host.keys(ftc.federated.resource))
        }
        return dump, statuses, streams, metrics, ctl

    def test_world_ab_bit_identical(self, monkeypatch):
        on = self._world(monkeypatch, "1")
        off = self._world(monkeypatch, "0")
        assert on[0] == off[0]  # member objects
        assert on[1] == off[1]  # propagation statuses
        assert on[2] == off[2]  # member watch streams
        assert all(len(v) == 6 for v in on[0].values())
        assert all(on[1][k] for k in on[1])
        # The coalesced world actually used batched intake...
        flushes = on[3].counters.get(
            "member_watch_flushes_total{controller=sync-deployments.apps}", 0)
        assert flushes > 0
        ev = on[3].counters.get(
            "member_watch_flush_events_total"
            "{controller=sync-deployments.apps}", 0)
        assert ev >= flushes
        # ...while the per-op world delivered through the per-event
        # intake (legacy _notify path never calls kt_batch).
        off_fl = off[3].counters.get(
            "member_watch_flushes_total{controller=sync-deployments.apps}", 0)
        assert off_fl == 0

    def test_echo_suppression_under_batched_delivery(self, monkeypatch):
        dump, statuses, streams, metrics, ctl = self._world(monkeypatch, "1")
        # Sync's own member writes flushed through _on_member_events but
        # never re-enqueued: the converged queue is empty.
        assert ctl.worker.queue.drain_due() == []
        calls = []
        orig = ctl.worker.enqueue_many
        ctl.worker.enqueue_many = lambda keys: (
            calls.append(sorted(keys)), orig(keys))[1]
        # Re-propagate: a spec change on the host re-writes both members;
        # those own writes come back through the batched intake and must
        # be swallowed (thread-identity echo check).
        fed = ctl.host.get(ctl._fed_resource, "default/web-0")
        fed["spec"]["template"]["spec"]["replicas"] = 42
        ctl.host.update(ctl._fed_resource, fed)
        while ctl.worker.step():
            pass
        assert calls == [], "own member writes re-enqueued through batch intake"
        # A FOREIGN batched write (member-side drift) must enqueue.
        member = ctl.fleet.member("m-1")
        drift = member.get(ctl.ftc.source.resource, "default/web-0")
        drift["spec"]["replicas"] = 1
        member.batch([{"verb": "update",
                       "resource": ctl.ftc.source.resource,
                       "object": drift}])
        assert calls == [["default/web-0"]]


def _strip_volatile(obj: dict) -> dict:
    """rv/uid are allocation counters: two separately-run worlds differ
    legitimately (the raw-store A/B above compares them exactly)."""
    import copy

    out = copy.deepcopy(obj)
    out.get("metadata", {}).pop("resourceVersion", None)
    out.get("metadata", {}).pop("uid", None)
    return out


# -- SLO decomposition in both modes ---------------------------------------
class TestSLODecompositionAB:
    """The coalesced flush mints SLO tokens per event in stream order —
    the stage decomposition must stay exact (ISSUE 18 acceptance: sums
    to the measured total within 10%) in BOTH modes."""

    @pytest.mark.parametrize("coalesce", ["1", "0"])
    def test_decomposition_exact(self, monkeypatch, coalesce):
        monkeypatch.setenv("KT_STORE_COALESCE", coalesce)
        rec = slo.SLORecorder(enabled=True)
        prev = slo.set_default(rec)
        try:
            ftc = next(f for f in default_ftcs()
                       if f.name == "deployments.apps")
            ftc = dataclasses.replace(
                ftc, controllers=(("kubeadmiral.io/global-scheduler",),))
            fleet = ClusterFleet()
            metrics = Metrics()
            rec.attach(metrics)
            controllers = [
                FederatedClusterController(
                    fleet, api_resource_probe=["apps/v1/Deployment"],
                    metrics=metrics),
                FederateController(fleet.host, ftc, metrics=metrics),
                SchedulerController(fleet.host, ftc, metrics=metrics),
                SyncController(fleet, ftc, metrics=metrics),
            ]
            for name in ("c1", "c2"):
                member = fleet.add_member(name)
                member.create(NODES, make_node("n1", "64", "128Gi"))
                fleet.host.create(FEDERATED_CLUSTERS, {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name}, "spec": {}})
            fleet.host.create(PROPAGATION_POLICIES, {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "PropagationPolicy",
                "metadata": {"name": "pp", "namespace": "default"},
                "spec": {"schedulingMode": "Divide"}})

            def settle():
                for _ in range(300):
                    if not any(c.worker.step() for c in controllers):
                        return
                raise AssertionError("world did not settle")

            settle()
            for i in range(4):
                fleet.host.create(
                    ftc.source.resource,
                    make_deployment(name=f"app-{i}", replicas=2 + i))
            settle()
            assert rec.pending_count() == 0
            assert rec.unwritten_placements() == 0
            summary = rec.summary()
            assert summary["stages"]["total"]["count"] == 4
            assert summary["slowest"]
            for exemplar in summary["slowest"]:
                stage_sum = sum(exemplar["stages_s"].values())
                assert stage_sum == pytest.approx(
                    exemplar["total_s"], rel=0.10, abs=1e-6)
                assert exemplar["acked"], exemplar
        finally:
            if prev is not None:
                slo.set_default(prev)


# -- lock discipline -------------------------------------------------------
class TestLockDiscipline:
    def test_shared_fields_declaration(self):
        assert FakeKube._shared_fields_ == {
            "_objects": "_lock",
            "_watchers": "_lock",
            "_all_watchers": "_lock",
            "_rv": "_lock",
        }

    def test_columnar_commit_is_lockcheck_clean(self):
        if not lockcheck.enabled():
            pytest.skip("KT_LOCKCHECK off")
        lockcheck.reset()
        k = FakeKube("m")
        b = _BatchRecorder()
        k.watch(RES, b)
        k.batch([{"verb": "create", "resource": RES, "object": _mkobj(f"o{i}")}
                 for i in range(10)])
        k.batch([{"verb": "delete", "resource": RES, "key": "default/o0"}])
        k.create(RES, _mkobj("direct"))
        fresh = FakeKube.restore(k.dump())
        assert fresh.current_rv() == k.current_rv()
        bad = [v for v in lockcheck.violations() if "FakeKube" in v]
        assert bad == [], bad

    def test_unguarded_rebind_is_flagged(self):
        if not lockcheck.enabled():
            pytest.skip("KT_LOCKCHECK off")
        lockcheck.reset()
        k = FakeKube("m")
        k._rv = 99  # naked write: the guard must notice
        bad = [v for v in lockcheck.violations()
               if "FakeKube._rv" in v]
        assert bad, "shared-field guard not armed on FakeKube"
        lockcheck.reset()
