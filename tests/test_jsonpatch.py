import pytest

from kubeadmiral_tpu.utils.jsonpatch import PatchError, apply_patch


def test_add_replace_remove_dict():
    doc = {"spec": {"replicas": 1}}
    out = apply_patch(doc, [
        {"op": "replace", "path": "/spec/replicas", "value": 5},
        {"op": "add", "path": "/spec/paused", "value": True},
        {"op": "remove", "path": "/spec/paused"},
    ])
    assert out == {"spec": {"replicas": 5}}
    assert doc == {"spec": {"replicas": 1}}  # input untouched


def test_replace_array_element_overwrites():
    doc = {"containers": [{"name": "a"}, {"name": "b"}]}
    out = apply_patch(doc, [{"op": "replace", "path": "/containers/0", "value": {"name": "X"}}])
    assert out == {"containers": [{"name": "X"}, {"name": "b"}]}


def test_add_array_inserts_and_appends():
    doc = {"xs": [1, 3]}
    out = apply_patch(doc, [
        {"op": "add", "path": "/xs/1", "value": 2},
        {"op": "add", "path": "/xs/-", "value": 4},
    ])
    assert out == {"xs": [1, 2, 3, 4]}


def test_move_copy_test_ops():
    doc = {"a": {"x": 1}, "b": {}}
    out = apply_patch(doc, [
        {"op": "copy", "from": "/a/x", "path": "/b/y"},
        {"op": "move", "from": "/a/x", "path": "/b/z"},
        {"op": "test", "path": "/b/y", "value": 1},
    ])
    assert out == {"a": {}, "b": {"y": 1, "z": 1}}


def test_escaping():
    doc = {"a/b": {"c~d": 1}}
    out = apply_patch(doc, [{"op": "replace", "path": "/a~1b/c~0d", "value": 2}])
    assert out == {"a/b": {"c~d": 2}}


def test_errors():
    with pytest.raises(PatchError):
        apply_patch({}, [{"op": "replace", "path": "/missing", "value": 1}])
    with pytest.raises(PatchError):
        apply_patch({}, [{"op": "nope", "path": "/x"}])
    with pytest.raises(PatchError):
        apply_patch({"xs": [1]}, [{"op": "add", "path": "/xs/9", "value": 1}])
    with pytest.raises(PatchError):
        apply_patch({"a": 1}, [{"op": "test", "path": "/a", "value": 2}])
