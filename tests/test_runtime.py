"""Runtime kernel tests: queues, workers, pending pipeline, informers, fakekube."""

import threading
import time

import pytest

from kubeadmiral_tpu.runtime.informer import FederatedInformer, Informer
from kubeadmiral_tpu.runtime.pending import (
    dependencies_fulfilled,
    get_pending,
    set_pending,
    update_pending,
)
from kubeadmiral_tpu.runtime.queue import Backoff, DirtyQueue
from kubeadmiral_tpu.runtime.worker import BatchWorker, Result, Worker
from kubeadmiral_tpu.testing.fakekube import (
    ADDED,
    Conflict,
    DELETED,
    MODIFIED,
    ClusterFleet,
    FakeKube,
    NotFound,
)


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def test_dirty_queue_dedups_and_delays():
    clock = FakeClock()
    q = DirtyQueue(clock)
    q.add("a")
    q.add("a")
    q.add("b", delay=5)
    assert q.drain_due() == ["a"]
    assert q.drain_due() == []
    clock.now = 5
    assert q.drain_due() == ["b"]


def test_dirty_queue_earliest_wins():
    clock = FakeClock()
    q = DirtyQueue(clock)
    q.add("a", delay=10)
    q.add("a", delay=2)  # earlier delivery replaces the later one
    clock.now = 2
    assert q.drain_due() == ["a"]
    clock.now = 10
    assert q.drain_due() == []


def test_backoff_doubles_and_resets():
    b = Backoff(initial=5, maximum=60)
    assert b.next_delay("k") == 5
    assert b.next_delay("k") == 10
    assert b.next_delay("k") == 20
    b.reset("k")
    assert b.next_delay("k") == 5
    assert b.next_delay("other") == 5


def test_worker_retry_uses_backoff():
    clock = FakeClock()
    calls = []

    def reconcile(key):
        calls.append(key)
        return Result.retry() if len(calls) < 3 else Result.ok()

    w = Worker("test", reconcile, clock=clock)
    w.enqueue("obj")
    assert w.step()
    assert calls == ["obj"]
    clock.now = 5  # first backoff delay
    assert w.step()
    clock.now = 15  # second backoff (10s)
    assert w.step()
    assert calls == ["obj", "obj", "obj"]
    assert not w.step()


def test_batch_worker_drains_everything_due():
    clock = FakeClock()
    batches = []

    def tick(keys):
        batches.append(sorted(keys))
        return {}

    w = BatchWorker("tick", tick, clock=clock)
    for k in ("a", "b", "c"):
        w.enqueue(k)
    w.enqueue("later", delay=60)
    w.step()
    assert batches == [["a", "b", "c"]]
    clock.now = 61
    w.step()
    assert batches == [["a", "b", "c"], ["later"]]


def test_pending_controllers_pipeline():
    obj = {"metadata": {}}
    groups = [["scheduler"], ["override"], ["sync"]]
    set_pending(obj, groups)
    assert dependencies_fulfilled(obj, "scheduler")
    assert not dependencies_fulfilled(obj, "override")

    # Scheduler acts and re-arms downstream.
    assert update_pending(obj, "scheduler", True, groups)
    assert get_pending(obj) == [["override"], ["sync"]]
    assert dependencies_fulfilled(obj, "override")

    # Override acts without changes: removes itself only.
    update_pending(obj, "override", False, groups)
    assert get_pending(obj) == [["sync"]]
    update_pending(obj, "sync", False, groups)
    assert get_pending(obj) == []
    assert dependencies_fulfilled(obj, "anything")


def test_pending_missing_annotation_raises():
    with pytest.raises(KeyError):
        get_pending({"metadata": {}})


def mk(ns, name, spec=None, **meta):
    return {
        "apiVersion": "v1",
        "kind": "Thing",
        "metadata": {"namespace": ns, "name": name, **meta},
        "spec": spec or {},
    }


def test_fakekube_crud_and_conflict():
    kube = FakeKube()
    created = kube.create("things", mk("ns", "a", {"x": 1}))
    assert created["metadata"]["resourceVersion"] == "1"
    assert created["metadata"]["generation"] == 1

    stale = dict(created, spec={"x": 2})
    updated = kube.update("things", stale)
    assert updated["metadata"]["generation"] == 2

    with pytest.raises(Conflict):
        kube.update("things", created)  # stale resourceVersion

    with pytest.raises(NotFound):
        kube.get("things", "ns/missing")


def test_fakekube_finalizers_gate_deletion():
    kube = FakeKube()
    obj = kube.create("things", mk("ns", "a", finalizers=["keep"]))
    events = []
    kube.watch("things", lambda e, o: events.append(e), replay=False)

    kube.delete("things", "ns/a")
    got = kube.get("things", "ns/a")
    assert got["metadata"]["deletionTimestamp"]
    assert events == [MODIFIED]

    got["metadata"]["finalizers"] = []
    kube.update("things", got)
    assert kube.try_get("things", "ns/a") is None
    assert events == [MODIFIED, DELETED]


def test_fakekube_list_filters():
    kube = FakeKube()
    kube.create("things", mk("ns1", "a", labels={"app": "x"}))
    kube.create("things", mk("ns2", "b", labels={"app": "y"}))
    assert len(kube.list("things")) == 2
    assert len(kube.list("things", namespace="ns1")) == 1
    assert len(kube.list("things", label_selector={"app": "y"})) == 1


def test_informer_cache_and_handlers():
    kube = FakeKube()
    kube.create("things", mk("ns", "pre"))
    informer = Informer(kube, "things")
    assert informer.get("ns/pre") is not None

    seen = []
    informer.add_handler(lambda e, o: seen.append((e, o["metadata"]["name"])))
    assert seen == [(ADDED, "pre")]

    kube.create("things", mk("ns", "new"))
    kube.delete("things", "ns/new")
    assert (ADDED, "new") in seen and (DELETED, "new") in seen
    assert informer.get("ns/new") is None


def test_federated_informer_multiplexes():
    fleet = ClusterFleet()
    c1, c2 = fleet.add_member("c1"), fleet.add_member("c2")
    fi = FederatedInformer("things")
    fi.add_cluster("c1", c1)
    fi.add_cluster("c2", c2)

    c1.create("things", mk("ns", "obj"))
    c2.create("things", mk("ns", "obj"))
    found = fi.get_from_all("ns/obj")
    assert set(found) == {"c1", "c2"}
    fi.remove_cluster("c2")
    assert set(fi.get_from_all("ns/obj")) == {"c1"}


def test_informer_close_detaches_watch():
    kube = FakeKube()
    informer = Informer(kube, "things")
    seen = []
    informer.add_handler(lambda e, o: seen.append(e), replay=False)
    informer.close()
    kube.create("things", mk("ns", "after-close"))
    assert seen == []
    fi = FederatedInformer("things")
    fi.add_cluster("c1", kube)
    events = []
    fi.add_handler(lambda cl, e, o: events.append((cl, e)))
    fi.remove_cluster("c1")
    kube.create("things", mk("ns", "x"))
    assert events == []
