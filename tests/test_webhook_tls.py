"""Webhook TLS round trip: the TLSConfig of a
SchedulerPluginWebhookConfiguration (caData/certData/keyData/insecure/
serverName — reference: types_schedulerpluginwebhookconfiguration.go:68-90,
consumed by scheduler/webhook.go:117-119) against a TLS-serving
extension service, over real sockets."""

import base64
import subprocess

import pytest

from kubeadmiral_tpu.scheduler.extension_service import ExtensionService
from kubeadmiral_tpu.scheduler.webhook import (
    UrllibClient,
    WebhookError,
    WebhookPlugin,
    parse_webhook_config,
)


@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    """Ephemeral CA + server cert (SAN localhost/127.0.0.1) + client cert."""
    d = tmp_path_factory.mktemp("pki")

    def run(*args):
        subprocess.run(args, check=True, capture_output=True, cwd=d)

    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", "ca.key", "-out", "ca.pem", "-days", "1",
        "-subj", "/CN=test-ca")
    # server cert for 127.0.0.1 + the SNI name "webhook.internal"
    run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", "server.key", "-out", "server.csr",
        "-subj", "/CN=webhook.internal")
    run("openssl", "x509", "-req", "-in", "server.csr", "-CA", "ca.pem",
        "-CAkey", "ca.key", "-CAcreateserial", "-out", "server.pem",
        "-days", "1", "-extfile", "/dev/stdin")
    # -extfile via stdin doesn't work with run(); redo with a file:
    (d / "ext.cnf").write_text(
        "subjectAltName=DNS:localhost,DNS:webhook.internal,IP:127.0.0.1\n"
    )
    run("openssl", "x509", "-req", "-in", "server.csr", "-CA", "ca.pem",
        "-CAkey", "ca.key", "-CAcreateserial", "-out", "server.pem",
        "-days", "1", "-extfile", "ext.cnf")
    run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", "client.key", "-out", "client.csr",
        "-subj", "/CN=webhook-client")
    run("openssl", "x509", "-req", "-in", "client.csr", "-CA", "ca.pem",
        "-CAkey", "ca.key", "-CAcreateserial", "-out", "client.pem",
        "-days", "1")
    return d


def b64(path):
    return base64.b64encode(path.read_bytes()).decode()


def webhook_obj(url_prefix, tls):
    return {
        "apiVersion": "core.kubeadmiral.io/v1alpha1",
        "kind": "SchedulerPluginWebhookConfiguration",
        "metadata": {"name": "tls-hook", "generation": 1},
        "spec": {
            "urlPrefix": url_prefix,
            "filterPath": "/filter",
            "payloadVersions": ["v1alpha1"],
            "tlsConfig": tls,
        },
    }


def make_unit_cluster():
    from kubeadmiral_tpu.models.types import ClusterState, SchedulingUnit, parse_resources

    su = SchedulingUnit(gvk="apps/v1/Deployment", namespace="d", name="w")
    cl = ClusterState(
        name="m1", labels={}, taints=(),
        allocatable=parse_resources({"cpu": "4"}),
        available=parse_resources({"cpu": "2"}),
        api_resources=frozenset({"apps/v1/Deployment"}),
    )
    return su, cl


class TestWebhookTLS:
    def test_ca_verified_round_trip(self, pki):
        svc = ExtensionService(
            filter_fn=lambda req: {"selected": True},
            tls_cert_file=str(pki / "server.pem"),
            tls_key_file=str(pki / "server.key"),
        )
        svc.start()
        try:
            cfg = parse_webhook_config(
                webhook_obj(svc.url_prefix, {"caData": b64(pki / "ca.pem")})
            )
            plugin = WebhookPlugin(cfg, client=UrllibClient())
            su, cl = make_unit_cluster()
            assert plugin.filter(su, cl) is True
        finally:
            svc.stop()

    def test_untrusted_ca_rejected(self, pki):
        svc = ExtensionService(
            filter_fn=lambda req: {"selected": True},
            tls_cert_file=str(pki / "server.pem"),
            tls_key_file=str(pki / "server.key"),
        )
        svc.start()
        try:
            cfg = parse_webhook_config(webhook_obj(svc.url_prefix, {}))
            # No CA configured -> system trust store -> handshake fails.
            plugin = WebhookPlugin(cfg, client=UrllibClient())
            su, cl = make_unit_cluster()
            with pytest.raises(Exception):
                plugin.filter(su, cl)
        finally:
            svc.stop()

    def test_insecure_skips_verification(self, pki):
        svc = ExtensionService(
            filter_fn=lambda req: {"selected": True},
            tls_cert_file=str(pki / "server.pem"),
            tls_key_file=str(pki / "server.key"),
        )
        svc.start()
        try:
            cfg = parse_webhook_config(
                webhook_obj(svc.url_prefix, {"insecure": True})
            )
            plugin = WebhookPlugin(cfg, client=UrllibClient())
            su, cl = make_unit_cluster()
            assert plugin.filter(su, cl) is True
        finally:
            svc.stop()

    def test_server_name_override(self, pki):
        """The cert carries SAN webhook.internal; dialing 127.0.0.1 with
        serverName=webhook.internal must verify."""
        svc = ExtensionService(
            filter_fn=lambda req: {"selected": True},
            tls_cert_file=str(pki / "server.pem"),
            tls_key_file=str(pki / "server.key"),
        )
        svc.start()
        try:
            cfg = parse_webhook_config(
                webhook_obj(
                    svc.url_prefix,
                    {"caData": b64(pki / "ca.pem"),
                     "serverName": "webhook.internal"},
                )
            )
            plugin = WebhookPlugin(cfg, client=UrllibClient())
            su, cl = make_unit_cluster()
            assert plugin.filter(su, cl) is True
        finally:
            svc.stop()

    def test_mutual_tls_client_certificate(self, pki):
        svc = ExtensionService(
            filter_fn=lambda req: {"selected": True},
            tls_cert_file=str(pki / "server.pem"),
            tls_key_file=str(pki / "server.key"),
            tls_client_ca_file=str(pki / "ca.pem"),
        )
        svc.start()
        try:
            su, cl = make_unit_cluster()
            # Without a client cert the handshake is refused...
            bare = parse_webhook_config(
                webhook_obj(svc.url_prefix, {"caData": b64(pki / "ca.pem")})
            )
            with pytest.raises(Exception):
                WebhookPlugin(bare, client=UrllibClient()).filter(su, cl)
            # ...with it, the call succeeds.
            cfg = parse_webhook_config(
                webhook_obj(
                    svc.url_prefix,
                    {"caData": b64(pki / "ca.pem"),
                     "certData": b64(pki / "client.pem"),
                     "keyData": b64(pki / "client.key")},
                )
            )
            assert WebhookPlugin(cfg, client=UrllibClient()).filter(su, cl)
        finally:
            svc.stop()

    def test_stalled_client_does_not_block_serving(self, pki):
        """A TCP client that never speaks TLS must not starve the accept
        loop (the handshake runs on the handler thread)."""
        import socket

        svc = ExtensionService(
            filter_fn=lambda req: {"selected": True},
            tls_cert_file=str(pki / "server.pem"),
            tls_key_file=str(pki / "server.key"),
        )
        port = svc.start()
        try:
            stall = socket.create_connection(("127.0.0.1", port))
            # While the stalled connection is open, a real client works.
            cfg = parse_webhook_config(
                webhook_obj(svc.url_prefix, {"caData": b64(pki / "ca.pem")})
            )
            plugin = WebhookPlugin(cfg, client=UrllibClient())
            su, cl = make_unit_cluster()
            assert plugin.filter(su, cl) is True
            stall.close()
        finally:
            svc.stop()
