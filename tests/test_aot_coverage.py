"""AOT + ledger coverage guard (ISSUE 10 satellite; source half
rewritten over ktlint in ISSUE 14).

Every jitted program the engine can dispatch must route through BOTH
``AotStore.wrap`` (so warm-boot failover can preload it instead of
re-tracing) and ``SchedulerEngine._obs_wrap`` (so the dispatch ledger
attributes its device time).  A builder that skips either silently
escapes restart failover or /debug/waterfall.

Two teeth:

* the STATIC half is ktlint's ``aot-ledger-coverage`` rule (tools/
  ktlint/rules/aot_ledger.py), which replaced this file's hand-rolled
  regex enumeration of ``scheduler/engine.py`` with a package-wide AST
  pass — here we assert the rule runs clean over the live tree AND that
  it actually saw the engine's jit sites (so an AST regression cannot
  pass vacuously);
* a RUNTIME check: each builder's product carries the AOT wrapper
  inside the ledger wrapper (single-device engines; meshes construct
  the store live-trace-only and their wrap is a counted pass-through).
"""

import pytest

from kubeadmiral_tpu.scheduler import aot as aot_mod
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine
from tools.ktlint import rule_by_id, run_rules


def test_ktlint_aot_rule_is_clean_package_wide():
    """One source of truth: the same rule `make lint` enforces.  Any
    new jit site anywhere in kubeadmiral_tpu/ must route through
    aot.wrap + _obs_wrap (or carry a justified suppression) before this
    passes — the generalization of the old EXPECTED_JIT_SITES list."""
    rule = rule_by_id("aot-ledger-coverage")
    violations, _ = run_rules([rule])
    assert [v.format() for v in violations] == []
    # The denominator: engine.py alone holds 40+ jit call sites; fewer
    # seen means the walker lost the tree, not that the tree is clean.
    assert rule.stats["jit_sites"] >= 40


def _is_aot_wrapped(fn) -> bool:
    return isinstance(fn, aot_mod._AotProgram)


def _obs_target(fn):
    """The fn captured by an _obs_wrap closure (None if not obs-wrapped)."""
    closure = getattr(fn, "__closure__", None)
    if not closure or getattr(fn, "__name__", "") != "observed":
        return None
    for cell in closure:
        try:
            value = cell.cell_contents
        except ValueError:
            continue
        if callable(value) and not hasattr(value, "observe"):
            return value
    return None


def _assert_covered(fn, what):
    inner = _obs_target(fn)
    assert inner is not None, f"{what}: not routed through _obs_wrap"
    assert _is_aot_wrapped(inner), f"{what}: not routed through aot.wrap"


def test_every_builder_routes_through_aot_and_ledger(tmp_path, monkeypatch):
    monkeypatch.setenv("KT_AOT", "1")
    monkeypatch.setenv("KT_COMPILE_CACHE_DIR", str(tmp_path))
    eng = SchedulerEngine(chunk_size=64, min_bucket=16,
                          min_cluster_bucket=8, mesh=None)
    assert eng._aot.enabled, "AOT store must be enabled for this guard"

    # Shared programs assigned in _build_programs + _instrument_programs.
    for name in (
        "_tick", "_tick_compact", "_gather", "_gather3", "_gather5",
        "_gather_over3", "_gather_over4", "_patch", "_patch_compact",
    ):
        _assert_covered(getattr(eng, name), name)

    # Per-key builder caches: one representative key each.
    builders = [
        ("_narrow_program", eng._narrow_program("compact", 16)),
        ("_narrow_program/dense", eng._narrow_program("dense", 16)),
        ("_fallback_program", eng._fallback_program("compact")),
        ("_cert_repair_program", eng._cert_repair_program()),
        ("_pack_program/full", eng._pack_program("full", 16)),
        ("_pack_program/gather", eng._pack_program("gather", 16)),
        ("_gate_program/compact", eng._gate_program("compact")),
        ("_gate_program/dense", eng._gate_program("dense")),
        ("_wcheck_program/i64", eng._wcheck_program(False)),
        ("_wcheck_program/i32", eng._wcheck_program(True)),
        ("_resolve_program", eng._resolve_program("compact", 16)),
        ("_replan_program", eng._replan_program("compact", 16, False)),
        ("_scoreonly_program", eng._replan_program("compact", 16, True)),
        ("_survivor_program", eng._survivor_program("compact", 16)),
        ("_nfeas_program", eng._nfeas_program()),
        ("_tb_program/full", eng._tb_program("full")),
        ("_tb_program/patch", eng._tb_program("patch")),
        ("_repair_program", eng._repair_program()),
        ("_sco_compress_program", eng._sco_compress_program(False)),
        ("_sco_compress_program/old", eng._sco_compress_program(True)),
        ("_sco_upcast_program", eng._sco_upcast_program()),
    ]
    for what, fn in builders:
        _assert_covered(fn, what)

    # The zeros builders cache obs-wrapped aot programs too.
    eng._zeros_for((16, 8))
    fn = eng._zero_fns[(16, 8)]
    _assert_covered(fn, "_zeros_for")
