"""Override controller: policy matching, per-cluster JSONPatch
resolution, pipeline hand-off (reference: pkg/controllers/override)."""

import json

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.overridectl import (
    CLUSTER_OVERRIDE_POLICIES,
    CLUSTER_OVERRIDE_POLICY_NAME_LABEL,
    OVERRIDE_POLICIES,
    OVERRIDE_POLICY_NAME_LABEL,
    OverrideController,
    is_cluster_matched,
    parse_overrides,
)
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.testing.fakekube import FakeKube


def deployment_ftc():
    return next(f for f in default_ftcs() if f.name == "deployments.apps")


def make_cluster(name, labels=None):
    return {
        "apiVersion": "core.kubeadmiral.io/v1alpha1",
        "kind": "FederatedCluster",
        "metadata": {"name": name, "labels": labels or {}},
        "spec": {},
    }


def make_fed(name="web", labels=None, clusters=("c1", "c2")):
    ftc = deployment_ftc()
    return {
        "apiVersion": ftc.federated.api_version,
        "kind": ftc.federated.kind,
        "metadata": {
            "name": name,
            "namespace": "default",
            "labels": labels or {},
            "annotations": {
                pending.PENDING_CONTROLLERS: json.dumps(
                    [[C.OVERRIDE_CONTROLLER]]
                )
            },
        },
        "spec": {
            "template": {"apiVersion": "apps/v1", "kind": "Deployment"},
            "placements": [
                {
                    "controller": C.SCHEDULER,
                    "placement": [{"cluster": c} for c in clusters],
                }
            ],
        },
    }


def make_policy(name, rules, namespace="default"):
    obj = {
        "apiVersion": "core.kubeadmiral.io/v1alpha1",
        "kind": "OverridePolicy" if namespace else "ClusterOverridePolicy",
        "metadata": {"name": name},
        "spec": {"overrideRules": rules},
    }
    if namespace:
        obj["metadata"]["namespace"] = namespace
    return obj


IMAGE_PATCH = {
    "operator": "replace",
    "path": "/spec/template/spec/containers/0/image",
    "value": "registry.cn/nginx",
}


class TestClusterMatching:
    def test_empty_target_matches_all(self):
        assert is_cluster_matched(None, make_cluster("c1"))
        assert is_cluster_matched({}, make_cluster("c1"))

    def test_names_selector_affinity_are_anded(self):
        cluster = make_cluster("c1", labels={"region": "us"})
        assert is_cluster_matched(
            {"clusters": ["c1"], "clusterSelector": {"region": "us"}}, cluster
        )
        assert not is_cluster_matched(
            {"clusters": ["c2"], "clusterSelector": {"region": "us"}}, cluster
        )
        assert not is_cluster_matched(
            {"clusters": ["c1"], "clusterSelector": {"region": "eu"}}, cluster
        )

    def test_affinity_terms(self):
        cluster = make_cluster("c1", labels={"tier": "1"})
        target = {
            "clusterAffinity": [
                {
                    "matchExpressions": [
                        {"key": "tier", "operator": "In", "values": ["1", "2"]}
                    ]
                }
            ]
        }
        assert is_cluster_matched(target, cluster)
        cluster2 = make_cluster("c2", labels={"tier": "9"})
        assert not is_cluster_matched(target, cluster2)


class TestParseOverrides:
    def test_per_cluster_patches(self):
        policy = make_policy(
            "p",
            [
                {
                    "targetClusters": {"clusters": ["c1"]},
                    "overriders": {"jsonpatch": [IMAGE_PATCH]},
                }
            ],
        )
        out = parse_overrides(policy, [make_cluster("c1"), make_cluster("c2")])
        assert set(out) == {"c1"}
        assert out["c1"] == [
            {
                "op": "replace",
                "path": "/spec/template/spec/containers/0/image",
                "value": "registry.cn/nginx",
            }
        ]


class TestOverrideController:
    def setup_method(self):
        self.kube = FakeKube()
        self.ftc = deployment_ftc()
        self.ctl = OverrideController(self.kube, self.ftc)
        self.fed_res = self.ftc.federated.resource
        for name, labels in (("c1", {"region": "us"}), ("c2", {"region": "eu"})):
            self.kube.create(C.FEDERATED_CLUSTERS, make_cluster(name, labels))

    def test_writes_overrides_and_flips_pipeline(self):
        self.kube.create(
            OVERRIDE_POLICIES,
            make_policy(
                "op-1",
                [
                    {
                        "targetClusters": {"clusterSelector": {"region": "us"}},
                        "overriders": {"jsonpatch": [IMAGE_PATCH]},
                    }
                ],
            ),
        )
        self.kube.create(
            self.fed_res, make_fed(labels={OVERRIDE_POLICY_NAME_LABEL: "op-1"})
        )
        self.ctl.run_until_idle()
        fed = self.kube.get(self.fed_res, "default/web")
        overrides = C.get_overrides(fed, C.OVERRIDE_CONTROLLER)
        assert set(overrides) == {"c1"}
        # The object changed, so the downstream follower group is re-armed
        # (reference pipeline: scheduler -> override -> follower,
        # config/sample/host/01-ftc.yaml:94-97).
        assert pending.get_pending(fed) == [[C.FOLLOWER_CONTROLLER]]

    def test_no_policy_label_clears_and_advances(self):
        self.kube.create(self.fed_res, make_fed())
        self.ctl.run_until_idle()
        fed = self.kube.get(self.fed_res, "default/web")
        assert C.get_overrides(fed, C.OVERRIDE_CONTROLLER) == {}
        assert pending.get_pending(fed) == []

    def test_cluster_and_namespaced_policies_stack_in_order(self):
        self.kube.create(
            CLUSTER_OVERRIDE_POLICIES,
            make_policy(
                "cop-1",
                [
                    {
                        "overriders": {
                            "jsonpatch": [
                                {
                                    "operator": "add",
                                    "path": "/metadata/annotations/a",
                                    "value": "cluster-wide",
                                }
                            ]
                        }
                    }
                ],
                namespace=None,
            ),
        )
        self.kube.create(
            OVERRIDE_POLICIES,
            make_policy(
                "op-1",
                [{"overriders": {"jsonpatch": [IMAGE_PATCH]}}],
            ),
        )
        self.kube.create(
            self.fed_res,
            make_fed(
                labels={
                    OVERRIDE_POLICY_NAME_LABEL: "op-1",
                    CLUSTER_OVERRIDE_POLICY_NAME_LABEL: "cop-1",
                }
            ),
        )
        self.ctl.run_until_idle()
        fed = self.kube.get(self.fed_res, "default/web")
        overrides = C.get_overrides(fed, C.OVERRIDE_CONTROLLER)
        # ClusterOverridePolicy applies first, namespaced second.
        assert [p["op"] for p in overrides["c1"]] == ["add", "replace"]

    def test_dangling_policy_reference_waits(self):
        self.kube.create(
            self.fed_res, make_fed(labels={OVERRIDE_POLICY_NAME_LABEL: "ghost"})
        )
        self.ctl.run_until_idle()
        fed = self.kube.get(self.fed_res, "default/web")
        # Pipeline not advanced while the reference dangles.
        assert pending.get_pending(fed) == [[C.OVERRIDE_CONTROLLER]]

        # Policy appears -> fed object re-enqueued -> resolved.
        self.kube.create(
            OVERRIDE_POLICIES,
            make_policy("ghost", [{"overriders": {"jsonpatch": [IMAGE_PATCH]}}]),
        )
        self.ctl.run_until_idle()
        fed = self.kube.get(self.fed_res, "default/web")
        assert C.get_overrides(fed, C.OVERRIDE_CONTROLLER)["c1"]
        assert pending.get_pending(fed) == [[C.FOLLOWER_CONTROLLER]]

    def test_policy_update_reconciles_objects(self):
        self.kube.create(
            OVERRIDE_POLICIES,
            make_policy("op-1", [{"overriders": {"jsonpatch": [IMAGE_PATCH]}}]),
        )
        self.kube.create(
            self.fed_res, make_fed(labels={OVERRIDE_POLICY_NAME_LABEL: "op-1"})
        )
        self.ctl.run_until_idle()

        # Drain the downstream follower group (as the follower controller
        # would) so the override controller may act on the policy update.
        fed = self.kube.get(self.fed_res, "default/web")
        pending.update_pending(fed, C.FOLLOWER_CONTROLLER, False, [])
        self.kube.update(self.fed_res, fed)

        policy = self.kube.get(OVERRIDE_POLICIES, "default/op-1")
        policy["spec"]["overrideRules"] = [
            {
                "overriders": {
                    "jsonpatch": [
                        {"operator": "replace", "path": "/spec/replicas", "value": 0}
                    ]
                }
            }
        ]
        self.kube.update(OVERRIDE_POLICIES, policy)
        self.ctl.run_until_idle()
        fed = self.kube.get(self.fed_res, "default/web")
        overrides = C.get_overrides(fed, C.OVERRIDE_CONTROLLER)
        assert overrides["c1"][0]["path"] == "/spec/replicas"
