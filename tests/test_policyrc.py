"""PolicyRC reference counting (reference: pkg/controllers/policyrc)."""

from kubeadmiral_tpu.federation.policyrc import Counter, PolicyRCController
from kubeadmiral_tpu.models import policy as P
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.testing.fakekube import FakeKube


def deployment_ftc():
    return next(f for f in default_ftcs() if f.name == "deployments.apps")


def make_fed(name, ns="default", labels=None):
    return {
        "apiVersion": "types.kubeadmiral.io/v1alpha1",
        "kind": "FederatedDeployment",
        "metadata": {"name": name, "namespace": ns, "labels": dict(labels or {})},
        "spec": {"template": {}},
    }


def make_policy(resource, name, ns=None):
    meta = {"name": name}
    if ns:
        meta["namespace"] = ns
    return {
        "apiVersion": "core.kubeadmiral.io/v1alpha1",
        "kind": "Policy",
        "metadata": meta,
        "spec": {},
    }


class TestCounter:
    def test_diffs_previous_against_new(self):
        dirty = []
        c = Counter(dirty.extend)
        c.update("obj1", (("ns", "a"),))
        c.update("obj2", (("ns", "a"),))
        assert c.count(("ns", "a")) == 2
        c.update("obj1", (("ns", "b"),))
        assert c.count(("ns", "a")) == 1
        assert c.count(("ns", "b")) == 1
        c.update("obj2", ())
        assert c.count(("ns", "a")) == 0
        assert ("ns", "a") in dirty and ("ns", "b") in dirty


class TestPolicyRCController:
    def setup_method(self):
        self.host = FakeKube()
        self.ftc = deployment_ftc()
        self.ctl = PolicyRCController(self.host, self.ftc)
        self.resource = self.ftc.federated.resource

    def settle(self):
        for _ in range(30):
            if not self.ctl.step_all():
                return

    def test_propagation_policy_refcount(self):
        self.host.create(
            P.PROPAGATION_POLICIES, make_policy(P.PROPAGATION_POLICIES, "pp", "default")
        )
        for i in range(3):
            self.host.create(
                self.resource,
                make_fed(f"w{i}", labels={P.PROPAGATION_POLICY_LABEL: "pp"}),
            )
        self.settle()
        pol = self.host.get(P.PROPAGATION_POLICIES, "default/pp")
        assert pol["status"]["refCount"] == 3
        assert pol["status"]["typedRefCount"] == [
            {"group": "apps", "resource": "deployments", "count": 3}
        ]

    def test_refcount_drops_on_unbind_and_delete(self):
        self.host.create(
            P.PROPAGATION_POLICIES, make_policy(P.PROPAGATION_POLICIES, "pp", "default")
        )
        self.host.create(
            self.resource, make_fed("w0", labels={P.PROPAGATION_POLICY_LABEL: "pp"})
        )
        self.host.create(
            self.resource, make_fed("w1", labels={P.PROPAGATION_POLICY_LABEL: "pp"})
        )
        self.settle()

        obj = self.host.get(self.resource, "default/w0")
        del obj["metadata"]["labels"][P.PROPAGATION_POLICY_LABEL]
        self.host.update(self.resource, obj)
        self.settle()
        assert self.host.get(P.PROPAGATION_POLICIES, "default/pp")["status"]["refCount"] == 1

        self.host.delete(self.resource, "default/w1")
        self.settle()
        assert self.host.get(P.PROPAGATION_POLICIES, "default/pp")["status"]["refCount"] == 0

    def test_policy_created_after_referrers_gets_counts(self):
        for i in range(2):
            self.host.create(
                self.resource,
                make_fed(f"w{i}", labels={P.CLUSTER_PROPAGATION_POLICY_LABEL: "cpp"}),
            )
        self.settle()
        # Policy appears afterwards: the create event triggers persist.
        self.host.create(
            P.CLUSTER_PROPAGATION_POLICIES,
            make_policy(P.CLUSTER_PROPAGATION_POLICIES, "cpp"),
        )
        self.settle()
        pol = self.host.get(P.CLUSTER_PROPAGATION_POLICIES, "cpp")
        assert pol["status"]["refCount"] == 2

    def test_override_policy_refcounts_both_kinds(self):
        from kubeadmiral_tpu.federation.overridectl import (
            CLUSTER_OVERRIDE_POLICY_NAME_LABEL,
            OVERRIDE_POLICY_NAME_LABEL,
        )

        self.host.create(
            P.OVERRIDE_POLICIES, make_policy(P.OVERRIDE_POLICIES, "op", "default")
        )
        self.host.create(
            P.CLUSTER_OVERRIDE_POLICIES,
            make_policy(P.CLUSTER_OVERRIDE_POLICIES, "cop"),
        )
        self.host.create(
            self.resource,
            make_fed(
                "w0",
                labels={
                    OVERRIDE_POLICY_NAME_LABEL: "op",
                    CLUSTER_OVERRIDE_POLICY_NAME_LABEL: "cop",
                },
            ),
        )
        self.settle()
        assert self.host.get(P.OVERRIDE_POLICIES, "default/op")["status"]["refCount"] == 1
        assert (
            self.host.get(P.CLUSTER_OVERRIDE_POLICIES, "cop")["status"]["refCount"] == 1
        )

    def test_typed_refcount_aggregates_across_ftcs(self):
        sts_ftc = next(f for f in default_ftcs() if f.name == "statefulsets.apps")
        ctl2 = PolicyRCController(self.host, sts_ftc)
        self.host.create(
            P.PROPAGATION_POLICIES, make_policy(P.PROPAGATION_POLICIES, "pp", "default")
        )
        self.host.create(
            self.resource, make_fed("w0", labels={P.PROPAGATION_POLICY_LABEL: "pp"})
        )
        fed_sts = make_fed("s0", labels={P.PROPAGATION_POLICY_LABEL: "pp"})
        fed_sts["kind"] = "FederatedStatefulSet"
        self.host.create(sts_ftc.federated.resource, fed_sts)
        for _ in range(30):
            progressed = self.ctl.step_all()
            progressed |= ctl2.step_all()
            if not progressed:
                break
        pol = self.host.get(P.PROPAGATION_POLICIES, "default/pp")
        assert pol["status"]["refCount"] == 2
        by_type = {t["resource"]: t["count"] for t in pol["status"]["typedRefCount"]}
        assert by_type == {"deployments": 1, "statefulsets": 1}
