"""Crash-safe control plane: durable snapshots, kill matrix, AOT failover.

Two layers:

* **In-process matrix** (tier-1): snapshot round trips through the real
  file store — fresh resume rides the O(B) no-op replay with ZERO
  device dispatches, stale resume rides the drift gate, churned resume
  re-solves only changed rows, and torn / corrupt / version-mismatched
  snapshots are quarantined and never loaded.  Plus breaker-state
  restore, sink finalization, streaming drain and leadership release.

* **Subprocess kill matrix** (``make restart-smoke``; the full sweep is
  marked slow): a victim process SIGKILLs itself mid-{featurize,
  dispatch, fetch, snapshot-write, snapshot-rename, dispatch-flush}
  (tools/restart_driver.py), and a successor process must converge to
  placements AND flight-recorder reason counts bit-identical to an
  uninterrupted reference run.
"""

from __future__ import annotations

import dataclasses
import json
import os
import pickle
import subprocess
import sys
import threading

import numpy as np
import pytest

from kubeadmiral_tpu.models import types as T
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.snapshot import SnapshotManager, SnapshotStore
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO, "tools", "restart_driver.py")


def small_world(n=220, c=10, seed=11):
    rng = np.random.default_rng(seed)
    clusters = [
        T.ClusterState(
            name=f"m-{j:03d}",
            labels={"region": ("us", "eu")[j % 2]},
            taints=(),
            allocatable=T.parse_resources({"cpu": "64", "memory": "256Gi"}),
            available=T.parse_resources(
                {"cpu": f"{int(rng.integers(8, 60))}", "memory": "128Gi"}
            ),
            api_resources=frozenset({"apps/v1/Deployment"}),
        )
        for j in range(c)
    ]
    units = [
        T.SchedulingUnit(
            gvk="apps/v1/Deployment",
            namespace="ns",
            name=f"w-{i:04d}",
            scheduling_mode=T.MODE_DIVIDE if i % 4 else "Duplicate",
            desired_replicas=int(rng.integers(1, 40)) if i % 4 else None,
            resource_request=T.parse_resources({"cpu": "250m"}),
            max_clusters=int(rng.integers(1, 6)) if i % 5 == 0 else None,
        )
        for i in range(n)
    ]
    return units, clusters


def clusters_eq(a, b):
    return all(x.clusters == y.clusters for x, y in zip(a, b)) and len(a) == len(b)


class TestSnapshotStore:
    def test_atomic_roundtrip(self, tmp_path):
        store = SnapshotStore(str(tmp_path))
        payload = {"x": np.arange(10), "y": [("a", 1)]}
        store.save(7, payload)
        header, loaded = store.load_latest()
        assert header["tick"] == 7
        assert np.array_equal(loaded["x"], payload["x"])
        assert loaded["y"] == payload["y"]

    def test_keep_prunes_old_generations(self, tmp_path):
        store = SnapshotStore(str(tmp_path), keep=2)
        for t in (1, 2, 3, 4):
            store.save(t, {"t": t})
        snaps = sorted(f for f in os.listdir(tmp_path) if f.endswith(".ktsnap"))
        assert len(snaps) == 2
        assert store.load_latest()[0]["tick"] == 4

    @pytest.mark.parametrize(
        "corrupt",
        ["truncate", "flip-payload", "bad-magic", "bad-version"],
    )
    def test_corrupt_snapshot_quarantined_never_loaded(self, tmp_path, corrupt):
        metrics = Metrics()
        store = SnapshotStore(str(tmp_path), metrics=metrics)
        store.save(1, {"gen": "old"})
        path = store.save(2, {"gen": "new"})
        raw = bytearray(open(path, "rb").read())
        if corrupt == "truncate":
            raw = raw[: len(raw) - 7]
        elif corrupt == "flip-payload":
            raw[-1] ^= 0xFF
        elif corrupt == "bad-magic":
            raw[:8] = b"NOTSNAP0"
        elif corrupt == "bad-version":
            # Re-write with a future version: never reinterpreted.
            import struct as _struct
            import zlib as _zlib

            blob = pickle.dumps({"gen": "future"}, protocol=4)
            header = pickle.dumps(
                {"version": 999, "tick": 2, "crc": _zlib.crc32(blob),
                 "payload_len": len(blob), "wall": 0.0},
                protocol=4,
            )
            raw = bytearray(
                b"KTSNAP01" + _struct.pack("<Q", len(header)) + header + blob
            )
        open(path, "wb").write(bytes(raw))
        header, payload = store.load_latest()
        # The torn newest generation is quarantined; the older valid one
        # is served instead of anything torn being trusted.
        assert payload["gen"] == "old"
        assert any(f.endswith(".quarantined") for f in os.listdir(tmp_path))
        counters = metrics.snapshot()["counters"]
        assert counters.get('engine_snapshot_total{result=quarantined}', 0) >= 1

    def test_all_generations_corrupt_falls_back_cold(self, tmp_path):
        store = SnapshotStore(str(tmp_path))
        path = store.save(1, {"gen": "only"})
        raw = bytearray(open(path, "rb").read())
        raw[-1] ^= 0xFF
        open(path, "wb").write(bytes(raw))
        assert store.load_latest() is None


class TestEngineRestore:
    def _converged(self, units, clusters):
        engine = SchedulerEngine(mesh=None)
        engine.schedule(units, clusters)
        snap = pickle.loads(pickle.dumps(engine.snapshot_state()))
        return engine, snap

    def test_fresh_resume_rides_noop_replay_zero_dispatches(self):
        units, clusters = small_world()
        e1, snap = self._converged(units, clusters)
        r1 = e1.schedule(units, clusters)

        units2, clusters2 = small_world()  # a relist: new objects, same world
        e2 = SchedulerEngine(mesh=None)
        e2.stage_restore(snap, assume_fresh=True)
        d0 = e2.dispatches_total
        r2 = e2.schedule(units2, clusters2)
        assert e2.restore_info["result"] == "loaded"
        assert e2.restore_info["fresh"] is True
        assert e2.dispatches_total == d0, "fresh resume must not dispatch"
        assert e2.fetch_stats["noop"] >= 1
        assert clusters_eq(r1, r2)

    def test_stale_resume_revalidates_through_drift_paths(self):
        units, clusters = small_world()
        _e1, snap = self._converged(units, clusters)

        units2, clusters2 = small_world()
        clusters2[0] = dataclasses.replace(
            clusters2[0],
            available={k: max(0, v // 2) for k, v in clusters2[0].available.items()},
        )
        e2 = SchedulerEngine(mesh=None)
        e2.stage_restore(snap)
        r2 = e2.schedule(units2, clusters2)
        assert e2.restore_info["result"] == "loaded"
        assert e2.restore_info["fresh"] is False
        assert e2.drift_stats["gated"] >= 1, "stale resume must ride the gate"

        ref = SchedulerEngine(mesh=None).schedule(units2, clusters2)
        assert clusters_eq(ref, r2)

    def test_churned_resume_resolves_only_changed_rows(self):
        units, clusters = small_world()
        _e1, snap = self._converged(units, clusters)

        units2, clusters2 = small_world()
        changed = (3, 17, 100)
        for i in changed:
            units2[i] = dataclasses.replace(
                units2[i], desired_replicas=(units2[i].desired_replicas or 1) + 9
            )
        e2 = SchedulerEngine(mesh=None)
        e2.stage_restore(snap)
        r2 = e2.schedule(units2, clusters2)
        assert e2.restore_info["result"] == "loaded"
        assert e2.fetch_stats["subbatch"] >= 1
        assert set(e2.last_changed) == set(changed)
        ref = SchedulerEngine(mesh=None).schedule(units2, clusters2)
        assert clusters_eq(ref, r2)

    def test_topology_change_rejects_to_cold(self):
        units, clusters = small_world()
        _e1, snap = self._converged(units, clusters)
        units2, clusters2 = small_world()
        clusters2[0] = dataclasses.replace(
            clusters2[0], labels={"region": "mars"}
        )
        e2 = SchedulerEngine(mesh=None)
        e2.stage_restore(snap)
        r2 = e2.schedule(units2, clusters2)
        assert e2.restore_info["result"] == "rejected"
        ref = SchedulerEngine(mesh=None).schedule(units2, clusters2)
        assert clusters_eq(ref, r2)

    def test_config_mismatch_rejects(self):
        units, clusters = small_world()
        _e1, snap = self._converged(units, clusters)
        snap["config"] = dict(snap["config"], narrow_m=999)
        e2 = SchedulerEngine(mesh=None)
        e2.stage_restore(snap)
        e2.schedule(*small_world())
        assert e2.restore_info["result"] == "rejected"

    def test_want_scores_not_served_by_scoreless_snapshot(self):
        units, clusters = small_world(n=80)
        _e1, snap = self._converged(units, clusters)
        e2 = SchedulerEngine(mesh=None)
        e2.stage_restore(snap)
        r2 = e2.schedule(*small_world(n=80), want_scores=True)
        ref = SchedulerEngine(mesh=None).schedule(
            *small_world(n=80), want_scores=True
        )
        assert clusters_eq(ref, r2)
        assert all(a.scores == b.scores for a, b in zip(ref, r2))

    def test_snapshot_manager_end_to_end_via_store(self, tmp_path):
        units, clusters = small_world()
        metrics = Metrics()
        e1 = SchedulerEngine(mesh=None, metrics=metrics)
        store = SnapshotStore(str(tmp_path), metrics=metrics)
        SnapshotManager(e1, store, every=1, flightrec=None)
        r1 = e1.schedule(units, clusters)
        assert store.load_latest() is not None

        e2 = SchedulerEngine(mesh=None)
        mgr2 = SnapshotManager(e2, store, flightrec=None)
        assert mgr2.restore() == "staged"
        r2 = e2.schedule(*small_world())
        assert e2.restore_info["result"] == "loaded"
        assert clusters_eq(r1, r2)


class TestBreakerRestore:
    def test_open_breaker_stays_open_with_remaining_cooldown(self):
        from kubeadmiral_tpu.transport.breaker import (
            OPEN, BreakerConfig, BreakerRegistry,
        )

        clock = [100.0]
        cfg = BreakerConfig(open_seconds=30.0)
        reg = BreakerRegistry(config=cfg, clock=lambda: clock[0])
        reg.for_member("m-1").record_failure(timeout=True)
        assert reg.for_member("m-1").state == OPEN
        clock[0] += 10.0  # 20s of cool-down left at export
        state = reg.export_state()
        assert abs(state["members"]["m-1"]["remaining_s"] - 20.0) < 1e-6

        # Successor: 5s of downtime between snapshot and restore.
        clock2 = [500.0]
        reg2 = BreakerRegistry(config=cfg, clock=lambda: clock2[0])
        state["wall"] -= 5.0  # pretend the export happened 5s ago
        reg2.restore_state(state)
        b = reg2.for_member("m-1")
        assert b.state == OPEN
        # First post-restart tick: still skipped, no free probe storm.
        assert not b.allow(consume_probe=False)
        # The probe resumes after the REMAINING cool-down (~15s), not a
        # fresh 30s window...
        clock2[0] += 16.0
        assert b.allow()  # half-open probe admitted
        # ...and not from zero either: at +1s it must still be closed off.
        clock3 = [0.0]
        reg3 = BreakerRegistry(config=cfg, clock=lambda: clock3[0])
        reg3.restore_state({"wall": __import__("time").time(), "members": {
            "m-1": {"state": "open", "remaining_s": 20.0, "consecutive": 1,
                    "failures_total": 1, "opens_total": 1,
                    "ewma_latency_s": None},
        }})
        clock3[0] += 1.0
        assert not reg3.for_member("m-1").allow(consume_probe=False)

    def test_half_open_restores_into_open_tail(self):
        from kubeadmiral_tpu.transport.breaker import (
            HALF_OPEN, OPEN, BreakerConfig, BreakerRegistry,
        )

        clock = [0.0]
        cfg = BreakerConfig(open_seconds=10.0)
        reg = BreakerRegistry(config=cfg, clock=lambda: clock[0])
        reg.for_member("m-1").record_failure(timeout=True)
        clock[0] += 11.0
        assert reg.for_member("m-1").allow()  # consume the probe
        assert reg.for_member("m-1").state == HALF_OPEN
        state = reg.export_state()

        reg2 = BreakerRegistry(config=cfg, clock=lambda: clock[0])
        reg2.restore_state(state)
        # The in-flight probe died with the old process: re-enter OPEN's
        # tail (remaining 0 -> immediately probe-able, but never CLOSED
        # for free).
        assert reg2.for_member("m-1").state == OPEN


class TestShutdownDrain:
    def test_batch_sink_finalize_sheds_and_raises(self):
        from kubeadmiral_tpu.federation.dispatch import BatchSink
        from kubeadmiral_tpu.runtime.metrics import Metrics as M
        from kubeadmiral_tpu.testing.fakekube import FakeKube
        from kubeadmiral_tpu.transport.breaker import BreakerRegistry

        metrics = M()
        breakers = BreakerRegistry(metrics=metrics)
        member = FakeKube("m")
        sink = BatchSink(lambda _c: member, breakers=breakers)
        sink.submit("c1", {"verb": "create", "resource": "v1/x",
                           "object": {"metadata": {"name": "a"}}}, lambda r: None)
        sink.submit("c1", {"verb": "create", "resource": "v1/x",
                           "object": {"metadata": {"name": "b"}}}, lambda r: None)
        shed = sink.finalize(deadline_s=1.0)
        assert shed == 2
        counters = metrics.snapshot()["counters"]
        assert counters.get('member_shed_writes_total{cluster=c1}') == 2
        with pytest.raises(RuntimeError):
            sink.submit("c1", {"verb": "create"}, lambda r: None)
        assert not [
            t for t in threading.enumerate()
            if t.name.startswith("dispatch-flush-")
        ]

    def test_finalize_all_sinks_covers_live_sinks(self):
        from kubeadmiral_tpu.federation import dispatch as D
        from kubeadmiral_tpu.testing.fakekube import FakeKube

        sink = D.BatchSink(lambda _c: FakeKube("m"))
        sink.submit("c9", {"verb": "delete", "resource": "v1/x", "key": "a"},
                    lambda r: None)
        assert D.finalize_all_sinks(1.0) >= 1
        assert sink._staged == {}

    def test_immediate_sink_finalize_cancels_unstarted(self):
        import time as _time
        from concurrent.futures import ThreadPoolExecutor

        from kubeadmiral_tpu.federation.dispatch import ImmediateSink

        class SlowKube:
            def batch(self, ops):
                _time.sleep(0.5)
                return [{"code": 200, "object": op.get("object", {})} for op in ops]

        pool = ThreadPoolExecutor(max_workers=1)
        sink = ImmediateSink(lambda _c: SlowKube(), pool=pool)
        done = []
        for i in range(4):
            sink.submit("c1", {"verb": "create", "object": {}},
                        lambda r: done.append(r))
        shed = sink.finalize(deadline_s=0.7)
        assert shed >= 1  # queued-behind writes cancelled
        with pytest.raises(RuntimeError):
            sink.submit("c1", {}, lambda r: None)
        pool.shutdown(wait=False)

    def test_streaming_drain_flushes_pending(self):
        from kubeadmiral_tpu.scheduler.streaming import StreamingScheduler

        units, clusters = small_world(n=64)
        engine = SchedulerEngine(mesh=None)
        stream = StreamingScheduler(engine, clusters, units, slab_age_ms=1e9)
        stream.flush()
        stream.offer(
            dataclasses.replace(units[0], desired_replicas=99)
        )
        assert stream.pending() == 1
        results = stream.drain(deadline_s=30.0)
        assert results is not None
        assert stream.pending() == 0
        assert stream.drain(deadline_s=1.0) is None  # nothing pending

    def test_leader_release_hands_off_immediately(self):
        from kubeadmiral_tpu.runtime.leaderelection import LeaderElector
        from kubeadmiral_tpu.testing.fakekube import FakeKube

        host = FakeKube("host")
        a = LeaderElector(host, identity="a")
        b = LeaderElector(host, identity="b")
        assert a.try_acquire_or_renew()
        assert not b.try_acquire_or_renew()
        assert a.release()
        assert b.try_acquire_or_renew(), "standby must win without lease expiry"

    def test_manager_shutdown_writes_final_snapshot(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KT_SNAPSHOT_DIR", str(tmp_path))
        from kubeadmiral_tpu.runtime.manager import ControllerManager
        from kubeadmiral_tpu.testing.fakekube import ClusterFleet

        fleet = ClusterFleet()
        fleet.add_member("c1")
        manager = ControllerManager(fleet)
        assert manager.snapshots is not None
        # Any converged tick persists via the post-tick hook...
        manager.engine.schedule(*small_world(n=32, c=4))
        # ...and shutdown() drains + writes a final generation.
        summary = manager.shutdown(deadline_s=5.0)
        assert summary["elapsed_s"] < 30
        store = manager.snapshots.store
        assert store.load_latest() is not None

        # A successor manager over the same dir stages the restore.
        m2 = ControllerManager(ClusterFleet())
        assert m2.snapshots.restore() == "staged"
        m2.engine.schedule(*small_world(n=32, c=4))
        assert m2.engine.restore_info["result"] == "loaded"


# -- subprocess kill matrix ------------------------------------------------
def _driver_env(workdir, phase="", prewarm=False, aot=False):
    env = os.environ.copy()
    for k in ("XLA_FLAGS", "PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
        env.pop(k, None)
    env.update(
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO,
        KT_RESTART_DIR=str(workdir),
        KT_RESTART_KILL_PHASE=phase,
        KT_RESTART_OBJECTS="160",
        KT_RESTART_CLUSTERS="10",
        KT_RESTART_PREWARM="1" if prewarm else "0",
        KT_AOT="1" if aot else "0",
        KT_BREAKER_OPEN_S="300",
        KT_COMPILE_CACHE_DIR=os.path.join(str(workdir), "xla"),
        KT_FLIGHTREC="1",
    )
    env.pop("KT_SNAPSHOT_KILL", None)
    return env


def _run_driver(mode, workdir, phase="", expect_kill=False, artifact=None,
                prewarm=False, aot=False):
    env = _driver_env(workdir, phase=phase, prewarm=prewarm, aot=aot)
    if artifact:
        env["KT_RESTART_ARTIFACT"] = artifact
    proc = subprocess.run(
        [sys.executable, DRIVER, mode],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    if expect_kill:
        assert proc.returncode == -9, (
            f"victim exited {proc.returncode} (kill never fired)\n"
            f"{proc.stdout}\n{proc.stderr}"
        )
    else:
        assert proc.returncode == 0, f"{proc.stdout}\n{proc.stderr}"
    return proc


@pytest.fixture(scope="module")
def reference_artifact(tmp_path_factory):
    workdir = tmp_path_factory.mktemp("restart-ref")
    _run_driver("reference", workdir)
    return json.load(open(os.path.join(workdir, "reference.json")))


def _kill_matrix_round(tmp_path_factory, phase, reference):
    workdir = tmp_path_factory.mktemp(f"restart-{phase}")
    _run_driver("victim", workdir, phase=phase, expect_kill=True)
    assert os.path.exists(os.path.join(workdir, "tick2.done"))
    if phase not in ("dispatch-flush",):
        assert not os.path.exists(os.path.join(workdir, "tick3.done"))
    _run_driver("successor", workdir)
    succ = json.load(open(os.path.join(workdir, "successor.json")))
    assert succ["restore"] == "staged"
    assert succ["restore_info"]["result"] == "loaded"
    # Bit-identical convergence: placements AND flight-recorder reason
    # counts match the uninterrupted run exactly.
    assert succ["placements"] == reference["placements"]
    assert succ["reason_counts"] == reference["reason_counts"]
    # The pre-crash OPEN breaker survived the restart: the member stays
    # short-circuited, no free probe storm.
    assert succ["breaker_m001"] == "open"
    assert succ["breaker_allows_m001"] is False
    # Torn writes leave temp files the loader ignores; nothing valid
    # was quarantined along the way.
    assert succ["quarantined"] == []
    return succ


class TestKillMatrix:
    def test_sigkill_mid_snapshot_write(self, tmp_path_factory, reference_artifact):
        """The tier-1 representative: die with the snapshot payload
        half-written; the successor loads the previous generation and
        still converges bit-identically."""
        succ = _kill_matrix_round(
            tmp_path_factory, "snapshot-write", reference_artifact
        )
        # tick 3 never persisted: the successor resumed from tick 2 and
        # re-decided the tick-3 churn rows through the sub-batch path.
        assert succ["fetch_paths"]["subbatch"] >= 1 or (
            succ["fetch_paths"]["noop"] >= 1
        )

    @pytest.mark.slow
    @pytest.mark.parametrize(
        "phase",
        ["featurize", "dispatch", "fetch", "snapshot-rename", "dispatch-flush"],
    )
    def test_sigkill_phase(self, tmp_path_factory, reference_artifact, phase):
        _kill_matrix_round(tmp_path_factory, phase, reference_artifact)


class TestWarmBootAot:
    @pytest.mark.slow
    def test_second_warm_boot_covers_ladder_from_caches(self, tmp_path_factory):
        """The persistent-cache assertion (satellite): on the SECOND
        warm boot the AOT manifest serves every ladder program
        (loaded, zero live traces) and every XLA compile is a
        persistent-cache hit — zero misses — so silent cache-key drift
        fails this test instead of only dimming a telemetry counter."""
        workdir = tmp_path_factory.mktemp("restart-aot")
        _run_driver("victim", workdir, phase="snapshot-write",
                    expect_kill=True, prewarm=True, aot=True)
        _run_driver("successor", workdir, artifact="succ1.json",
                    prewarm=True, aot=True)
        s1 = json.load(open(os.path.join(workdir, "succ1.json")))
        assert s1["aot"]["loaded"] > 0, s1["aot"]
        _run_driver("successor", workdir, artifact="succ2.json",
                    prewarm=True, aot=True)
        s2 = json.load(open(os.path.join(workdir, "succ2.json")))
        assert s2["aot"]["loaded"] > 0
        assert s2["aot"]["rejected"] == 0
        counters = s2["counters"]
        hits = counters.get('engine_persistent_cache_total{result=hit}', 0)
        misses = counters.get('engine_persistent_cache_total{result=miss}', 0)
        assert hits >= s2["aot"]["loaded"], counters
        assert misses == 0, (
            f"second warm boot recompiled {misses} program(s): "
            f"persistent-cache key drift ({counters})"
        )
