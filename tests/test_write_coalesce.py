"""Write-path coalescing window (ISSUE 15): batch x breaker x deadline.

Covers the per-member coalescing window in federation/dispatch.py
(run_member_batches + the BatchSink/ImmediateSink staging growth):

* chunking honors KT_MEMBER_BATCH / KT_WRITE_COALESCE, result order is
  per-op stable, continuations fire per item off the batch ack;
* an open-breaker member sheds a whole staged batch without a socket;
* mid-batch deadline expiry sheds the remainder (member_shed_writes
  counted, statuses stay at their pre-recorded *_TIMED_OUT values);
* a partial batch failure retries only the failed items;
* KT_WRITE_COALESCE=0 A/B: member-visible objects and propagation
  statuses bit-identical to the coalesced path;
* queue-depth-driven admission backpressure (runtime/worker.py) and the
  drain cap;
* the watch-boundary trigger filters (status-only fed writes do not
  re-enqueue scheduler/override/federate);
* sync's bulk member-read prefetch over a real HTTP farm.
"""

from __future__ import annotations

import threading
import time

import pytest

from kubeadmiral_tpu.federation import dispatch as D
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import BatchWorker, Worker
from kubeadmiral_tpu.testing.fakekube import ClusterFleet, FakeKube
from kubeadmiral_tpu.transport import breaker as B


class RecordingKube:
    """FakeKube-duck client recording every batch() call (sizes + ops);
    NOT a FakeKube subclass, so the coalescing window treats it as a
    network client (pipelining + stall-capable paths engage)."""

    def __init__(self, inner=None, fail_keys=(), fail_times=1, batch_delay=0.0):
        self.inner = inner or FakeKube("m")
        self.calls: list[list[dict]] = []
        self.fail_keys = set(fail_keys)
        self.fail_remaining = {k: fail_times for k in self.fail_keys}
        self.batch_delay = batch_delay
        self._lock = threading.Lock()

    def batch(self, ops):
        with self._lock:
            self.calls.append([dict(op) for op in ops])
        if self.batch_delay:
            time.sleep(self.batch_delay)
        results = []
        for op in ops:
            name = (op.get("object") or {}).get("metadata", {}).get("name") or op.get("key")
            with self._lock:
                left = self.fail_remaining.get(name, 0)
                if left > 0:
                    self.fail_remaining[name] = left - 1
                    results.append({"code": 500, "status": {
                        "reason": "InternalError", "message": "flaky"}})
                    continue
            results.extend(self.inner.batch([op]))
        return results

    def __getattr__(self, item):
        return getattr(self.inner, item)


def _create_op(i):
    return {
        "verb": "create",
        "resource": "v1/pods",
        "object": {"metadata": {"name": f"p-{i:03d}"}, "spec": {}},
    }


class TestRunMemberBatches:
    def test_chunking_and_order(self, monkeypatch):
        monkeypatch.setenv("KT_MEMBER_BATCH", "4")
        monkeypatch.setenv("KT_MEMBER_INFLIGHT", "1")
        client = RecordingKube()
        ops = [_create_op(i) for i in range(10)]
        out = D.run_member_batches(client, ops, time.monotonic() + 5.0, cluster="m")
        assert [len(c) for c in client.calls] == [4, 4, 2]
        assert len(out) == 10
        # Per-op result order matches the op order.
        for i, res in enumerate(out):
            assert res["code"] == 201
            assert res["object"]["metadata"]["name"] == f"p-{i:03d}"

    def test_pipelined_window_preserves_order(self, monkeypatch):
        monkeypatch.setenv("KT_MEMBER_BATCH", "2")
        monkeypatch.setenv("KT_MEMBER_INFLIGHT", "3")
        client = RecordingKube()
        ops = [_create_op(i) for i in range(9)]
        out = D.run_member_batches(client, ops, time.monotonic() + 5.0, cluster="m")
        assert len(client.calls) == 5  # ceil(9 / 2)
        assert [r["object"]["metadata"]["name"] for r in out] == [
            f"p-{i:03d}" for i in range(9)
        ]

    def test_coalesce_off_is_per_object(self, monkeypatch):
        monkeypatch.setenv("KT_WRITE_COALESCE", "0")
        monkeypatch.setenv("KT_MEMBER_INFLIGHT", "1")
        client = RecordingKube()
        ops = [_create_op(i) for i in range(5)]
        D.run_member_batches(client, ops, time.monotonic() + 5.0, cluster="m")
        assert [len(c) for c in client.calls] == [1] * 5

    def test_partial_failure_retries_only_failed_items(self, monkeypatch):
        monkeypatch.setenv("KT_MEMBER_BATCH", "8")
        monkeypatch.setenv("KT_RETRY_BASE_S", "0.001")
        client = RecordingKube(fail_keys={"p-002"}, fail_times=1)
        ops = [_create_op(i) for i in range(6)]
        out = D.run_member_batches(client, ops, time.monotonic() + 5.0, cluster="m")
        assert all(r["code"] == 201 for r in out)
        # First request carried all 6 ops; the retry carried ONLY the
        # failed item.
        assert len(client.calls[0]) == 6
        retried = client.calls[1]
        assert len(retried) == 1
        assert retried[0]["object"]["metadata"]["name"] == "p-002"

    def test_mid_batch_deadline_sheds_remainder(self, monkeypatch):
        monkeypatch.setenv("KT_MEMBER_BATCH", "2")
        monkeypatch.setenv("KT_MEMBER_INFLIGHT", "1")
        monkeypatch.setenv("KT_RETRY_MAX", "0")
        metrics = Metrics()
        registry = B.BreakerRegistry(metrics=metrics)
        # Each chunk takes ~80 ms; the deadline allows roughly one.
        client = RecordingKube(batch_delay=0.08)
        ops = [_create_op(i) for i in range(10)]
        out = D.run_member_batches(
            client, ops, time.monotonic() + 0.1, cluster="m", breakers=registry
        )
        shed = [r for r in out if r.get("shed")]
        landed = [r for r in out if not r.get("shed")]
        assert shed and landed, (len(shed), len(landed))
        assert len(out) == 10
        # Shed ops counted via the registry (member_shed_writes_total).
        assert registry.shed_total() == len(shed)
        # The landed prefix is contiguous: ops dispatch in order.
        assert all(r["code"] == 201 for r in landed)

    def test_breaker_open_mid_flush_stops_sockets(self, monkeypatch):
        monkeypatch.setenv("KT_MEMBER_BATCH", "2")
        monkeypatch.setenv("KT_MEMBER_INFLIGHT", "1")
        registry = B.BreakerRegistry(metrics=Metrics())
        client = RecordingKube()
        for _ in range(10):
            registry.for_member("m").record_failure()
        assert not registry.allow("m", consume_probe=False)
        ops = [_create_op(i) for i in range(6)]
        out = D.run_member_batches(
            client, ops, time.monotonic() + 5.0, cluster="m", breakers=registry
        )
        assert client.calls == []  # not a single socket touched
        assert all(r.get("shed") for r in out)
        assert registry.shed_total() == 6


class TestBatchSinkCoalesce:
    def test_open_breaker_sheds_whole_staged_batch_without_socket(self):
        registry = B.BreakerRegistry(metrics=Metrics())
        for _ in range(10):
            registry.for_member("m").record_failure()
        client = RecordingKube()
        sink = D.BatchSink(lambda c: client, breakers=registry)
        statuses = []
        for i in range(5):
            sink.submit("m", _create_op(i), statuses.append)
        sink.flush(timeout=5.0)
        assert client.calls == []  # shed at flush time, no socket
        assert statuses == []      # continuations never ran
        assert registry.shed_total() >= 5

    def test_batch_telemetry_emitted(self, monkeypatch):
        monkeypatch.setenv("KT_MEMBER_BATCH", "3")
        metrics = Metrics()
        registry = B.BreakerRegistry(metrics=metrics)
        client = RecordingKube()
        sink = D.BatchSink(lambda c: client, breakers=registry)
        done = []
        for i in range(7):
            sink.submit("m", _create_op(i), done.append)
        sink.flush(timeout=5.0)
        assert len(done) == 7
        snap = registry.snapshot()["m"]
        assert snap["batch"]["requests"].get("ok", 0) == 3  # ceil(7/3)
        assert snap["batch"]["max_ops"] == 3
        assert metrics.counters.get(
            "member_bulk_writes_total{cluster=m,result=ok}"
        ) == 3


class TestCoalesceAB:
    """KT_WRITE_COALESCE=0 must produce bit-identical member objects and
    propagation statuses (the acceptance A/B)."""

    def _run_world(self, monkeypatch, coalesce: str):
        import dataclasses

        from kubeadmiral_tpu.federation.sync import SyncController
        from kubeadmiral_tpu.models.ftc import default_ftcs

        monkeypatch.setenv("KT_WRITE_COALESCE", coalesce)
        monkeypatch.setenv("KT_MEMBER_BATCH", "3")
        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        ftc = dataclasses.replace(ftc, controllers=(), revision_history=False)
        fleet = ClusterFleet()
        for name in ("m-1", "m-2", "m-3"):
            fleet.add_member(name)
            fleet.host.create(
                "core.kubeadmiral.io/v1alpha1/federatedclusters",
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": {},
                    "status": {"conditions": [
                        {"type": "Joined", "status": "True"},
                        {"type": "Ready", "status": "True"},
                    ]},
                },
            )
        ctl = SyncController(fleet, ftc)
        for i in range(8):
            fed = {
                "apiVersion": ftc.federated.api_version,
                "kind": ftc.federated.kind,
                "metadata": {
                    "name": f"web-{i}",
                    "namespace": "default",
                    "annotations": {
                        "kubeadmiral.io/pending-controllers": "[]",
                    },
                },
                "spec": {
                    "template": {
                        "apiVersion": "apps/v1",
                        "kind": "Deployment",
                        "metadata": {"name": f"web-{i}", "namespace": "default"},
                        "spec": {"replicas": i + 1},
                    },
                    "placements": [
                        {
                            "controller": "kubeadmiral.io/global-scheduler",
                            "placement": [
                                {"cluster": "m-1"},
                                {"cluster": "m-2" if i % 2 else "m-3"},
                            ],
                        }
                    ],
                },
            }
            fleet.host.create(ftc.federated.resource, fed)
        while ctl.worker.step():
            pass
        dump = {}
        for name in ("m-1", "m-2", "m-3"):
            member = fleet.member(name)
            dump[name] = {
                key: _strip_volatile(member.get(ftc.source.resource, key))
                for key in sorted(member.keys(ftc.source.resource))
            }
        statuses = {}
        for key in sorted(fleet.host.keys(ftc.federated.resource)):
            fed = fleet.host.get(ftc.federated.resource, key)
            statuses[key] = (fed.get("status") or {}).get("clusters")
        return dump, statuses

    def test_ab_bit_identical(self, monkeypatch):
        on_dump, on_status = self._run_world(monkeypatch, "1")
        off_dump, off_status = self._run_world(monkeypatch, "0")
        assert on_dump == off_dump
        assert on_status == off_status
        # Sanity: the world actually propagated.
        assert any(on_dump[m] for m in on_dump)
        assert all(
            all(e["status"] == "OK" for e in entries)
            for entries in on_status.values()
            if entries
        )


def _strip_volatile(obj: dict) -> dict:
    """Drop per-store sequencing fields that legitimately differ between
    two separately-run worlds (rv/uid are allocation counters)."""
    import copy

    out = copy.deepcopy(obj)
    out.get("metadata", {}).pop("resourceVersion", None)
    out.get("metadata", {}).pop("uid", None)
    return out


class TestAdmission:
    def test_enqueue_past_depth_defers(self, monkeypatch):
        monkeypatch.setenv("KT_ADMIT_DEPTH", "10")
        monkeypatch.setenv("KT_ADMIT_DELAY_MS", "200")
        metrics = Metrics()
        w = BatchWorker("admit-test", lambda keys: {}, metrics=metrics)
        for i in range(11):
            w.enqueue(f"k-{i}")
        # Depth is now 11 > 10: the next enqueue defers.
        w.enqueue("late")
        due = w.queue.drain_due()
        assert "late" not in due
        assert len(due) == 11
        assert w.queue.next_due_in() is not None

    def test_admission_disabled(self, monkeypatch):
        monkeypatch.setenv("KT_ADMIT_DEPTH", "0")
        w = Worker("admit-off", lambda k: None)
        for i in range(50):
            w.enqueue(f"k-{i}")
        assert len(w.queue.drain_due()) == 50

    def test_drain_cap(self, monkeypatch):
        monkeypatch.setenv("KT_ADMIT_BATCH", "5")
        seen = []

        def tick(keys):
            seen.append(list(keys))
            return {}

        w = BatchWorker("drain-cap", tick, metrics=Metrics())
        monkeypatch.setenv("KT_ADMIT_DEPTH", "0")
        for i in range(12):
            w.enqueue(f"k-{i}")
        while w.step():
            pass
        assert [len(batch) for batch in seen] == [5, 5, 2]


class TestEventSigFilters:
    """Status-only fed writes must not re-enqueue the scheduling-side
    controllers (the watch-boundary half of admission backpressure)."""

    def _fed(self, gen=1, status=None, ann=None):
        obj = {
            "metadata": {
                "name": "web", "namespace": "d", "generation": gen,
                "labels": {"app": "web"},
                "annotations": dict(ann or {}),
            },
            "spec": {"template": {}},
        }
        if status is not None:
            obj["status"] = status
        return obj

    def test_scheduler_skips_status_only_writes(self):
        import dataclasses

        from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
        from kubeadmiral_tpu.models.ftc import default_ftcs

        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        host = FakeKube("host")
        ctl = SchedulerController(host, ftc)
        ctl.worker.queue.drain_due()  # clear replay noise
        ctl._on_object_event("ADDED", self._fed())
        assert ctl.worker.queue.drain_due() == ["d/web"]
        # Same metadata, status changed: a status-subresource write.
        ctl._on_object_event("MODIFIED", self._fed(status={"clusters": []}))
        assert ctl.worker.queue.drain_due() == []
        # Generation bump (spec change): re-enqueues.
        ctl._on_object_event("MODIFIED", self._fed(gen=2))
        assert ctl.worker.queue.drain_due() == ["d/web"]
        # Syncing-feedback annotation churn: filtered noise.
        ctl._on_object_event(
            "MODIFIED",
            self._fed(gen=2, ann={"kubeadmiral.io/syncing": "{...}"}),
        )
        assert ctl.worker.queue.drain_due() == []
        # Any other annotation (pending-controllers advance): enqueues.
        ctl._on_object_event(
            "MODIFIED",
            self._fed(gen=2, ann={"kubeadmiral.io/pending-controllers": "[]"}),
        )
        assert ctl.worker.queue.drain_due() == ["d/web"]
        # DELETED always enqueues and clears the sig.
        ctl._on_object_event("DELETED", self._fed(gen=2))
        assert ctl.worker.queue.drain_due() == ["d/web"]

    def test_federate_skips_status_only_fed_writes(self):
        from kubeadmiral_tpu.federation.federate import FederateController
        from kubeadmiral_tpu.models.ftc import default_ftcs

        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        host = FakeKube("host")
        ctl = FederateController(host, ftc)
        ctl.worker.queue.drain_due()
        ctl._on_fed_event("ADDED", self._fed())
        assert ctl.worker.queue.drain_due() == ["d/web"]
        ctl._on_fed_event("MODIFIED", self._fed(status={"clusters": []}))
        assert ctl.worker.queue.drain_due() == []
        # The syncing annotation IS federate's trigger (it mirrors it to
        # the source): must re-enqueue.
        ctl._on_fed_event(
            "MODIFIED", self._fed(ann={"kubeadmiral.io/syncing": "{}"})
        )
        assert ctl.worker.queue.drain_due() == ["d/web"]


@pytest.mark.slow
class TestBulkReadsHttp:
    """Sync's bulk member-read prefetch over a real HTTP farm: the
    propagated world must be identical with the prefetch on and off."""

    def _world(self, monkeypatch, bulk: str):
        import dataclasses

        from kubeadmiral_tpu.federation.clusterctl import (
            FederatedClusterController,
            NODES,
        )
        from kubeadmiral_tpu.federation.sync import SyncController
        from kubeadmiral_tpu.models.ftc import default_ftcs
        from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm

        monkeypatch.setenv("KT_BULK_READS", bulk)
        gvk = "apps/v1/Deployment"
        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        ftc = dataclasses.replace(ftc, controllers=(), revision_history=False)
        farm = KwokLiteFarm()
        try:
            cluster_ctl = FederatedClusterController(
                farm.fleet, api_resource_probe=[gvk]
            )
            members = {}
            for name in ("m-1", "m-2"):
                member = farm.add_member(name)
                members[name] = member
                member.create(NODES, {
                    "apiVersion": "v1", "kind": "Node",
                    "metadata": {"name": "n1"}, "spec": {},
                    "status": {"allocatable": {"cpu": "32", "memory": "64Gi"},
                               "conditions": [{"type": "Ready", "status": "True"}]},
                })
                farm.fleet.host.create(
                    "core.kubeadmiral.io/v1alpha1/federatedclusters",
                    {
                        "apiVersion": "core.kubeadmiral.io/v1alpha1",
                        "kind": "FederatedCluster",
                        "metadata": {"name": name},
                        "spec": farm.cluster_spec(name),
                    },
                )
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                while cluster_ctl.worker.step():
                    pass
                joined = [
                    c for c in farm.fleet.host.list(
                        "core.kubeadmiral.io/v1alpha1/federatedclusters"
                    )
                    if any(
                        cond.get("type") == "Ready" and cond.get("status") == "True"
                        for cond in c.get("status", {}).get("conditions", [])
                    )
                ]
                if len(joined) == 2:
                    break
                time.sleep(0.1)
            assert len(joined) == 2, "members never joined"
            sync = SyncController(farm.fleet, ftc)
            assert sync._bulk_reads == (bulk != "0")
            for i in range(6):
                farm.fleet.host.create(ftc.federated.resource, {
                    "apiVersion": ftc.federated.api_version,
                    "kind": ftc.federated.kind,
                    "metadata": {
                        "name": f"w-{i}", "namespace": "default",
                        "annotations": {
                            "kubeadmiral.io/pending-controllers": "[]"},
                    },
                    "spec": {
                        "template": {
                            "apiVersion": "apps/v1", "kind": "Deployment",
                            "metadata": {"name": f"w-{i}",
                                         "namespace": "default"},
                            "spec": {"replicas": 1 + i},
                        },
                        "placements": [{
                            "controller": "kubeadmiral.io/global-scheduler",
                            "placement": [{"cluster": "m-1"},
                                          {"cluster": "m-2"}],
                        }],
                    },
                })
            deadline = time.monotonic() + 30.0
            want = {f"default/w-{i}" for i in range(6)}
            while time.monotonic() < deadline:
                while sync.worker.step():
                    pass
                done = all(
                    set(members[m].keys(ftc.source.resource)) >= want
                    for m in members
                )
                if done:
                    break
                time.sleep(0.1)
            out = {
                m: {
                    k: _strip_volatile(members[m].get(ftc.source.resource, k))
                    for k in sorted(members[m].keys(ftc.source.resource))
                }
                for m in members
            }
            return out
        finally:
            farm.close()

    def test_bulk_vs_direct_identical(self, monkeypatch):
        bulk = self._world(monkeypatch, "1")
        direct = self._world(monkeypatch, "0")
        assert bulk == direct
        assert all(len(v) == 6 for v in bulk.values())
