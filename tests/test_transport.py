"""The real HTTP transport: REST semantics, watch streams, auth, fleet.

Everything FakeKube guarantees in-process must survive the network hop:
optimistic concurrency, finalizer-gated deletion, status subresource,
label-selector lists, LIST+WATCH with resourceVersion resume and 410
relist, bearer-token auth with service-account token minting.
"""

import json
import threading
import time

import pytest

from kubeadmiral_tpu.testing.fakekube import (
    AlreadyExists,
    Conflict,
    FakeKube,
    NotFound,
)
from kubeadmiral_tpu.transport.apiserver import KubeApiServer
from kubeadmiral_tpu.transport.client import (
    FederatedClientFactory,
    HttpKube,
    TransportError,
)
from kubeadmiral_tpu.transport.paths import parse_path, resource_to_path

DEPLOYMENTS = "apps/v1/deployments"
CONFIGMAPS = "v1/configmaps"


def wait_for(predicate, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def make_obj(name="web", ns="default", labels=None, spec=None):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": ns, "labels": labels or {}},
        "spec": spec or {"replicas": 1},
    }


@pytest.fixture()
def server():
    store = FakeKube("test")
    srv = KubeApiServer(store)
    yield srv
    srv.close()


@pytest.fixture()
def kube(server):
    client = HttpKube(server.url, name="test")
    yield client
    client.close()


class TestPaths:
    def test_roundtrip(self):
        cases = [
            ("v1/pods", "default", "web", None),
            ("v1/nodes", None, "n1", None),
            ("apps/v1/deployments", "default", "web", "status"),
            ("core.kubeadmiral.io/v1alpha1/federatedclusters", None, "c1", None),
            ("apps/v1/statefulsets", None, None, None),
        ]
        for resource, ns, name, sub in cases:
            path = resource_to_path(resource, ns, name, sub)
            parsed = parse_path(path)
            assert parsed.resource == resource
            assert (parsed.namespace or None) == ns
            assert parsed.name == name
            assert parsed.subresource == sub

    def test_namespaces_resource_itself(self):
        assert parse_path("/api/v1/namespaces") == ("v1/namespaces", None, None, None)
        assert parse_path("/api/v1/namespaces/foo") == (
            "v1/namespaces", None, "foo", None,
        )
        assert parse_path("/api/v1/namespaces/foo/status") == (
            "v1/namespaces", None, "foo", "status",
        )
        assert parse_path("/api/v1/namespaces/foo/pods/web") == (
            "v1/pods", "foo", "web", None,
        )


class TestCrud:
    def test_create_get_roundtrip(self, kube):
        created = kube.create(DEPLOYMENTS, make_obj())
        assert created["metadata"]["resourceVersion"]
        assert created["metadata"]["uid"]
        got = kube.get(DEPLOYMENTS, "default/web")
        assert got == created

    def test_create_conflict(self, kube):
        kube.create(DEPLOYMENTS, make_obj())
        with pytest.raises(AlreadyExists):
            kube.create(DEPLOYMENTS, make_obj())

    def test_get_not_found(self, kube):
        with pytest.raises(NotFound):
            kube.get(DEPLOYMENTS, "default/nope")
        assert kube.try_get(DEPLOYMENTS, "default/nope") is None

    def test_update_optimistic_concurrency(self, kube):
        obj = kube.create(DEPLOYMENTS, make_obj())
        stale = dict(obj, metadata=dict(obj["metadata"]))
        obj["spec"] = {"replicas": 3}
        updated = kube.update(DEPLOYMENTS, obj)
        assert updated["spec"] == {"replicas": 3}
        assert updated["metadata"]["generation"] == 2
        stale["spec"] = {"replicas": 9}
        with pytest.raises(Conflict):
            kube.update(DEPLOYMENTS, stale)

    def test_status_subresource_only_touches_status(self, kube):
        obj = kube.create(DEPLOYMENTS, make_obj())
        obj["spec"] = {"replicas": 99}  # must NOT be applied
        obj["status"] = {"readyReplicas": 1}
        updated = kube.update_status(DEPLOYMENTS, obj)
        assert updated["status"] == {"readyReplicas": 1}
        assert updated["spec"] == {"replicas": 1}
        assert updated["metadata"]["generation"] == 1

    def test_finalizer_gated_delete(self, kube):
        obj = make_obj()
        obj["metadata"]["finalizers"] = ["test/finalizer"]
        kube.create(DEPLOYMENTS, obj)
        kube.delete(DEPLOYMENTS, "default/web")
        pending = kube.get(DEPLOYMENTS, "default/web")
        assert pending["metadata"]["deletionTimestamp"]
        pending["metadata"]["finalizers"] = []
        kube.update(DEPLOYMENTS, pending)
        assert kube.try_get(DEPLOYMENTS, "default/web") is None

    def test_cluster_scoped_resource(self, kube):
        kube.create("v1/nodes", {"apiVersion": "v1", "kind": "Node",
                                 "metadata": {"name": "n1"}, "spec": {}})
        assert kube.get("v1/nodes", "n1")["metadata"]["name"] == "n1"
        assert kube.keys("v1/nodes") == ["n1"]
        kube.delete("v1/nodes", "n1")
        assert kube.try_get("v1/nodes", "n1") is None

    def test_list_namespace_and_selector(self, kube):
        kube.create(DEPLOYMENTS, make_obj("a", "ns1", {"app": "x"}))
        kube.create(DEPLOYMENTS, make_obj("b", "ns1", {"app": "y"}))
        kube.create(DEPLOYMENTS, make_obj("c", "ns2", {"app": "x"}))
        assert {o["metadata"]["name"] for o in kube.list(DEPLOYMENTS)} == {
            "a", "b", "c",
        }
        assert {o["metadata"]["name"] for o in kube.list(DEPLOYMENTS, "ns1")} == {
            "a", "b",
        }
        sel = {o["metadata"]["name"]
               for o in kube.list(DEPLOYMENTS, label_selector={"app": "x"})}
        assert sel == {"a", "c"}


class TestWatch:
    def test_replay_and_live_events(self, kube):
        kube.create(DEPLOYMENTS, make_obj("pre"))
        events = []
        cond = threading.Condition()

        def handler(event, obj):
            with cond:
                events.append((event, obj["metadata"]["name"]))
                cond.notify_all()

        kube.watch(DEPLOYMENTS, handler, replay=True)
        assert ("ADDED", "pre") in events

        kube.create(DEPLOYMENTS, make_obj("live"))
        assert wait_for(lambda: ("ADDED", "live") in events)
        live = kube.get(DEPLOYMENTS, "default/live")
        live["spec"] = {"replicas": 5}
        kube.update(DEPLOYMENTS, live)
        assert wait_for(lambda: ("MODIFIED", "live") in events)
        kube.delete(DEPLOYMENTS, "default/live")
        assert wait_for(lambda: ("DELETED", "live") in events)

    def test_two_handlers_share_stream(self, kube):
        seen1, seen2 = [], []
        kube.watch(DEPLOYMENTS, lambda e, o: seen1.append(o["metadata"]["name"]))
        kube.watch(DEPLOYMENTS, lambda e, o: seen2.append(o["metadata"]["name"]))
        kube.create(DEPLOYMENTS, make_obj("shared"))
        assert wait_for(lambda: "shared" in seen1 and "shared" in seen2)

    def test_unwatch_owner_detaches(self, kube):
        class Ctl:
            def __init__(self):
                self.seen = []

            def on_event(self, event, obj):
                self.seen.append(obj["metadata"]["name"])

        ctl = Ctl()
        kube.watch(DEPLOYMENTS, ctl.on_event, replay=False)
        kube.create(DEPLOYMENTS, make_obj("one"))
        assert wait_for(lambda: "one" in ctl.seen)
        kube.unwatch_owner(ctl)
        kube.create(DEPLOYMENTS, make_obj("two"))
        time.sleep(0.3)
        assert "two" not in ctl.seen

    def test_410_relist_recovers(self):
        store = FakeKube("tiny")
        srv = KubeApiServer(store, event_log_cap=4)
        client = HttpKube(srv.url, name="tiny")
        try:
            seen = set()
            client.watch(
                CONFIGMAPS,
                lambda e, o: seen.add(o["metadata"]["name"]),
                replay=True,
            )
            # Overflow the 4-event log while the stream is mid-flight;
            # the reflector must relist on Gone and keep going.
            for i in range(40):
                store.create(
                    CONFIGMAPS,
                    {"apiVersion": "v1", "kind": "ConfigMap",
                     "metadata": {"name": f"cm-{i}", "namespace": "d"}},
                )
            assert wait_for(lambda: len(seen) == 40, timeout=10.0), len(seen)
        finally:
            client.close()
            srv.close()


    def test_delete_during_log_truncation_synthesizes_deleted(self):
        """An object deleted while the watch log is truncated must still
        surface as DELETED: the reflector relists after 410 Gone and
        tombstones keys missing from the relist."""
        store = FakeKube("tiny")
        srv = KubeApiServer(store, event_log_cap=4)
        client = HttpKube(srv.url, name="tiny")
        try:
            store.create(
                CONFIGMAPS,
                {"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "victim", "namespace": "d"}},
            )
            events = []
            client.watch(
                CONFIGMAPS,
                lambda e, o: events.append((e, o["metadata"]["name"])),
                replay=True,
            )
            assert wait_for(lambda: ("ADDED", "victim") in events)
            # Hold the event-log condition (reentrant) so the stream
            # thread cannot drain while we delete + overflow the log:
            # the delete event is guaranteed evicted before it is read.
            with srv._log.cond:
                store.delete(CONFIGMAPS, "d/victim")
                for i in range(20):
                    store.create(
                        CONFIGMAPS,
                        {"apiVersion": "v1", "kind": "ConfigMap",
                         "metadata": {"name": f"f-{i}", "namespace": "d"}},
                    )
            assert wait_for(
                lambda: ("DELETED", "victim") in events, timeout=10.0
            ), events[-5:]
        finally:
            client.close()
            srv.close()


class TestAuth:
    def test_rejects_bad_token(self):
        store = FakeKube("m")
        srv = KubeApiServer(store, admin_token="sekrit")
        try:
            bad = HttpKube(srv.url, token="wrong")
            with pytest.raises(TransportError, match="401"):
                bad.list(DEPLOYMENTS)
            bad.close()
            good = HttpKube(srv.url, token="sekrit")
            assert good.list(DEPLOYMENTS) == []
            good.close()
        finally:
            srv.close()

    def test_minted_sa_token_authorizes(self):
        store = FakeKube("m")
        srv = KubeApiServer(store, admin_token="sekrit", mint_sa_tokens=True)
        try:
            admin = HttpKube(srv.url, token="sekrit")
            admin.create(
                "v1/serviceaccounts",
                {"apiVersion": "v1", "kind": "ServiceAccount",
                 "metadata": {"name": "bot", "namespace": "sys"}},
            )
            minted = admin.get("v1/secrets", "sys/bot-token")
            token = minted["data"]["token"]
            sa_client = HttpKube(srv.url, token=token)
            assert sa_client.list(DEPLOYMENTS) == []
            sa_client.close()
            admin.close()
        finally:
            srv.close()

    def test_healthz_reflects_store_health(self, server, kube):
        assert kube.healthy
        server.store.healthy = False
        assert not kube.healthy
        server.store.healthy = True
        assert kube.healthy


class TestFactory:
    def test_client_from_join_secret(self):
        host_store = FakeKube("host")
        host_srv = KubeApiServer(host_store)
        member_store = FakeKube("m1")
        member_srv = KubeApiServer(member_store, admin_token="tok-m1")
        host = HttpKube(host_srv.url)
        try:
            host.create(
                "v1/secrets",
                {"apiVersion": "v1", "kind": "Secret",
                 "metadata": {"name": "m1-secret",
                              "namespace": "kube-admiral-system"},
                 "data": {"token": "tok-m1"}},
            )
            factory = FederatedClientFactory(host)
            cluster = {
                "metadata": {"name": "m1"},
                "spec": {"apiEndpoint": member_srv.url,
                         "secretRef": {"name": "m1-secret"}},
            }
            client = factory.client_for(cluster)
            assert client.healthy
            assert client.list(DEPLOYMENTS) == []
            # Cached by (endpoint, token).
            assert factory.client_for(cluster) is client
            factory.close()
        finally:
            host.close()
            member_srv.close()
            host_srv.close()


class TestTokenTrustBoundary:
    def test_workload_secret_with_token_type_is_not_a_credential(self):
        """A client-created Secret merely CLAIMING the service-account-
        token type (e.g. a federated user Secret propagated by sync)
        must not become an apiserver credential: only secrets whose
        kubernetes.io/service-account.name annotation references an
        existing ServiceAccount count (ADVICE r2)."""
        store = FakeKube("m")
        srv = KubeApiServer(store, admin_token="sekrit", mint_sa_tokens=True)
        try:
            admin = HttpKube(srv.url, token="sekrit")
            # No annotation at all.
            admin.create(
                "v1/secrets",
                {"apiVersion": "v1", "kind": "Secret",
                 "type": "kubernetes.io/service-account-token",
                 "metadata": {"name": "evil1", "namespace": "default"},
                 "data": {"token": "evil-token-1"}},
            )
            # Annotation referencing a ServiceAccount that doesn't exist.
            admin.create(
                "v1/secrets",
                {"apiVersion": "v1", "kind": "Secret",
                 "type": "kubernetes.io/service-account-token",
                 "metadata": {
                     "name": "evil2", "namespace": "default",
                     "annotations": {
                         "kubernetes.io/service-account.name": "ghost"
                     },
                 },
                 "data": {"token": "evil-token-2"}},
            )
            for token in ("evil-token-1", "evil-token-2"):
                bad = HttpKube(srv.url, token=token)
                with pytest.raises(TransportError, match="401"):
                    bad.list(DEPLOYMENTS)
                bad.close()
            # The genuinely minted token still works.
            admin.create(
                "v1/serviceaccounts",
                {"apiVersion": "v1", "kind": "ServiceAccount",
                 "metadata": {"name": "bot", "namespace": "sys"}},
            )
            minted = admin.get("v1/secrets", "sys/bot-token")
            good = HttpKube(srv.url, token=minted["data"]["token"])
            assert good.list(DEPLOYMENTS) == []
            good.close()
            admin.close()
        finally:
            srv.close()

    def test_revocation_survives_sa_deleted_first(self):
        """Deleting the ServiceAccount revokes the credential AND
        garbage-collects its minted token secret (the token-controller
        GC real apiservers perform) — no live credential or orphaned
        secret remains regardless of deletion order."""
        store = FakeKube("m")
        srv = KubeApiServer(store, admin_token="sekrit", mint_sa_tokens=True)
        try:
            admin = HttpKube(srv.url, token="sekrit")
            admin.create(
                "v1/serviceaccounts",
                {"apiVersion": "v1", "kind": "ServiceAccount",
                 "metadata": {"name": "bot", "namespace": "sys"}},
            )
            token = admin.get("v1/secrets", "sys/bot-token")["data"]["token"]
            client = HttpKube(srv.url, token=token)
            assert client.list(DEPLOYMENTS) == []
            admin.delete("v1/serviceaccounts", "sys/bot")
            # Token secret GC'd with its SA; the credential is dead.
            assert admin.try_get("v1/secrets", "sys/bot-token") is None
            with pytest.raises(TransportError, match="401"):
                client.list(DEPLOYMENTS)
            client.close()
            admin.close()
        finally:
            srv.close()

    def test_sa_delete_revokes_while_secret_lingers(self):
        """The regrant-on-SA-delete safety net, independent of the token
        GC: on a non-minting server a trusted token secret outlives its
        deleted SA — the credential must die the moment the SA does."""
        import hashlib as _hashlib
        import hmac as _hmac

        store = FakeKube("m")
        signing_key = "k" * 32
        token = _hmac.new(
            signing_key.encode(), b"sys/bot-token\x00bot", _hashlib.sha256
        ).hexdigest()
        store.create(
            "v1/serviceaccounts",
            {"apiVersion": "v1", "kind": "ServiceAccount",
             "metadata": {"name": "bot", "namespace": "sys"}},
        )
        store.create(
            "v1/secrets",
            {"apiVersion": "v1", "kind": "Secret",
             "type": "kubernetes.io/service-account-token",
             "metadata": {"name": "bot-token", "namespace": "sys",
                          "annotations": {"kubernetes.io/service-account.name": "bot"}},
             "data": {"token": token}},
        )
        srv = KubeApiServer(
            store, admin_token="sekrit", mint_sa_tokens=False,
            sa_signing_key=signing_key,
        )
        try:
            client = HttpKube(srv.url, token=token)
            assert client.list(DEPLOYMENTS) == []
            admin = HttpKube(srv.url, token="sekrit")
            admin.delete("v1/serviceaccounts", "sys/bot")
            # No GC on a non-minting server: the secret lingers...
            assert admin.try_get("v1/secrets", "sys/bot-token") is not None
            # ...but the credential is already dead.
            with pytest.raises(TransportError, match="401"):
                client.list(DEPLOYMENTS)
            client.close()
            admin.close()
        finally:
            srv.close()

    def test_restart_regrants_minted_tokens_only(self):
        """A server restarted over a resumed store (same signing key)
        re-grants exactly the tokens it minted — and nothing an
        attacker planted into the store meanwhile (HMAC provenance
        survives restart; client-settable fields never authenticate)."""
        store = FakeKube("m")
        srv1 = KubeApiServer(store, admin_token="sekrit",
                             mint_sa_tokens=True, sa_signing_key="key-1")
        admin = HttpKube(srv1.url, token="sekrit")
        admin.create(
            "v1/serviceaccounts",
            {"apiVersion": "v1", "kind": "ServiceAccount",
             "metadata": {"name": "bot", "namespace": "sys"}},
        )
        minted = admin.get("v1/secrets", "sys/bot-token")["data"]["token"]
        # Attacker-planted token-typed secret lands in the store too.
        admin.create(
            "v1/secrets",
            {"apiVersion": "v1", "kind": "Secret",
             "type": "kubernetes.io/service-account-token",
             "metadata": {
                 "name": "planted", "namespace": "sys",
                 "annotations": {
                     "kubernetes.io/service-account.name": "bot"
                 },
             },
             "data": {"token": "attacker-chosen"}},
        )
        admin.close()
        srv1.close()

        srv2 = KubeApiServer(store, admin_token="sekrit",
                             mint_sa_tokens=True, sa_signing_key="key-1")
        try:
            good = HttpKube(srv2.url, token=minted)
            assert good.list(DEPLOYMENTS) == []
            good.close()
            bad = HttpKube(srv2.url, token="attacker-chosen")
            with pytest.raises(TransportError, match="401"):
                bad.list(DEPLOYMENTS)
            bad.close()
        finally:
            srv2.close()

        # A restart with a DIFFERENT signing key trusts nothing.
        srv3 = KubeApiServer(store, admin_token="sekrit",
                             mint_sa_tokens=True, sa_signing_key="key-2")
        try:
            stale = HttpKube(srv3.url, token=minted)
            with pytest.raises(TransportError, match="401"):
                stale.list(DEPLOYMENTS)
            stale.close()
        finally:
            srv3.close()

    def test_client_chosen_token_never_authenticates_even_with_sa(self):
        """The full attack from ADVICE r2: sync propagates BOTH a
        ServiceAccount and a token-typed Secret with a chosen value.
        The type, annotation and value are all client-settable; only
        mint provenance is not — so the chosen value must get 401."""
        store = FakeKube("m")
        srv = KubeApiServer(store, admin_token="sekrit", mint_sa_tokens=True)
        try:
            admin = HttpKube(srv.url, token="sekrit")
            admin.create(
                "v1/serviceaccounts",
                {"apiVersion": "v1", "kind": "ServiceAccount",
                 "metadata": {"name": "bot", "namespace": "prod"}},
            )
            admin.create(
                "v1/secrets",
                {"apiVersion": "v1", "kind": "Secret",
                 "type": "kubernetes.io/service-account-token",
                 "metadata": {
                     "name": "planted", "namespace": "prod",
                     "annotations": {
                         "kubernetes.io/service-account.name": "bot"
                     },
                 },
                 "data": {"token": "attacker-chosen"}},
            )
            bad = HttpKube(srv.url, token="attacker-chosen")
            with pytest.raises(TransportError, match="401"):
                bad.list(DEPLOYMENTS)
            bad.close()
            # The server-minted token for the same SA still works.
            minted = admin.get("v1/secrets", "prod/bot-token")["data"]["token"]
            good = HttpKube(srv.url, token=minted)
            assert good.list(DEPLOYMENTS) == []
            good.close()
            admin.close()
        finally:
            srv.close()

    def test_token_rotation_revokes_stale_value(self):
        """Overwriting a minted secret's data.token must revoke the old
        value (no unrevocable lingering credential) and must NOT grant
        the new, non-minted value."""
        store = FakeKube("m")
        srv = KubeApiServer(store, admin_token="sekrit", mint_sa_tokens=True)
        try:
            admin = HttpKube(srv.url, token="sekrit")
            admin.create(
                "v1/serviceaccounts",
                {"apiVersion": "v1", "kind": "ServiceAccount",
                 "metadata": {"name": "bot", "namespace": "sys"}},
            )
            secret = admin.get("v1/secrets", "sys/bot-token")
            old_token = secret["data"]["token"]
            client = HttpKube(srv.url, token=old_token)
            assert client.list(DEPLOYMENTS) == []
            secret["data"]["token"] = "rotated-by-hand"
            admin.update("v1/secrets", secret)
            with pytest.raises(TransportError, match="401"):
                client.list(DEPLOYMENTS)
            rotated = HttpKube(srv.url, token="rotated-by-hand")
            with pytest.raises(TransportError, match="401"):
                rotated.list(DEPLOYMENTS)
            rotated.close()
            client.close()
            admin.close()
        finally:
            srv.close()

    def test_sa_deletion_revokes_lingering_token(self):
        """A crash between unjoin's SA delete and secret delete must not
        leave a live credential: deleting the SA revokes its tokens."""
        store = FakeKube("m")
        srv = KubeApiServer(store, admin_token="sekrit", mint_sa_tokens=True)
        try:
            admin = HttpKube(srv.url, token="sekrit")
            admin.create(
                "v1/serviceaccounts",
                {"apiVersion": "v1", "kind": "ServiceAccount",
                 "metadata": {"name": "bot", "namespace": "sys"}},
            )
            token = admin.get("v1/secrets", "sys/bot-token")["data"]["token"]
            client = HttpKube(srv.url, token=token)
            assert client.list(DEPLOYMENTS) == []
            admin.delete("v1/serviceaccounts", "sys/bot")  # secret lingers
            with pytest.raises(TransportError, match="401"):
                client.list(DEPLOYMENTS)
            client.close()
            admin.close()
        finally:
            srv.close()

    def test_namespaceless_serviceaccount_token(self):
        """SA with no namespace: the grant lookup must use the store's
        key format (bare name), not '/name'."""
        store = FakeKube("m")
        srv = KubeApiServer(store, admin_token="sekrit", mint_sa_tokens=True)
        try:
            admin = HttpKube(srv.url, token="sekrit")
            admin.create(
                "v1/serviceaccounts",
                {"apiVersion": "v1", "kind": "ServiceAccount",
                 "metadata": {"name": "bare"}},
            )
            token = admin.get("v1/secrets", "bare-token")["data"]["token"]
            client = HttpKube(srv.url, token=token)
            assert client.list(DEPLOYMENTS) == []
            client.close()
            admin.close()
        finally:
            srv.close()

    def test_malformed_token_value_is_untrusted_not_fatal(self):
        """Non-str / non-ASCII data.token must read as untrusted — and
        must not crash a server restart over the resumed store."""
        store = FakeKube("m")
        srv = KubeApiServer(store, admin_token="sekrit", mint_sa_tokens=True)
        admin = HttpKube(srv.url, token="sekrit")
        admin.create(
            "v1/serviceaccounts",
            {"apiVersion": "v1", "kind": "ServiceAccount",
             "metadata": {"name": "bot", "namespace": "sys"}},
        )
        for i, bad_token in enumerate((123, "émoji-token-é", None)):
            admin.create(
                "v1/secrets",
                {"apiVersion": "v1", "kind": "Secret",
                 "type": "kubernetes.io/service-account-token",
                 "metadata": {
                     "name": f"weird-{i}", "namespace": "sys",
                     "annotations": {
                         "kubernetes.io/service-account.name": "bot"
                     },
                 },
                 "data": {"token": bad_token}},
            )
        minted = admin.get("v1/secrets", "sys/bot-token")["data"]["token"]
        good = HttpKube(srv.url, token=minted)
        assert good.list(DEPLOYMENTS) == []  # server still serving
        good.close()
        admin.close()
        srv.close()
        # Restart over the resumed store: must construct cleanly.
        srv2 = KubeApiServer(store, admin_token="sekrit", mint_sa_tokens=True)
        srv2.close()


class TestBatchEndpoint:
    """POST /batch: many operations, one round trip; per-operation
    failures isolated (the bulk-write path the sync fan-out amortizes
    member writes through)."""

    def test_mixed_batch_over_http(self):
        store = FakeKube("m")
        srv = KubeApiServer(store)
        kube = HttpKube(srv.url)
        try:
            dep = lambda name, replicas=1: {
                "apiVersion": "apps/v1", "kind": "Deployment",
                "metadata": {"name": name, "namespace": "d"},
                "spec": {"replicas": replicas},
            }
            results = kube.batch([
                {"verb": "create", "resource": DEPLOYMENTS, "object": dep("a")},
                {"verb": "create", "resource": DEPLOYMENTS, "object": dep("b")},
                {"verb": "create", "resource": DEPLOYMENTS, "object": dep("a")},
                {"verb": "get", "resource": DEPLOYMENTS, "key": "d/b"},
                {"verb": "delete", "resource": DEPLOYMENTS, "key": "d/missing"},
                {"verb": "bogus"},
            ])
            assert [r["code"] for r in results] == [201, 201, 409, 200, 404, 400]
            assert results[2]["status"]["reason"] == "AlreadyExists"
            assert results[3]["object"]["metadata"]["name"] == "b"
            # updates with stale rv fail per-op with Conflict
            got = results[0]["object"]
            got["spec"]["replicas"] = 5
            stale = json.loads(json.dumps(got))
            stale["metadata"]["resourceVersion"] = "1"
            r2 = kube.batch([
                {"verb": "update", "resource": DEPLOYMENTS, "object": got},
                {"verb": "update", "resource": DEPLOYMENTS, "object": stale},
            ])
            assert r2[0]["code"] == 200
            assert r2[1]["code"] == 409 and r2[1]["status"]["reason"] == "Conflict"
        finally:
            kube.close()
            srv.close()

    def test_fakekube_batch_parity(self):
        store = FakeKube("m")
        dep = {"apiVersion": "apps/v1", "kind": "Deployment",
               "metadata": {"name": "a", "namespace": "d"}, "spec": {}}
        results = store.batch([
            {"verb": "create", "resource": DEPLOYMENTS, "object": dep},
            {"verb": "create", "resource": DEPLOYMENTS, "object": dep},
            {"verb": "get", "resource": DEPLOYMENTS, "key": "d/a"},
        ])
        assert [r["code"] for r in results] == [201, 409, 200]

    def test_batch_requires_auth(self):
        store = FakeKube("m")
        srv = KubeApiServer(store, admin_token="sekrit")
        try:
            bad = HttpKube(srv.url, token="nope")
            with pytest.raises(TransportError, match="401"):
                bad.batch([{"verb": "get", "resource": DEPLOYMENTS, "key": "d/a"}])
            bad.close()
        finally:
            srv.close()
