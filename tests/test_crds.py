"""CRD manifests: generation, on-disk sync, FTC-implied CRDs, install."""

import glob
import os

import yaml

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.models import crds
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import (
    CLUSTER_PROPAGATION_POLICIES,
    OVERRIDE_POLICIES,
    PROPAGATION_POLICIES,
)
from kubeadmiral_tpu.testing.fakekube import FakeKube


def crd_resource_key(manifest: dict) -> str:
    spec = manifest["spec"]
    version = spec["versions"][0]["name"]
    return f"{spec['group']}/{version}/{spec['names']['plural']}"


class TestCoreCrds:
    def test_covers_the_api_surface(self):
        keys = {crd_resource_key(m) for m in crds.core_crds()}
        for expected in (
            C.FEDERATED_CLUSTERS,
            PROPAGATION_POLICIES,
            CLUSTER_PROPAGATION_POLICIES,
            OVERRIDE_POLICIES,
            "core.kubeadmiral.io/v1alpha1/federatedtypeconfigs",
            "core.kubeadmiral.io/v1alpha1/schedulingprofiles",
            "core.kubeadmiral.io/v1alpha1/schedulerpluginwebhookconfigurations",
            "core.kubeadmiral.io/v1alpha1/propagatedversions",
            "core.kubeadmiral.io/v1alpha1/clusterpropagatedversions",
        ):
            assert expected in keys, expected

    def test_manifests_on_disk_match_generator(self):
        on_disk = {}
        for path in glob.glob(os.path.join(crds.MANIFEST_DIR, "*.yaml")):
            with open(path) as f:
                manifest = yaml.safe_load(f)
            on_disk[manifest["metadata"]["name"]] = manifest
        generated = {m["metadata"]["name"]: m for m in crds.core_crds()}
        assert on_disk == generated, (
            "config/crds/ out of sync: run python -m kubeadmiral_tpu.models.crds"
        )

    def test_schema_shape(self):
        for manifest in crds.core_crds():
            v = manifest["spec"]["versions"][0]
            schema = v["schema"]["openAPIV3Schema"]
            assert schema["type"] == "object"
            assert "spec" in schema["properties"]
            assert manifest["metadata"]["name"].startswith(
                manifest["spec"]["names"]["plural"] + "."
            )

    def test_policy_spec_fields(self):
        pp = next(
            m for m in crds.core_crds()
            if m["spec"]["names"]["kind"] == "PropagationPolicy"
        )
        props = (
            pp["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
            ["properties"]["spec"]["properties"]
        )
        for field in (
            "schedulingMode", "stickyCluster", "clusterSelector",
            "clusterAffinity", "tolerations", "maxClusters", "placement",
            "schedulingProfile", "disableFollowerScheduling",
            "autoMigration", "replicaRescheduling",
        ):
            assert field in props, field


class TestFtcCrds:
    def test_crd_for_every_default_ftc(self):
        for ftc in default_ftcs():
            manifest = crds.crd_for_ftc(ftc)
            assert crd_resource_key(manifest) == ftc.federated.resource
            scope = manifest["spec"]["scope"]
            assert scope == ("Namespaced" if ftc.namespaced else "Cluster")
            schema = (
                manifest["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
            )
            spec_props = schema["properties"]["spec"]["properties"]
            assert {"template", "placements", "overrides", "follows"} <= set(
                spec_props
            )

    def test_install_is_idempotent(self):
        store = FakeKube("host")
        ftcs = default_ftcs()
        n = crds.install(store, ftcs)
        assert n == len(crds.core_crds()) + len(ftcs)
        assert crds.install(store, ftcs) == 0
        names = store.keys(crds.CRD_RESOURCE)
        assert "propagationpolicies.core.kubeadmiral.io" in names
        assert any(n.startswith("federateddeployments.") for n in names)
