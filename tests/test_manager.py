"""Controller-manager runtime: registry, FTC lifecycle, health, leader
election (reference: cmd/controller-manager/app +
pkg/controllermanager)."""

import json
import urllib.request

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.clusterctl import FEDERATED_CLUSTERS, NODES
from kubeadmiral_tpu.models.ftc import (
    FEDERATED_TYPE_CONFIGS,
    default_ftcs,
    ftc_to_object,
    parse_ftc,
)
from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
from kubeadmiral_tpu.runtime.healthcheck import HealthCheckRegistry, HealthServer
from kubeadmiral_tpu.runtime.leaderelection import LeaderElector
from kubeadmiral_tpu.runtime.manager import ControllerManager
from kubeadmiral_tpu.testing import fakekube
from kubeadmiral_tpu.testing.fakekube import ClusterFleet

from test_e2e_slice import make_deployment, make_node


class TestFTCRoundTrip:
    def test_parse_inverts_serialize(self):
        for ftc in default_ftcs():
            assert parse_ftc(ftc_to_object(ftc)) == ftc

    def test_explicit_empty_controllers_preserved(self):
        obj = deployment_ftc_object()
        obj["spec"]["controllers"] = []
        assert parse_ftc(obj).controllers == ()

    def test_explicit_nulls_tolerated(self):
        obj = deployment_ftc_object()
        obj["spec"]["controllers"] = None
        obj["spec"]["statusCollection"] = {"enabled": True, "fields": None}
        obj["spec"]["autoMigration"] = None
        ftc = parse_ftc(obj)
        assert ftc.controllers  # default pipeline
        assert ftc.status_collection
        assert ftc.status_collection_fields == ("status",)
        assert not ftc.auto_migration


class TestHealthCheck:
    def test_registry_and_server(self):
        registry = HealthCheckRegistry()
        registry.add_readiness("a", lambda: True)
        registry.add_readiness("b", lambda: False)
        assert registry.readyz() == {"a": True, "b": False}

        server = HealthServer(registry)
        port = server.start()
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/livez") as resp:
                assert resp.status == 200
            try:
                urllib.request.urlopen(f"http://127.0.0.1:{port}/readyz")
                raise AssertionError("expected 500")
            except urllib.error.HTTPError as e:
                assert e.code == 500
                body = json.loads(e.read())
                assert body["checks"]["b"] is False
            registry.remove("b")
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/readyz") as resp:
                assert resp.status == 200
        finally:
            server.stop()

    def test_raising_check_reads_unhealthy(self):
        registry = HealthCheckRegistry()
        registry.add_liveness("bad", lambda: 1 / 0)
        assert registry.livez() == {"bad": False}


class TestLeaderElection:
    def test_single_holder(self):
        fleet = ClusterFleet()
        now = [0.0]
        a = LeaderElector(fleet.host, "a", clock=lambda: now[0])
        b = LeaderElector(fleet.host, "b", clock=lambda: now[0])
        assert a.try_acquire_or_renew()
        assert not b.try_acquire_or_renew()
        # a renews within the lease: b still locked out.
        now[0] += 10.0
        assert a.try_acquire_or_renew()
        now[0] += 10.0
        assert not b.try_acquire_or_renew()

    def test_expired_lease_taken_over_with_callback(self):
        fleet = ClusterFleet()
        now = [0.0]
        lost = []
        a = LeaderElector(
            fleet.host, "a", clock=lambda: now[0],
            on_stopped_leading=lambda: lost.append(True),
        )
        b = LeaderElector(fleet.host, "b", clock=lambda: now[0])
        assert a.try_acquire_or_renew()
        now[0] += 60.0  # a's lease expires
        assert b.try_acquire_or_renew()
        assert not a.try_acquire_or_renew()
        assert lost == [True]


def deployment_ftc_object():
    ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
    return ftc_to_object(ftc)


class TestControllerManager:
    def setup_method(self):
        self.fleet = ClusterFleet()
        self.manager = ControllerManager(
            self.fleet,
            cluster_controller_kwargs={
                "api_resource_probe": ["apps/v1/Deployment"]
            },
        )
        for name in ("c1", "c2", "c3"):
            member = self.fleet.add_member(name)
            member.create(NODES, make_node("n1", "64", "128Gi"))
            self.fleet.host.create(
                FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": {},
                },
            )

    def test_ftc_starts_controllers_and_propagates(self):
        self.fleet.host.create(FEDERATED_TYPE_CONFIGS, deployment_ftc_object())
        assert "deployments.apps" in self.manager._ftcs
        ready = self.manager.health.readyz()
        assert ready.get("deployments.apps/scheduler") is True
        assert ready.get("deployments.apps/sync") is True

        self.fleet.host.create(
            PROPAGATION_POLICIES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "PropagationPolicy",
                "metadata": {"name": "pp", "namespace": "default"},
                "spec": {"schedulingMode": "Divide"},
            },
        )
        self.fleet.host.create("apps/v1/deployments", make_deployment(replicas=9))
        self.manager.settle()

        total = 0
        for name in ("c1", "c2", "c3"):
            obj = self.fleet.member(name).get("apps/v1/deployments", "default/web")
            assert obj["metadata"]["labels"][C.MANAGED_LABEL] == "true"
            total += obj["spec"]["replicas"]
        assert total == 9

    def test_ftc_delete_stops_controllers(self):
        self.fleet.host.create(FEDERATED_TYPE_CONFIGS, deployment_ftc_object())
        runtime = self.manager._ftcs["deployments.apps"]
        self.fleet.host.delete(FEDERATED_TYPE_CONFIGS, "deployments.apps")
        assert "deployments.apps" not in self.manager._ftcs
        assert self.manager.health.readyz().get("deployments.apps/sync") is None
        for controller in runtime.controllers.values():
            for worker in self.manager._workers_of(controller):
                assert worker._stop.is_set()

    def test_ftc_spec_change_restarts_controllers(self):
        self.fleet.host.create(FEDERATED_TYPE_CONFIGS, deployment_ftc_object())
        old = self.manager._ftcs["deployments.apps"]
        obj = self.fleet.host.get(FEDERATED_TYPE_CONFIGS, "deployments.apps")
        obj["spec"]["statusAggregation"] = None
        self.fleet.host.update(FEDERATED_TYPE_CONFIGS, obj)
        new = self.manager._ftcs["deployments.apps"]
        assert new is not old
        assert "statusaggregator" not in new.controllers

    def test_ftc_delete_detaches_watch_handlers(self):
        baseline = sum(
            len(hs) for hs in self.fleet.host._watchers.values()
        )
        self.fleet.host.create(FEDERATED_TYPE_CONFIGS, deployment_ftc_object())
        attached = sum(len(hs) for hs in self.fleet.host._watchers.values())
        assert attached > baseline
        self.fleet.host.delete(FEDERATED_TYPE_CONFIGS, "deployments.apps")
        remaining = sum(len(hs) for hs in self.fleet.host._watchers.values())
        # Only the rebuilt follower controller's handlers remain beyond
        # the baseline.
        follower_handlers = sum(
            1
            for hs in self.fleet.host._watchers.values()
            for h in hs
            if fakekube.handler_owner(h) is self.manager._follower
        )
        assert remaining == baseline + follower_handlers

    def test_controllers_flag_semantics(self):
        assert ControllerManager._resolve_enabled(None) == {"cluster", "follower"}
        assert ControllerManager._resolve_enabled(["*", "-follower"]) == {"cluster"}
        assert ControllerManager._resolve_enabled(["cluster"]) == {"cluster"}
