"""Device-time attribution suite (ISSUE 8).

The dispatch ledger's contract: per-program device/queue attribution
that agrees with ``block_until_ready`` ground truth on CPU, per-tick
waterfalls that reconcile with the engine's host-side stage timers,
endpoint plumbing (/debug/waterfall, /debug/profile?mode=jax), the
streaming/dispatch trace spans, and the structured-logging knob.
"""

import dataclasses
import json
import logging
import io
import os
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeadmiral_tpu.runtime import devprof, trace
from kubeadmiral_tpu.runtime.devprof import DispatchLedger
from kubeadmiral_tpu.runtime.logconf import setup_logging
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine
from kubeadmiral_tpu.scheduler.streaming import StreamingScheduler

from test_engine_cache import make_world


def _heavy_program(ms_scale: int = 400):
    """A jitted program whose runtime is large enough to measure
    robustly on any CPU (a few-hundred-square matmul chain)."""

    @jax.jit
    def fn(x):
        def body(_, acc):
            return jnp.tanh(acc @ acc) + 1e-3

        return jax.lax.fori_loop(0, 8, body, x).sum()

    x = jnp.ones((ms_scale, ms_scale), jnp.float32) * 1e-3
    fn(x).block_until_ready()  # compile outside any measurement
    return fn, x


class TestLedgerAttribution:
    def test_device_time_matches_block_until_ready_ground_truth(self):
        """Chain-model device_s over a sequential dispatch chain must
        reconcile with the host-measured dispatch->ready wall."""
        ledger = DispatchLedger(enabled=True)
        fn, x = _heavy_program()
        n = 4
        t0 = time.perf_counter()
        outs = []
        for _ in range(n):
            out = fn(x)
            ledger.observe("tick", out)
            outs.append(out)
        jax.block_until_ready(outs)
        wall = time.perf_counter() - t0
        assert ledger.drain(10.0)
        recs = list(ledger._untracked)
        assert len(recs) == n
        total_device = sum(r.device_s for r in recs)
        total_queue = sum(r.queue_s for r in recs)
        # The device was busy for ~the whole wall (same thread enqueued
        # back-to-back); generous slack absorbs watcher scheduling.
        assert total_device + total_queue <= wall * 1.5 + 0.25
        assert total_device >= wall * 0.3, (total_device, wall)

    def test_queue_wait_attributed_to_backpressure(self):
        """A program dispatched while an earlier one still runs must
        show queue_s > 0: its wait is backpressure, not compute."""
        ledger = DispatchLedger(enabled=True)
        fn, x = _heavy_program()
        a = fn(x)
        ledger.observe("tick", a)
        b = fn(x)  # enqueued behind a
        ledger.observe("gather", b)
        jax.block_until_ready((a, b))
        assert ledger.drain(10.0)
        recs = sorted(ledger._untracked, key=lambda r: r.seq)
        assert [r.kind for r in recs] == ["tick", "gather"]
        # b could not start before a finished; nearly all of a's
        # runtime shows up as b's queue wait.
        assert recs[1].queue_s >= recs[0].device_s * 0.25

    def test_disabled_ledger_records_nothing(self):
        ledger = DispatchLedger(enabled=False)
        fn, x = _heavy_program(64)
        ledger.observe("tick", fn(x))
        assert ledger.begin_tick() == 0
        ledger.end_tick()
        wf = ledger.waterfall()
        assert wf == {"enabled": False, "ticks": []}

    def test_metrics_emission(self):
        m = Metrics()
        ledger = DispatchLedger(enabled=True, metrics=m)
        fn, x = _heavy_program(64)
        ledger.observe("tick", fn(x))
        assert ledger.drain(10.0)
        snap = m.snapshot()
        # Labels are (device, program) since ISSUE 12: the device lane
        # is d<id> or mesh<N> depending on the output's sharding.
        assert any(
            k.startswith("engine_device_seconds{device=")
            and "program=tick}" in k
            for k in snap["histograms"]
        ), sorted(snap["histograms"])
        assert any(
            k.startswith("engine_queue_wait_seconds{device=")
            and "program=tick}" in k
            for k in snap["histograms"]
        )


class TestEngineWaterfall:
    def test_waterfall_reconciles_with_stage_timers(self):
        """One engine tick: every dispatch lands in the tick's
        waterfall, and the summed device+queue time stays within the
        host-measured tick wall (the chain model cannot invent device
        time the host never waited for)."""
        units, clusters = make_world(b=96, c=12)
        ledger = DispatchLedger(enabled=True)
        engine = SchedulerEngine(chunk_size=64, devprof=ledger)
        t0 = time.perf_counter()
        engine.schedule(units, clusters)
        wall = time.perf_counter() - t0
        s = ledger.tick_summary()
        assert s["tick"] == engine.last_tick_id
        assert s["records"] > 0
        assert s["device_ms"] > 0
        # Host stage timers ride along in the same entry.
        assert set(s["stage_ms"]) >= {"featurize", "device", "fetch", "decode"}
        assert (s["device_ms"] + s["queue_ms"]) <= wall * 1e3 * 1.5 + 250
        kinds = set(s["by_program"])
        assert kinds <= set(devprof.PROGRAM_KINDS), kinds
        assert "tick" in kinds or "tick_narrow" in kinds

    def test_waterfall_records_ordered_and_tick_scoped(self):
        units, clusters = make_world(b=64, c=8)
        ledger = DispatchLedger(enabled=True)
        engine = SchedulerEngine(chunk_size=64, devprof=ledger)
        engine.schedule(units, clusters)
        first = engine.last_tick_id
        churned = list(units)
        churned[3] = dataclasses.replace(churned[3], desired_replicas=77)
        engine.schedule(churned, clusters)
        second = engine.last_tick_id
        wf = ledger.waterfall()
        ticks = {t["tick"]: t for t in wf["ticks"]}
        assert first in ticks and second in ticks
        for entry in ticks.values():
            seqs = [r["seq"] for r in entry["records"]]
            assert seqs == sorted(seqs)
            for r in entry["records"]:
                assert r["ready_ms"] >= r["t_ms"]
                assert r["device_ms"] >= 0 and r["queue_ms"] >= 0
        # The sub-batch churn tick repairs prev planes in place.
        assert "repair" in ticks[second]["by_program"]

    def test_noop_replay_dispatches_nothing(self):
        units, clusters = make_world(b=48, c=8)
        ledger = DispatchLedger(enabled=True)
        engine = SchedulerEngine(chunk_size=64, devprof=ledger)
        engine.schedule(units, clusters)
        engine.schedule(units, clusters)  # O(1) no-op replay
        s = ledger.tick_summary()
        assert s["tick"] == engine.last_tick_id
        assert s["records"] == 0

    def test_drift_tick_attributes_gate_programs(self):
        units, clusters = make_world(b=64, c=12)
        ledger = DispatchLedger(enabled=True)
        engine = SchedulerEngine(chunk_size=64, devprof=ledger)
        engine.schedule(units, clusters)
        drifted = list(clusters)
        drifted[0] = dataclasses.replace(
            drifted[0],
            available={
                k: max(0, v // 2) for k, v in drifted[0].available.items()
            },
        )
        engine.schedule(units, drifted)
        s = ledger.tick_summary()
        if engine.drift_stats["gated"]:
            assert "gate" in s["by_program"], s["by_program"]


class TestEndpoints:
    def _get(self, port, path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as resp:
            return resp.status, json.loads(resp.read())

    def test_debug_waterfall_and_jax_profile_smoke(self, tmp_path):
        from kubeadmiral_tpu.runtime.healthcheck import (
            HealthCheckRegistry,
            HealthServer,
        )

        units, clusters = make_world(b=48, c=8)
        engine = SchedulerEngine(chunk_size=64)  # default (served) ledger
        engine.schedule(units, clusters)
        server = HealthServer(HealthCheckRegistry())
        port = server.start()
        try:
            status, wf = self._get(port, "/debug/waterfall?records=16")
            assert status == 200
            assert wf["enabled"] is True
            assert wf["ticks"], wf
            assert all("by_program" in t for t in wf["ticks"])
            status, prof = self._get(
                port,
                "/debug/profile?seconds=0.1&mode=jax"
                f"&dir={tmp_path / 'prof'}",
            )
            assert status == 200
            assert "error" not in prof, prof
            assert os.path.isdir(prof["dir"])
            assert prof["files"] >= 1
            # The stack-sampling default is untouched.
            status, stacks = self._get(port, "/debug/profile?seconds=0.1")
            assert status == 200
            assert "top" in stacks
        finally:
            server.stop()


class TestStreamingSpans:
    def test_offer_flush_spans_connect_to_engine_tick(self, monkeypatch):
        # stream.offer spans are sampled 1-in-KT_TRACE_SAMPLE_N in
        # production; this test asserts each offer's span, so trace all.
        monkeypatch.setenv("KT_TRACE_SAMPLE_N", "1")
        trace.reset_sampling()
        try:
            self._run_offer_flush_case()
        finally:
            monkeypatch.undo()
            trace.reset_sampling()

    def _run_offer_flush_case(self):
        tracer = trace.get_default()
        tracer.clear()
        units, clusters = make_world(b=32, c=8)
        engine = SchedulerEngine(chunk_size=32)
        stream = StreamingScheduler(
            engine, clusters, units, slab_rows=4, slab_age_ms=1e9
        )
        stream.flush()
        stream.offer(dataclasses.replace(units[0], desired_replicas=41))
        stream.remove(units[1].key)
        stream.flush()
        spans = tracer.spans()
        offers = [s for s in spans if s.name == "stream.offer"]
        flushes = [s for s in spans if s.name == "stream.flush"]
        assert {s.args["kind"] for s in offers} >= {"upsert", "delete"}
        assert flushes
        last = flushes[-1]
        assert last.args["flush"] == stream.last_flush_id
        assert last.args["tick"] == engine.last_tick_id
        assert last.args["events"] == 2
        # engine.schedule nests under the flush span (same thread).
        children = [
            s for s in spans
            if s.name == "engine.schedule" and s.parent_id == last.span_id
        ]
        assert children and children[0].args["tick"] == engine.last_tick_id

    def test_stage_histograms_recorded(self):
        m = Metrics()
        units, clusters = make_world(b=32, c=8)
        engine = SchedulerEngine(chunk_size=32)
        stream = StreamingScheduler(engine, clusters, units, metrics=m)
        stream.offer(dataclasses.replace(units[0], desired_replicas=9))
        stream.flush()
        hists = m.snapshot()["histograms"]
        for stage in ("queued", "apply", "engine"):
            key = f"engine_stream_stage_seconds{{stage={stage}}}"
            assert key in hists, sorted(hists)


class TestDispatchSpans:
    def test_retry_span_recorded(self, monkeypatch):
        from kubeadmiral_tpu.federation.dispatch import run_batch_with_retries

        monkeypatch.setenv("KT_RETRY_BASE_S", "0.001")
        monkeypatch.setenv("KT_RETRY_CAP_S", "0.002")
        tracer = trace.get_default()
        tracer.clear()

        class Flaky:
            def __init__(self):
                self.calls = 0

            def batch(self, ops):
                self.calls += 1
                if self.calls == 1:
                    return [
                        {"code": 503, "status": {"reason": "Unavailable"}}
                    ] * len(ops)
                return [{"code": 200, "object": {}}] * len(ops)

        results = run_batch_with_retries(
            Flaky(),
            [{"verb": "create", "resource": "r", "object": {}}],
            deadline=time.monotonic() + 5.0,
            cluster="c-1",
        )
        assert results[0]["code"] == 200
        retries = [
            s for s in tracer.spans() if s.name == "dispatch.retry"
        ]
        assert retries and retries[0].args["cluster"] == "c-1"
        assert retries[0].args["ops"] == 1

    def test_shed_span_and_log_on_deadline(self, caplog):
        from kubeadmiral_tpu.federation.dispatch import BatchSink
        from kubeadmiral_tpu.transport.breaker import BreakerRegistry

        tracer = trace.get_default()
        tracer.clear()

        class Stalling:
            """Duck-typed non-FakeKube client that parks the flush."""

            def batch(self, ops):
                time.sleep(1.0)
                return [{"code": 200, "object": {}}] * len(ops)

            def get(self, *a, **k):
                raise KeyError

        sink = BatchSink(
            lambda cluster: Stalling(),
            breakers=BreakerRegistry(),
            deadline=0.15,
        )
        sink.submit("c-slow", {"verb": "create", "resource": "r",
                               "object": {}}, lambda r: None)
        with caplog.at_level(logging.WARNING, logger="kubeadmiral.dispatch"):
            sink.flush()
        sheds = [s for s in tracer.spans() if s.name == "dispatch.shed"]
        assert sheds and sheds[0].args["cluster"] == "c-slow"
        assert any("shedding" in r.message for r in caplog.records)


class TestLogging:
    def test_json_logging_with_span_context(self, monkeypatch):
        monkeypatch.setenv("KT_LOG_JSON", "1")
        monkeypatch.setenv("KT_LOG_LEVEL", "DEBUG")
        buf = io.StringIO()
        logger = setup_logging(stream=buf, force=True)
        try:
            with trace.span("test.logspan") as sp:
                logging.getLogger("kubeadmiral.engine").debug(
                    "tick=%d hello", 42
                )
            lines = [l for l in buf.getvalue().splitlines() if l.strip()]
            assert lines, buf.getvalue()
            doc = json.loads(lines[-1])
            assert doc["logger"] == "kubeadmiral.engine"
            assert doc["msg"] == "tick=42 hello"
            assert doc["level"] == "DEBUG"
            assert doc["span"] == sp.span_id
        finally:
            # Restore the quiet default for the rest of the suite.
            monkeypatch.delenv("KT_LOG_JSON")
            monkeypatch.delenv("KT_LOG_LEVEL")
            setup_logging(force=True)

    def test_engine_debug_log_carries_tick_id(self, caplog):
        units, clusters = make_world(b=32, c=8)
        engine = SchedulerEngine(
            chunk_size=32, devprof=DispatchLedger(enabled=True)
        )
        with caplog.at_level(logging.DEBUG, logger="kubeadmiral.engine"):
            engine.schedule(units, clusters)
        msgs = [r.message for r in caplog.records if "tick=" in r.message]
        assert any(f"tick={engine.last_tick_id}" in m for m in msgs), msgs


class TestBenchDeviceAttr:
    def test_bench_attr_merge_shape(self):
        """bench.py's _attr merge: summed per-program totals + the
        reconcile ratio against the host device stage."""
        units, clusters = make_world(b=64, c=8)
        ledger = DispatchLedger(enabled=True)
        engine = SchedulerEngine(chunk_size=64, devprof=ledger)
        ids = []
        world = units
        for i in range(2):
            world = list(world)
            world[i] = dataclasses.replace(world[i], desired_replicas=60 + i)
            engine.schedule(world, clusters)
            ids.append(engine.last_tick_id)
        summaries = [ledger.tick_summary(t) for t in ids]
        assert all(s["tick"] == t for s, t in zip(summaries, ids))
        total = sum(s["device_ms"] for s in summaries)
        stage = sum(s["stage_ms"].get("device", 0) for s in summaries)
        assert total >= 0 and stage >= 0
