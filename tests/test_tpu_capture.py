"""Opportunistic on-chip capture mechanism (tpu_capture.py), driven
with fake probe/runner/clock — no chip involved (VERDICT r4 #2)."""

import json
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_capture import capture_loop  # noqa: E402


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.t += s


def tpu_artifact(config):
    return {
        "metric": f"objects_scheduled_per_sec_c{config}",
        "value": 1.0,
        "detail": {"platform": "tpu", "config": config},
    }


def test_waits_for_window_then_captures_all(tmp_path):
    clock = FakeClock()
    probes = iter([False, False, True])
    ran = []

    def probe():
        return next(probes)

    def runner(config):
        ran.append(config)
        return tpu_artifact(config)

    captured = capture_loop(
        ["3", "4"],
        probe=probe,
        runner=runner,
        sleep=clock.sleep,
        clock=clock,
        interval_s=60,
        deadline_s=3600,
        write_dir=str(tmp_path),
    )
    assert ran == ["3", "4"]
    assert set(captured) == {"3", "4"}
    for config, path in captured.items():
        with open(path) as f:
            assert json.load(f)["detail"]["platform"] == "tpu"
    # Probed only until the window opened: two sleeps of 60s.
    assert clock.t == 120


def test_chip_lost_mid_window_resumes_watching(tmp_path):
    clock = FakeClock()
    # Window opens immediately; config 4 loses the chip; next window
    # retries ONLY config 4.
    probes = iter([True, False, True])
    attempts = []

    def probe():
        return next(probes)

    def runner(config):
        attempts.append((config, clock()))
        if config == "4" and len(attempts) == 2:
            return None  # chip lost
        return tpu_artifact(config)

    captured = capture_loop(
        ["3", "4"],
        probe=probe,
        runner=runner,
        sleep=clock.sleep,
        clock=clock,
        interval_s=60,
        deadline_s=3600,
        write_dir=str(tmp_path),
    )
    assert [c for c, _ in attempts] == ["3", "4", "4"]
    assert set(captured) == {"3", "4"}


def test_deadline_bounds_the_watch(tmp_path):
    clock = FakeClock()

    def probe():
        return False

    captured = capture_loop(
        ["5"],
        probe=probe,
        runner=lambda c: tpu_artifact(c),
        sleep=clock.sleep,
        clock=clock,
        interval_s=100,
        deadline_s=1000,
        write_dir=str(tmp_path),
    )
    assert captured == {}
    assert clock.t <= 1100  # bounded: ~deadline / interval probes


def test_cpu_fallback_artifact_not_captured(tmp_path):
    """A runner returning None (bench degraded to cpu-fallback) must
    not produce a _tpu artifact file."""
    clock = FakeClock()
    probes = iter([True, False])

    def probe():
        try:
            return next(probes)
        except StopIteration:
            return False

    captured = capture_loop(
        ["3"],
        probe=probe,
        runner=lambda c: None,
        sleep=clock.sleep,
        clock=clock,
        interval_s=60,
        deadline_s=200,
        write_dir=str(tmp_path),
    )
    assert captured == {}
    assert not list(tmp_path.iterdir())
