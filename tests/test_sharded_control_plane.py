"""Sharded control plane (ISSUE 20): leader-per-shard leases,
per-shard snapshot artifacts, the /debug/shards surface, and — the
core correctness claim — an in-process N-replica set whose unioned
scheduler output is bit-identical to the unsharded oracle.

The bench tier proves the same properties at 10000x500 scale
(bench_e2e.py --shards); these tests pin the mechanisms at unit scale
so a regression fails in seconds, not in a bench round.
"""

from __future__ import annotations

import dataclasses
import json
import urllib.request

import pytest

from kubeadmiral_tpu.federation import shardmap as SM
from kubeadmiral_tpu.runtime.leaderelection import (
    LEASES,
    shard_elector,
    shard_lease_name,
    shard_lease_status,
)
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.snapshot import SnapshotManager, shard_snapshot_store
from kubeadmiral_tpu.testing.fakekube import ClusterFleet, FakeKube


@pytest.fixture(autouse=True)
def _restore_default_shardmap():
    prev = SM.set_default(SM.ShardMap(shard_count=1, shard_index=0))
    try:
        yield
    finally:
        SM.set_default(prev or SM.ShardMap(shard_count=1, shard_index=0))


class _Clock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now


class TestShardLeases:
    def test_disjoint_acquisition(self):
        """N replicas against N shard leases: each wins its own, nobody
        wins a lease another replica holds."""
        host = FakeKube()
        electors = [
            shard_elector(host, identity=f"replica-{i}", shard_index=i)
            for i in range(3)
        ]
        assert all(e.try_acquire_or_renew() for e in electors)
        # Cross-acquisition attempts against a fresh lease all lose.
        thief = shard_elector(host, identity="thief", shard_index=1)
        assert not thief.try_acquire_or_renew()
        holders = {
            host.get(LEASES, f"kube-admiral-system/{shard_lease_name(i)}")
            ["spec"]["holderIdentity"]
            for i in range(3)
        }
        assert holders == {"replica-0", "replica-1", "replica-2"}

    def test_failover_to_standby_after_expiry(self):
        """A killed replica's shard fails over: the standby's elector
        acquires kt-shard-<i> once the dead holder's lease expires, and
        never a moment before."""
        host = FakeKube()
        clock = _Clock()
        dead = shard_elector(
            host, identity="dead", shard_index=0,
            lease_seconds=15.0, clock=clock,
        )
        assert dead.try_acquire_or_renew()
        standby = shard_elector(
            host, identity="standby", shard_index=0,
            lease_seconds=15.0, clock=clock,
        )
        clock.now += 10.0  # inside the lease: holder presumed alive
        assert not standby.try_acquire_or_renew()
        clock.now += 10.0  # 20s since renew > 15s duration: expired
        assert standby.try_acquire_or_renew()
        assert standby.is_leader
        lease = host.get(
            LEASES, f"kube-admiral-system/{shard_lease_name(0)}"
        )
        assert lease["spec"]["holderIdentity"] == "standby"
        # The late-returning dead replica observes the loss.
        assert not dead.try_acquire_or_renew()
        assert not dead.is_leader

    def test_release_hands_off_immediately(self):
        host = FakeKube()
        clock = _Clock()
        a = shard_elector(host, identity="a", shard_index=2, clock=clock)
        b = shard_elector(host, identity="b", shard_index=2, clock=clock)
        assert a.try_acquire_or_renew()
        assert not b.try_acquire_or_renew()
        a.release()
        assert b.try_acquire_or_renew()  # no expiry wait after release

    def test_shard_lease_status_rows(self):
        host = FakeKube()
        clock = _Clock()
        e0 = shard_elector(host, identity="r0", shard_index=0, clock=clock)
        assert e0.try_acquire_or_renew()
        clock.now += 60.0  # r0 went silent: stale holder
        rows = shard_lease_status(host, 3, clock=clock)
        assert [r["shard"] for r in rows] == [0, 1, 2]
        assert rows[0]["holder"] == "r0"
        assert rows[0]["age_s"] == 60.0
        assert rows[0]["fresh"] is False  # past lease duration
        assert rows[1]["holder"] is None and rows[1]["fresh"] is False
        e2 = shard_elector(host, identity="r2", shard_index=2, clock=clock)
        assert e2.try_acquire_or_renew()
        rows = shard_lease_status(host, 3, clock=clock)
        assert rows[2]["holder"] == "r2" and rows[2]["fresh"] is True


class _StubEngine:
    """The minimal engine surface SnapshotManager drives."""

    def __init__(self, state=None):
        self._state = state if state is not None else {"plane": [1, 2, 3]}
        self.tick_seq = 7
        self.last_changed = True
        self.flightrec = None
        self.staged = None

    def snapshot_state(self):
        return self._state

    def stage_restore(self, state, assume_fresh=False):
        self.staged = (state, assume_fresh)


class TestPerShardSnapshots:
    def test_store_keyed_by_shard_directory(self, tmp_path):
        s0 = shard_snapshot_store(str(tmp_path), SM.ShardMap(2, 0))
        s1 = shard_snapshot_store(str(tmp_path), SM.ShardMap(2, 1))
        SnapshotManager(_StubEngine(), s0, every=1, shard=SM.ShardMap(2, 0)).snapshot()
        SnapshotManager(_StubEngine(), s1, every=1, shard=SM.ShardMap(2, 1)).snapshot()
        assert (tmp_path / "shard-0").is_dir()
        assert (tmp_path / "shard-1").is_dir()

    def test_payload_stamped_and_matching_restore_staged(self, tmp_path):
        shard = SM.ShardMap(2, 0)
        store = shard_snapshot_store(str(tmp_path), shard)
        SnapshotManager(_StubEngine(), store, every=1, shard=shard).snapshot()
        _, payload = store.load_latest()
        assert payload["shard"] == {
            "shard_count": 2, "shard_index": 0, "epoch": 0,
        }
        successor = _StubEngine(state=None)
        mgr = SnapshotManager(successor, store, every=1, shard=SM.ShardMap(2, 0))
        assert mgr.restore() == "staged"
        assert successor.staged is not None

    @pytest.mark.parametrize(
        "wrong",
        [
            SM.ShardMap(2, 1),                 # another shard's replica
            SM.ShardMap(4, 0),                 # different shard count
            SM.ShardMap(2, 0, epoch=1),        # post-resize epoch
        ],
        ids=["index", "count", "epoch"],
    )
    def test_mismatched_restore_refused_cold(self, tmp_path, wrong):
        shard = SM.ShardMap(2, 0)
        metrics = Metrics()
        store = shard_snapshot_store(str(tmp_path), shard, metrics=metrics)
        SnapshotManager(_StubEngine(), store, every=1, shard=shard).snapshot()
        successor = _StubEngine()
        # Point the mismatched replica at the same directory on purpose:
        # the payload stamp, not the path layout, is the contract.
        mgr = SnapshotManager(successor, store, every=1, shard=wrong)
        assert mgr.restore() == "cold"
        assert successor.staged is None
        assert mgr.last_result == "cold"

    def test_unsharded_manager_ignores_stamp(self, tmp_path):
        from kubeadmiral_tpu.runtime.snapshot import SnapshotStore

        store = SnapshotStore(str(tmp_path))
        SnapshotManager(_StubEngine(), store, every=1).snapshot()
        _, payload = store.load_latest()
        assert payload["shard"] is None
        assert SnapshotManager(_StubEngine(), store, every=1).restore() == "staged"


class TestDebugShards:
    def test_provider_slot_last_wins(self):
        from kubeadmiral_tpu.runtime import profiling

        try:
            profiling.set_shards_provider(lambda: {"a": 1})
            profiling.set_shards_provider(lambda: {"b": 2})
            assert profiling.shards_report() == {"b": 2}
        finally:
            profiling.set_shards_provider(None)
        assert profiling.shards_report() is None

    def test_endpoint_serves_report(self):
        from kubeadmiral_tpu.runtime import profiling
        from kubeadmiral_tpu.runtime.profiling import ProfilingServer

        server = ProfilingServer()
        port = server.start()
        try:
            url = f"http://127.0.0.1:{port}/debug/shards"
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(url, timeout=10)
            assert e.value.code == 404  # no provider installed yet
            profiling.set_shards_provider(
                lambda: {"shard_count": 2, "shard_index": 0, "epoch": 3}
            )
            with urllib.request.urlopen(url, timeout=10) as r:
                doc = json.loads(r.read())
            assert doc == {"shard_count": 2, "shard_index": 0, "epoch": 3}
        finally:
            profiling.set_shards_provider(None)
            server.stop()

    def test_manager_report_shape(self):
        from kubeadmiral_tpu.runtime.manager import ControllerManager

        with SM.scoped(SM.ShardMap(2, 0)):
            mgr = ControllerManager(ClusterFleet())
        try:
            report = mgr.shard_report()
        finally:
            mgr.shutdown()
        assert report["shard_count"] == 2 and report["shard_index"] == 0
        assert "epoch" in report and "owned_keys" in report
        leases = report["leases"]
        assert leases is None or len(leases) == 2


def _stack(fleet, ftc, shard):
    """One in-process replica's controller stack under its scope, the
    bench_e2e._controller_set shape at unit scale."""
    from kubeadmiral_tpu.federation.federate import FederateController
    from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
    from kubeadmiral_tpu.federation.sync import SyncController
    from kubeadmiral_tpu.runtime.flightrec import FlightRecorder
    from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

    with SM.scoped(shard):
        engine = SchedulerEngine(flight_recorder=FlightRecorder())
        return [
            FederateController(fleet.host, ftc),
            SchedulerController(fleet.host, ftc, engine=engine),
            SyncController(fleet, ftc),
        ]


def _settle(stacks):
    progressed = True
    while progressed:
        progressed = False
        for ctl in stacks:
            while ctl.worker.step():
                progressed = True


def _world(n_objects=24, n_clusters=4):
    from kubeadmiral_tpu.federation.clusterctl import (
        FEDERATED_CLUSTERS,
        NODES,
        FederatedClusterController,
    )
    from kubeadmiral_tpu.models.ftc import default_ftcs
    from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES

    fleet = ClusterFleet()
    ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
    ftc = dataclasses.replace(
        ftc, controllers=(("kubeadmiral.io/global-scheduler",),)
    )
    cluster_ctl = FederatedClusterController(
        fleet, api_resource_probe=["apps/v1/Deployment"]
    )
    for j in range(n_clusters):
        name = f"m-{j}"
        member = fleet.add_member(name)
        member.create(NODES, {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": "n1"}, "spec": {},
            "status": {
                "allocatable": {"cpu": f"{8 + 4 * j}", "memory": "64Gi"},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        })
        fleet.host.create(FEDERATED_CLUSTERS, {
            "apiVersion": "core.kubeadmiral.io/v1alpha1",
            "kind": "FederatedCluster",
            "metadata": {"name": name}, "spec": {},
        })
    fleet.host.create(PROPAGATION_POLICIES, {
        "apiVersion": "core.kubeadmiral.io/v1alpha1",
        "kind": "PropagationPolicy",
        "metadata": {"name": "pp", "namespace": "default"},
        "spec": {"schedulingMode": "Divide"},
    })
    _settle([cluster_ctl])
    for i in range(n_objects):
        fleet.host.create(ftc.source.resource, {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {
                "name": f"web-{i:03d}", "namespace": "default",
                "labels": {"kubeadmiral.io/propagation-policy-name": "pp"},
            },
            "spec": {
                "replicas": (i % 5) + 1,
                "template": {"spec": {"containers": [
                    {"name": "c", "resources": {"requests": {"cpu": "100m"}}}
                ]}},
            },
        })
    return fleet, ftc, cluster_ctl


def _placements(fleet, ftc):
    out = {}
    for key in sorted(fleet.host.keys(ftc.federated.resource)):
        spec = fleet.host.get(ftc.federated.resource, key).get("spec", {})
        out[key] = {
            "placements": spec.get("placements", []),
            "overrides": spec.get("overrides", []),
        }
    return out


class TestInprocReplicaSetParity:
    def test_union_of_two_shards_matches_unsharded_oracle(self):
        fleet_o, ftc_o, cl_o = _world()
        _settle([cl_o] + _stack(fleet_o, ftc_o, SM.ShardMap(1, 0)))
        oracle = _placements(fleet_o, ftc_o)
        assert oracle and any(v["placements"] for v in oracle.values())

        fleet_s, ftc_s, cl_s = _world()
        stacks = [cl_s]
        for i in range(2):
            stacks += _stack(fleet_s, ftc_s, SM.ShardMap(2, i))
        _settle(stacks)
        assert _placements(fleet_s, ftc_s) == oracle

    def test_single_shard_replica_covers_only_its_keys(self):
        fleet, ftc, cl = _world()
        _settle([cl] + _stack(fleet, ftc, SM.ShardMap(2, 0)))
        probe = SM.ShardMap(2, 0)
        for key, val in _placements(fleet, ftc).items():
            if probe.owns(key):
                assert val["placements"], key
            else:
                assert not val["placements"], (
                    f"shard 0 scheduled non-owned key {key}"
                )
