"""The full 21-type default FTC set, with RBAC/quota propagation e2e.

Mirrors the reference's default registrations
(config/sample/host/01-ftc.yaml) and its resourcepropagation e2e style:
create a source object + policy, run the controllers, observe member
objects — for a namespaced RBAC type (Role), a quota type
(ResourceQuota, member-owned status retained across template updates),
and a cluster-scoped type (ClusterRole via ClusterPropagationPolicy).
"""

import dataclasses

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.clusterctl import (
    FEDERATED_CLUSTERS,
    FederatedClusterController,
)
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import (
    CLUSTER_PROPAGATION_POLICIES,
    PROPAGATION_POLICIES,
)
from kubeadmiral_tpu.testing.fakekube import ClusterFleet

REFERENCE_21 = {
    "namespaces", "configmaps", "deployments.apps", "serviceaccounts",
    "secrets", "services", "storageclasses.storage.k8s.io",
    "persistentvolumes", "persistentvolumeclaims",
    "roles.rbac.authorization.k8s.io",
    "rolebindings.rbac.authorization.k8s.io",
    "clusterroles.rbac.authorization.k8s.io",
    "clusterrolebindings.rbac.authorization.k8s.io",
    "statefulsets.apps", "daemonsets.apps", "jobs.batch", "cronjobs.batch",
    "ingresses.networking.k8s.io", "limitranges", "resourcequotas",
    "customresourcedefinitions.apiextensions.k8s.io",
}


def test_default_set_matches_reference_21():
    names = {f.name for f in default_ftcs()}
    assert names == REFERENCE_21
    by_name = {f.name: f for f in default_ftcs()}
    for cluster_scoped in (
        "persistentvolumes", "storageclasses.storage.k8s.io",
        "clusterroles.rbac.authorization.k8s.io",
        "clusterrolebindings.rbac.authorization.k8s.io",
        "customresourcedefinitions.apiextensions.k8s.io", "namespaces",
    ):
        assert not by_name[cluster_scoped].namespaced, cluster_scoped


def ftc_by_name(name, scheduler_only=True):
    ftc = next(f for f in default_ftcs() if f.name == name)
    if scheduler_only:
        ftc = dataclasses.replace(
            ftc, controllers=(("kubeadmiral.io/global-scheduler",),)
        )
    return ftc


def settle(*controllers, rounds=30):
    for _ in range(rounds):
        if not any([c.worker.step() for c in controllers]):
            return


class _Harness:
    def __init__(self, ftc):
        self.ftc = ftc
        self.fleet = ClusterFleet()
        gvk = ftc.source.gvk
        self.controllers = (
            FederatedClusterController(self.fleet, api_resource_probe=[gvk]),
            FederateController(self.fleet.host, ftc),
            SchedulerController(self.fleet.host, ftc),
            SyncController(self.fleet, ftc),
        )
        for name in ("c1", "c2"):
            self.fleet.add_member(name)
            self.fleet.host.create(
                FEDERATED_CLUSTERS,
                {"apiVersion": "core.kubeadmiral.io/v1alpha1",
                 "kind": "FederatedCluster",
                 "metadata": {"name": name}, "spec": {}},
            )

    def run(self):
        settle(*self.controllers)


def test_role_propagates_to_members():
    h = _Harness(ftc_by_name("roles.rbac.authorization.k8s.io"))
    h.fleet.host.create(
        PROPAGATION_POLICIES,
        {"apiVersion": "core.kubeadmiral.io/v1alpha1",
         "kind": "PropagationPolicy",
         "metadata": {"name": "pp", "namespace": "team-a"},
         "spec": {"schedulingMode": "Duplicate"}},
    )
    role = {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "Role",
        "metadata": {"name": "reader", "namespace": "team-a",
                     "labels": {C.PROPAGATION_POLICY_NAME: "pp"}},
        "rules": [{"apiGroups": [""], "resources": ["pods"],
                   "verbs": ["get", "list"]}],
    }
    h.fleet.host.create(h.ftc.source.resource, role)
    h.run()
    for member in ("c1", "c2"):
        got = h.fleet.member(member).get(h.ftc.source.resource, "team-a/reader")
        assert got["rules"] == role["rules"]
        assert got["metadata"]["labels"][C.MANAGED_LABEL] == "true"


def test_resourcequota_propagates_and_member_status_retained():
    h = _Harness(ftc_by_name("resourcequotas"))
    h.fleet.host.create(
        PROPAGATION_POLICIES,
        {"apiVersion": "core.kubeadmiral.io/v1alpha1",
         "kind": "PropagationPolicy",
         "metadata": {"name": "pp", "namespace": "team-a"},
         "spec": {"schedulingMode": "Duplicate"}},
    )
    quota = {
        "apiVersion": "v1",
        "kind": "ResourceQuota",
        "metadata": {"name": "caps", "namespace": "team-a",
                     "labels": {C.PROPAGATION_POLICY_NAME: "pp"}},
        "spec": {"hard": {"cpu": "10", "memory": "20Gi"}},
    }
    h.fleet.host.create(h.ftc.source.resource, quota)
    h.run()
    member = h.fleet.member("c1")
    got = member.get(h.ftc.source.resource, "team-a/caps")
    assert got["spec"]["hard"] == {"cpu": "10", "memory": "20Gi"}

    # Member-side controller fills status (member-owned); a template
    # update from the host must not clobber it.
    got["status"] = {"used": {"cpu": "3"}}
    member.update_status(h.ftc.source.resource, got)

    src = h.fleet.host.get(h.ftc.source.resource, "team-a/caps")
    src["spec"]["hard"]["cpu"] = "16"
    h.fleet.host.update(h.ftc.source.resource, src)
    h.run()

    got = member.get(h.ftc.source.resource, "team-a/caps")
    assert got["spec"]["hard"]["cpu"] == "16"
    assert got["status"] == {"used": {"cpu": "3"}}


def test_clusterrole_propagates_via_cluster_policy():
    h = _Harness(ftc_by_name("clusterroles.rbac.authorization.k8s.io"))
    h.fleet.host.create(
        CLUSTER_PROPAGATION_POLICIES,
        {"apiVersion": "core.kubeadmiral.io/v1alpha1",
         "kind": "ClusterPropagationPolicy",
         "metadata": {"name": "cpp"},
         "spec": {"schedulingMode": "Duplicate"}},
    )
    cr = {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": {"name": "admin-lite",
                     "labels": {C.CLUSTER_PROPAGATION_POLICY_NAME: "cpp"}},
        "rules": [{"apiGroups": ["*"], "resources": ["*"], "verbs": ["get"]}],
    }
    h.fleet.host.create(h.ftc.source.resource, cr)
    h.run()
    for member in ("c1", "c2"):
        got = h.fleet.member(member).get(h.ftc.source.resource, "admin-lite")
        assert got["rules"] == cr["rules"]
        assert got["metadata"]["labels"][C.MANAGED_LABEL] == "true"
