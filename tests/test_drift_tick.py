"""Drift-tick regression suite (ISSUE 4).

A cluster-capacity drift revalidates every row, but only rows whose
decision can actually move may be recomputed — and nothing but the
cluster planes may cross the host->device link again.  The drift gate's
exactness claims (ops/pipeline.py, "drift gate") are checked here both
by targeted rule cases and by a randomized differential against a
cache-less engine and the sequential oracle.
"""

import dataclasses

import numpy as np

from kubeadmiral_tpu.bench_support import sequential_schedule
from kubeadmiral_tpu.models.types import parse_resources
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

from test_engine_cache import make_world, results_equal
from test_engine_vs_sequential import random_cluster, random_unit


def halve_available(cluster):
    return dataclasses.replace(
        cluster,
        available={k: max(0, v // 2) for k, v in cluster.available.items()},
    )


class TestDriftUploadBytes:
    def test_drift_does_not_reupload_object_planes(self):
        """On a drift tick the cluster planes are the ONLY bytes that
        changed: the cached per-object device tensors must be reused
        as-is (satellite (a): pinned via the upload-byte counters)."""
        units, clusters = make_world(b=64, c=12)
        engine = SchedulerEngine(chunk_size=32)
        engine.schedule(units, clusters)
        engine.schedule(list(units), clusters)  # device copies armed
        object0 = engine.upload_bytes["object"]
        cluster0 = engine.upload_bytes["cluster"]

        drifted = [halve_available(c) if j == 0 else c
                   for j, c in enumerate(clusters)]
        engine.schedule(units, drifted)
        assert engine.drift_stats["gated"] >= 2, engine.drift_stats
        # The chunk planes must NOT ride the link again; only the
        # recomputed rows' slab inputs may (a small fraction of the
        # cold upload).
        recomputed = engine.drift_stats["recompute"]
        delta = engine.upload_bytes["object"] - object0
        assert recomputed < len(units) // 2
        # The slab pads its rows to a pow2 bucket, so bound against the
        # padded slab size: strictly less than re-uploading the chunks
        # (the provably-zero case is pinned by the inert-column test).
        assert delta < object0 // 2 + 1024, (
            "drift tick re-uploaded per-object planes",
            delta, object0, engine.drift_stats,
        )
        assert engine.upload_bytes["cluster"] > cluster0

    def test_inert_drift_uploads_no_object_bytes_at_all(self):
        """When the drifted column is infeasible for every row, the
        drift tick moves ZERO object-plane bytes."""
        units, clusters = make_world(b=48, c=12)
        # No tolerations anywhere: cluster 0 (tainted in make_world) is
        # infeasible for every row.
        units = [dataclasses.replace(u, tolerations=()) for u in units]
        engine = SchedulerEngine(chunk_size=32)
        engine.schedule(units, clusters)
        engine.schedule(list(units), clusters)
        object0 = engine.upload_bytes["object"]
        drifted = [halve_available(c) if j == 0 else c
                   for j, c in enumerate(clusters)]
        engine.schedule(units, drifted)
        assert engine.drift_stats["gated"] >= 1
        assert engine.upload_bytes["object"] == object0, engine.upload_bytes

    def test_cluster_planes_uploaded_once_per_tick(self):
        """Every chunk dispatch shares ONE padded cluster-plane upload:
        a multi-chunk drift tick charges the cluster counter for a
        single plane set, not per chunk (the cold tick's vocabulary
        tables are already device-resident)."""
        units, clusters = make_world(b=96, c=12)
        engine = SchedulerEngine(chunk_size=32)
        engine.schedule(units, clusters)  # cold: tables + planes
        assert engine.cache_stats["miss"] == 3
        cluster0 = engine.upload_bytes["cluster"]
        drifted = [halve_available(c) for c in clusters[:2]] + clusters[2:]
        engine.schedule(units, drifted)
        per_tick = engine.upload_bytes["cluster"] - cluster0
        # One padded plane set (alloc/used [C,R] i64, cpu planes [C]
        # i64, valid [C] bool) + the wcheck's old cpu planes + the
        # per-chunk gate delta slices (4 x [8, R] i64 each — a few
        # hundred bytes) — NOT one plane-set copy per chunk, and no
        # table re-upload.
        c_bucket = 16
        r = np.asarray(engine._chunk_cache[0].inputs.alloc).shape[1]
        plane_set = c_bucket * (2 * r * 8 + 2 * 8 + 1)
        gate_slices = 3 * 4 * 8 * r * 8
        assert 0 < per_tick <= plane_set + 2 * c_bucket * 8 + gate_slices, (
            per_tick, plane_set, gate_slices,
        )


class TestDriftExactness:
    def test_drift_matches_sequential_oracle(self):
        """Satellite (b): bit-exact against the per-object sequential
        oracle after capacity drift."""
        rng = np.random.default_rng(20260804)
        clusters = [random_cluster(rng, j) for j in range(16)]
        names = [c.name for c in clusters]
        units = [random_unit(rng, i, names) for i in range(96)]
        engine = SchedulerEngine(chunk_size=32, min_bucket=16,
                                 min_cluster_bucket=8)
        engine.schedule(units, clusters)

        drifted = [halve_available(c) if j in (0, 5) else c
                   for j, c in enumerate(clusters)]
        got = engine.schedule(units, drifted)
        assert engine.drift_stats["gated"] >= 1, engine.drift_stats
        want = sequential_schedule(units, drifted)
        for i, (g, w) in enumerate(zip(got, want)):
            w_named = {names[j]: reps for j, reps in w.items()}
            assert g.clusters == w_named, (
                f"object {i} ({units[i].name}): engine={dict(g.clusters)} "
                f"sequential={w_named}"
            )

    def test_randomized_drift_sequence_differential(self):
        """Many drift patterns in sequence — single column, cpu-only,
        alloc growth, churn interleaved, mass drift (gate bail) — each
        tick compared against a cache-less engine on the same world."""
        rng = np.random.default_rng(7)
        clusters = [random_cluster(rng, j) for j in range(14)]
        names = [c.name for c in clusters]
        units = [random_unit(rng, i, names) for i in range(72)]
        engine = SchedulerEngine(chunk_size=32, min_bucket=16,
                                 min_cluster_bucket=8)
        engine.schedule(units, clusters)

        for step in range(8):
            kind = step % 4
            if kind == 0:  # one column's available halves
                j = int(rng.integers(0, len(clusters)))
                clusters = [halve_available(c) if i == j else c
                            for i, c in enumerate(clusters)]
            elif kind == 1:  # cpu-only change on two columns
                picks = set(rng.integers(0, len(clusters), 2).tolist())
                clusters = [
                    dataclasses.replace(
                        c,
                        available={**c.available,
                                   "cpu": max(0, c.available.get("cpu", 0) - 1500)},
                    )
                    if i in picks else c
                    for i, c in enumerate(clusters)
                ]
            elif kind == 2:  # churn + drift in the same tick
                units = list(units)
                for r in rng.integers(0, len(units), 3):
                    units[int(r)] = dataclasses.replace(
                        units[int(r)],
                        desired_replicas=int(rng.integers(1, 50)),
                    )
                j = int(rng.integers(0, len(clusters)))
                clusters = [halve_available(c) if i == j else c
                            for i, c in enumerate(clusters)]
            else:  # mass drift: every column moves (gate bails out)
                clusters = [
                    dataclasses.replace(
                        c,
                        available={k: max(0, v - v // 10)
                                   for k, v in c.available.items()},
                    )
                    for c in clusters
                ]
            got = engine.schedule(units, clusters)
            fresh = SchedulerEngine(
                chunk_size=32, min_bucket=16, min_cluster_bucket=8
            ).schedule(units, clusters)
            results_equal(got, fresh)
        # The sequence must actually have exercised the gate.
        assert engine.drift_stats["gated"] >= 2, engine.drift_stats
        assert engine.drift_stats["skip"] > 0, engine.drift_stats

    def test_infeasible_drift_column_skips_everything(self):
        """A drifted column that no row can use (untolerated taint) is
        provably inert: the gate must skip every row without any
        recompute dispatch."""
        from kubeadmiral_tpu.models.types import (
            ClusterState, SchedulingUnit, Taint, MODE_DIVIDE,
        )

        gvk = "apps/v1/Deployment"
        clusters = [
            ClusterState(
                name=f"m-{j}",
                labels={},
                taints=(Taint("walled", "off", "NoSchedule"),) if j == 0 else (),
                allocatable=parse_resources({"cpu": "32", "memory": "64Gi"}),
                available=parse_resources({"cpu": "16", "memory": "32Gi"}),
                api_resources=frozenset({gvk}),
            )
            for j in range(6)
        ]
        units = [
            SchedulingUnit(
                gvk=gvk, namespace="ns", name=f"w-{i}",
                scheduling_mode=MODE_DIVIDE, desired_replicas=9,
                resource_request=parse_resources({"cpu": "100m"}),
            )
            for i in range(24)
        ]
        engine = SchedulerEngine(chunk_size=32, min_bucket=8)
        first = engine.schedule(units, clusters)
        dispatches0 = engine.dispatches_total
        drifted = [halve_available(c) if j == 0 else c
                   for j, c in enumerate(clusters)]
        got = engine.schedule(units, drifted)
        assert engine.drift_stats["recompute"] == 0, engine.drift_stats
        assert engine.drift_stats["skip"] == len(units), engine.drift_stats
        # One gate dispatch, zero tick/fetch dispatches.
        assert engine.dispatches_total == dispatches0 + 1
        results_equal(got, first)  # placements can't have moved

    def test_sticky_rows_never_recompute_on_drift(self):
        """Sticky rows with current placements short-circuit to them —
        cluster drift cannot move them, and the gate must know."""
        units, clusters = make_world(b=32, c=8)
        units = [
            dataclasses.replace(
                u, sticky_cluster=True,
                current_clusters={clusters[i % 8].name: 3},
            )
            for i, u in enumerate(units)
        ]
        engine = SchedulerEngine(chunk_size=32, min_bucket=8)
        engine.schedule(units, clusters)
        drifted = [halve_available(c) for c in clusters]  # mass cpu drift
        # Mass drift bails to full dispatch; narrow the drift so the
        # gate engages.
        drifted = [drifted[0]] + clusters[1:]
        got = engine.schedule(units, drifted)
        assert engine.drift_stats["gated"] >= 1
        assert engine.drift_stats["recompute"] == 0, engine.drift_stats
        fresh = SchedulerEngine(chunk_size=32, min_bucket=8).schedule(
            units, drifted
        )
        results_equal(got, fresh)

    def test_finite_max_clusters_rank_refinement(self):
        """Top-K rows with a feasible drifted column are skipped ONLY
        when the exact rank test proves no membership flip; a drift
        that pushes a column across the K boundary must recompute and
        move the placement."""
        # Part 1: a mild drift that reorders nothing — the refined gate
        # proves every row unchanged (the coarse rule would have
        # recomputed all of them).
        units, clusters = make_world(b=24, c=8)
        units = [
            dataclasses.replace(u, max_clusters=3, tolerations=()) for u in units
        ]
        engine = SchedulerEngine(chunk_size=32, min_bucket=8)
        engine.schedule(units, clusters)
        drifted = [
            halve_available(c) if j == 1 else c for j, c in enumerate(clusters)
        ]
        got = engine.schedule(units, drifted)
        assert engine.drift_stats["gated"] >= 1
        assert engine.drift_stats["skip"] == len(units), engine.drift_stats
        fresh = SchedulerEngine(chunk_size=32, min_bucket=8).schedule(
            units, drifted
        )
        results_equal(got, fresh)

        # Part 2: a drift that crosses the K boundary — the previous
        # winner's availability collapses, the runner-up must take the
        # single slot, and the gate must have recomputed.
        from kubeadmiral_tpu.models.types import ClusterState, SchedulingUnit

        gvk = "apps/v1/Deployment"

        def cluster(name, cpu_avail):
            return ClusterState(
                name=name, labels={},
                allocatable=parse_resources({"cpu": "64", "memory": "64Gi"}),
                available=parse_resources(
                    {"cpu": str(cpu_avail), "memory": "60Gi"}
                ),
                api_resources=frozenset({gvk}),
            )

        clusters2 = [cluster("lead", 60), cluster("next", 50)]
        units2 = [
            SchedulingUnit(
                gvk=gvk, namespace="ns", name=f"s-{i}",
                scheduling_mode="Duplicate", max_clusters=1,
                resource_request=parse_resources({"cpu": "100m"}),
            )
            for i in range(6)
        ]
        eng2 = SchedulerEngine(chunk_size=32, min_bucket=8)
        before = eng2.schedule(units2, clusters2)
        assert all(r.cluster_set == {"lead"} for r in before)
        drifted2 = [
            dataclasses.replace(
                clusters2[0],
                available=parse_resources({"cpu": "4", "memory": "60Gi"}),
            ),
            clusters2[1],
        ]
        after = eng2.schedule(units2, drifted2)
        assert eng2.drift_stats["gated"] >= 1, eng2.drift_stats
        assert (
            eng2.drift_stats["recompute"] + eng2.drift_stats["fallback"] > 0
        ), eng2.drift_stats
        fresh2 = SchedulerEngine(chunk_size=32, min_bucket=8).schedule(
            units2, drifted2
        )
        results_equal(after, fresh2)
        assert all(r.cluster_set == {"next"} for r in after)


class TestWantScoresBypass:
    def test_want_scores_drift_bypasses_gate_and_stays_exact(self):
        """Score-carrying consumers need exact score planes, which the
        gate's skip rows don't refresh per-decode — so a want_scores
        drift tick must take the full dispatch path, scores included."""
        units, clusters = make_world(b=32, c=8)
        engine = SchedulerEngine(chunk_size=32, min_bucket=8)
        engine.schedule(units, clusters, want_scores=True)
        drifted = [halve_available(c) if j == 0 else c
                   for j, c in enumerate(clusters)]
        got = engine.schedule(units, drifted, want_scores=True)
        assert engine.drift_stats["gated"] == 0, engine.drift_stats
        fresh = SchedulerEngine(chunk_size=32, min_bucket=8).schedule(
            units, drifted, want_scores=True
        )
        for a, b in zip(got, fresh):
            assert a.clusters == b.clusters and a.scores == b.scores


class TestFiniteKDynamicWeights:
    def test_topk_dynamic_weight_row_recomputes_on_cpu_drift(self):
        """A finite-K Divide row without given weights whose top-K
        selection contains the cpu-drifted column must RECOMPUTE: its
        weight set is the selection (not the feasible set), so the
        feasible-set weight check cannot decide it."""
        from kubeadmiral_tpu.models.types import (
            ClusterState, SchedulingUnit, MODE_DIVIDE,
        )

        gvk = "apps/v1/Deployment"

        def cluster(name, cpu_avail):
            return ClusterState(
                name=name,
                labels={},
                allocatable=parse_resources({"cpu": "64", "memory": "256Gi"}),
                available=parse_resources(
                    {"cpu": str(cpu_avail), "memory": "128Gi"}
                ),
                api_resources=frozenset({gvk}),
            )

        clusters = [cluster("big", 48), cluster("mid", 24), cluster("sml", 6)]
        units = [
            SchedulingUnit(
                gvk=gvk, namespace="ns", name=f"w-{i}",
                scheduling_mode=MODE_DIVIDE, desired_replicas=100,
                max_clusters=2,
                resource_request=parse_resources({"cpu": "100m"}),
            )
            for i in range(8)
        ]
        engine = SchedulerEngine(chunk_size=32, min_bucket=8)
        engine.schedule(units, clusters)
        drifted = [
            dataclasses.replace(
                clusters[0],
                available=parse_resources({"cpu": "12", "memory": "128Gi"}),
            )
        ] + clusters[1:]
        got = engine.schedule(units, drifted)
        fresh = SchedulerEngine(chunk_size=32, min_bucket=8).schedule(
            units, drifted
        )
        results_equal(got, fresh)
        assert engine.drift_stats["gated"] >= 1, engine.drift_stats
        # The weight shift really moved replicas, and the gate must have
        # routed these rows through a real recompute (slab or fallback).
        pre_drift = SchedulerEngine(chunk_size=32, min_bucket=8).schedule(
            units, clusters
        )
        assert any(g.clusters != p.clusters for g, p in zip(got, pre_drift))
        assert (
            engine.drift_stats["recompute"] > 0
            or engine.drift_stats["fallback"] > 0
        ), engine.drift_stats


class TestGeometryInvariance:
    def test_megachunk_and_small_chunks_identical(self):
        """Satellite (c): megachunk and 256-row-chunk geometries must
        produce identical placements for the same world — and the
        megachunk engine must issue fewer dispatches."""
        rng = np.random.default_rng(99)
        clusters = [random_cluster(rng, j) for j in range(12)]
        names = [c.name for c in clusters]
        units = [random_unit(rng, i, names) for i in range(200)]

        mega = SchedulerEngine(chunk_size=4096, min_bucket=16,
                               min_cluster_bucket=8)
        small = SchedulerEngine(chunk_size=32, min_bucket=16,
                                min_cluster_bucket=8)
        got_mega = mega.schedule(units, clusters)
        got_small = small.schedule(units, clusters)
        results_equal(got_mega, got_small)
        assert mega.dispatches_total < small.dispatches_total

        # And after a drift both geometries still agree.
        drifted = [halve_available(c) if j == 2 else c
                   for j, c in enumerate(clusters)]
        results_equal(
            mega.schedule(units, drifted), small.schedule(units, drifted)
        )

    def test_cell_budget_knob_bounds_rows(self):
        """KT_CELL_BUDGET / KT_MEGACHUNK_ROWS shape the chunk geometry.
        Both are PER-DEVICE limits (ISSUE 12): a mesh with N devices on
        the objects axis multiplies them, because chunks dispatch
        rows-sharded and each device resides only B/N rows."""
        eng = SchedulerEngine(cell_budget=512 * 64, megachunk_rows=4096,
                              mesh=None)
        c_bucket, eff_chunk, _ = eng._tick_geometry(512)
        assert c_bucket == 512 and eff_chunk == 64
        eng2 = SchedulerEngine(megachunk_rows=256, mesh=None)
        _, eff2, _ = eng2._tick_geometry(512)
        assert eff2 == 256
        # Default budget keeps full megachunks through the 5k config.
        eng3 = SchedulerEngine(mesh=None)
        _, eff3, _ = eng3._tick_geometry(5000)
        assert eff3 == 4096, eff3
        # Device-count-aware layout: the same per-device budget on an
        # N-device objects mesh allows N x the cells per chunk (capped
        # by chunk_size), so c6-wide cluster axes keep full megachunks.
        import jax

        from kubeadmiral_tpu.parallel import mesh as M

        if len(jax.devices()) >= 4:
            mesh = M.make_mesh(jax.devices()[:4])
            eng4 = SchedulerEngine(cell_budget=512 * 64, megachunk_rows=4096,
                                   mesh=mesh)
            _, eff4, _ = eng4._tick_geometry(512)
            assert eff4 == 64 * 4, eff4
            # c6's 10k cluster axis: one device's budget halves the
            # megachunk; 4 devices keep the full 4096 rows.
            solo = SchedulerEngine(mesh=None)
            _, eff_solo, _ = solo._tick_geometry(10_000)
            eng5 = SchedulerEngine(mesh=mesh)
            _, eff_mesh, _ = eng5._tick_geometry(10_000)
            assert eff_solo == 2048 and eff_mesh == 4096, (eff_solo, eff_mesh)


class TestPrewarmLadder:
    def test_prewarm_with_ladder_warms_drift_and_repair_programs(self, caplog):
        """The laddered prewarm path (wide-C geometries) must complete —
        including the drift-gate, weight-check and donated repair-chain
        warms (prewarm swallows exceptions into a warning; a swallowed
        failure here is a real bug) — and the engine must then schedule
        exactly."""
        import logging

        units, clusters = make_world(b=48, c=12)
        engine = SchedulerEngine(
            chunk_size=64, min_bucket=8, min_cluster_bucket=8, canonical_c=8
        )
        assert engine._tick_geometry(len(clusters))[2] is not None  # ladder on
        with caplog.at_level(logging.WARNING, logger="kubeadmiral.engine"):
            engine.prewarm(len(units), len(clusters), wait=True)
        assert not [r for r in caplog.records if "prewarm failed" in r.message], (
            [r.message for r in caplog.records]
        )
        got = engine.schedule(units, clusters)
        fresh = SchedulerEngine(
            chunk_size=64, min_bucket=8, min_cluster_bucket=8, canonical_c=8
        ).schedule(units, clusters)
        results_equal(got, fresh)
        drifted = [halve_available(c) if j == 0 else c
                   for j, c in enumerate(clusters)]
        results_equal(
            engine.schedule(units, drifted),
            SchedulerEngine(
                chunk_size=64, min_bucket=8, min_cluster_bucket=8,
                canonical_c=8,
            ).schedule(units, drifted),
        )


class TestNoopGate:
    def test_fresh_list_same_rows_rides_noop_gate(self):
        """A re-submitted batch that is a FRESH list of the SAME row
        objects must replay through the no-op gate's content-identity
        arm: no signature walk, no dispatch (the 100k-row no-op floor
        satellite)."""
        units, clusters = make_world(b=48, c=8)
        engine = SchedulerEngine(chunk_size=32, min_bucket=8)
        first = engine.schedule(units, clusters)
        noop0 = engine.fetch_stats["noop"]
        dispatch0 = engine.dispatches_total
        hits0 = engine.cache_stats["hit"]

        again = engine.schedule(list(units), clusters)  # fresh container
        assert engine.fetch_stats["noop"] > noop0
        assert engine.dispatches_total == dispatch0
        assert engine.cache_stats["hit"] == hits0  # no per-chunk walk
        assert engine.last_changed == []
        results_equal(first, again)

        # A genuinely changed fresh list must fall through.
        churned = list(units)
        row = next(
            i for i, u in enumerate(units) if u.scheduling_mode == "Divide"
        )
        churned[row] = dataclasses.replace(churned[row], desired_replicas=41)
        changed = engine.schedule(churned, clusters)
        assert sum(r != f for r, f in zip(changed, first)) >= 1
