"""Engine-side follower union (ops/follower.py): correctness vs the
naive full recompute, incrementality driven by the engine's changed-row
set, and the engine integration (schedule(follower_index=...)).
Reference semantics: follower placement = union of its leaders'
placements (pkg/controllers/follower/controller.go:95-521)."""

import dataclasses

import pytest

from kubeadmiral_tpu.models.types import (
    ClusterState,
    SchedulingUnit,
    parse_resources,
)
from kubeadmiral_tpu.ops.follower import FollowerIndex
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine, ScheduleResult


def make_world(b=12, c=4):
    units = [
        SchedulingUnit(
            gvk="apps/v1/Deployment",
            namespace="ns",
            name=f"w-{i}",
            scheduling_mode="Divide",
            desired_replicas=3 + i,
            resource_request=parse_resources({"cpu": "100m"}),
        )
        for i in range(b)
    ]
    clusters = [
        ClusterState(
            name=f"c{j}",
            labels={},
            taints=(),
            allocatable=parse_resources({"cpu": "64", "memory": "256Gi"}),
            available=parse_resources({"cpu": "64", "memory": "256Gi"}),
            api_resources=frozenset({"apps/v1/Deployment"}),
        )
        for j in range(c)
    ]
    return units, clusters


def naive_union(results, follows):
    out = list(results)
    for f, leaders in follows.items():
        union: dict = {}
        for leader in leaders:
            union.update(results[leader].clusters)
        out[f] = ScheduleResult(clusters=dict.fromkeys(union))
    return out


class TestFollowerIndex:
    def test_matches_naive_union(self):
        follows = {3: (0, 1, 2), 7: (4, 5), 11: (8,)}
        results = [
            ScheduleResult(clusters={f"c{i % 3}": i, f"c{(i + 1) % 3}": 1})
            for i in range(12)
        ]
        want = naive_union(results, follows)
        got = FollowerIndex(follows).apply(list(results), changed=None)
        for f in follows:
            assert got[f].clusters == want[f].clusters
            # union carries placement only, no replica counts
            assert all(v is None for v in got[f].clusters.values())

    def test_bipartite_enforced(self):
        with pytest.raises(ValueError):
            FollowerIndex({3: (1, 2), 2: (0,)})

    def test_incremental_recompute_only_affected(self):
        follows = {3: (0, 1), 7: (4, 5)}
        idx = FollowerIndex(follows)
        r1 = [ScheduleResult(clusters={"a": 1}) for _ in range(8)]
        idx.apply(r1, changed=None)
        cached_7 = idx._cache[7]
        # Leader 0 changed: follower 3 recomputes, follower 7 reuses.
        r2 = list(r1)
        r2[0] = ScheduleResult(clusters={"b": 2})
        out = idx.apply(r2, changed=[0])
        assert out[3].clusters == {"a": None, "b": None}
        assert idx._cache[7] is cached_7
        assert out[7] is cached_7

    def test_changed_none_recomputes_all(self):
        follows = {3: (0,)}
        idx = FollowerIndex(follows)
        r1 = [ScheduleResult(clusters={"a": 1}) for _ in range(4)]
        idx.apply(r1, changed=None)
        r2 = list(r1)
        r2[0] = ScheduleResult(clusters={"z": 9})
        out = idx.apply(r2, changed=None)
        assert out[3].clusters == {"z": None}


class TestEngineFollowerIntegration:
    def test_engine_applies_union_and_tracks_changed(self):
        units, clusters = make_world(b=12)
        follows = {11: (8, 9, 10)}
        engine = SchedulerEngine(chunk_size=8)
        fidx = FollowerIndex(follows)

        r1 = engine.schedule(units, clusters, follower_index=fidx)
        want = set()
        for leader in follows[11]:
            want.update(r1[leader].clusters)
        assert set(r1[11].clusters) == want
        assert engine.last_changed is None  # cold tick: everything new

        # No-op tick: nothing changed, union comes from cache.
        r2 = engine.schedule(units, clusters, follower_index=fidx)
        assert engine.last_changed == []
        assert r2[11] is r1[11]

        # Churn one leader: last_changed names it, union follows.
        churned = list(units)
        churned[9] = dataclasses.replace(
            churned[9], desired_replicas=units[9].desired_replicas + 50
        )
        r3 = engine.schedule(churned, clusters, follower_index=fidx)
        assert engine.last_changed is not None
        assert 9 in engine.last_changed
        want3 = set()
        for leader in follows[11]:
            want3.update(r3[leader].clusters)
        assert set(r3[11].clusters) == want3

    def test_last_changed_spans_chunks(self):
        units, clusters = make_world(b=20)
        engine = SchedulerEngine(chunk_size=8)
        engine.schedule(units, clusters)
        engine.schedule(units, clusters)
        churned = list(units)
        for i in (2, 17):
            churned[i] = dataclasses.replace(
                churned[i], desired_replicas=units[i].desired_replicas + 40
            )
        engine.schedule(churned, clusters)
        assert engine.last_changed is not None
        # Changed rows are reported with GLOBAL indices (chunk offsets
        # applied); placements that didn't move may be omitted, but a
        # replica bump this large must surface.
        assert {2, 17} <= set(engine.last_changed)
