"""Hand-derived golden cases for the sequential planner oracle.

Each expectation below is worked out by hand from the distribution rules
(see module docstring of planner_oracle; reference semantics:
pkg/controllers/util/planner/planner.go).  The batched device planner is
separately diff-tested against this oracle on randomized inputs.
"""

from kubeadmiral_tpu.ops.planner_oracle import ClusterPref, PlanInput, plan


def run(prefs, total, clusters=None, **kw):
    clusters = clusters if clusters is not None else sorted(prefs.keys() - {"*"})
    return plan(PlanInput(prefs=prefs, total=total, clusters=clusters, **kw))


def test_single_cluster_takes_all():
    p, o = run({"a": ClusterPref(weight=1)}, 7)
    assert p == {"a": 7} and o == {}


def test_conservation_equal_weights():
    p, o = run({c: ClusterPref(weight=1) for c in "abcd"}, 10)
    assert sum(p.values()) == 10
    assert o == {}
    # ceil(10/4)=3 for earlier clusters, running remainder caps the tail.
    assert sorted(p.values(), reverse=True) == [3, 3, 3, 1]


def test_weight_zero_cluster_only_gets_min():
    prefs = {"a": ClusterPref(weight=0, min_replicas=2), "b": ClusterPref(weight=1)}
    p, o = run(prefs, 5)
    assert p == {"a": 2, "b": 3} and o == {}


def test_max_replicas_caps_and_strands_remainder():
    prefs = {
        "a": ClusterPref(weight=1, max_replicas=1),
        "b": ClusterPref(weight=1, max_replicas=2),
    }
    p, o = run(prefs, 5)
    assert p == {"a": 1, "b": 2}
    assert o == {}  # max clipping is not overflow


def test_capacity_overflow_kept_by_default():
    p, o = run({"a": ClusterPref(weight=1)}, 5, capacity={"a": 2})
    assert p == {"a": 2}
    assert o == {"a": 3}  # avoid_disruption=False forces keep_unschedulable


def test_weighted_rounds_with_capacity():
    # a (w=2) sorts first; round 1: a gets ceil(10/3*2)=7 -> capped at 4
    # (overflow 3), b gets ceil(10/3)=4 capped by remainder; round 2 tops b up.
    prefs = {"a": ClusterPref(weight=2), "b": ClusterPref(weight=1)}
    p, o = run(prefs, 10, capacity={"a": 4})
    assert p == {"a": 4, "b": 6}
    assert o == {"a": 3}


def test_min_pass_respects_capacity_and_records_overflow():
    prefs = {"a": ClusterPref(weight=1, min_replicas=4)}
    p, o = run(prefs, 10, capacity={"a": 1})
    assert p == {"a": 1}
    # min pass wanted 4, capacity 1 -> overflow 3; rounds add ceil-overflow too.
    assert o["a"] >= 3


def test_wildcard_pref_applies_to_all():
    p, o = run({"*": ClusterPref(weight=1)}, 2, clusters=["a", "b"])
    assert sum(p.values()) == 2 and set(p) == {"a", "b"}


def test_cluster_without_pref_excluded():
    p, o = run({"a": ClusterPref(weight=1)}, 3, clusters=["a", "b"])
    assert p == {"a": 3}
    assert "b" not in p


def test_hash_tiebreak_is_key_dependent():
    prefs = {c: ClusterPref(weight=1) for c in ("a", "b", "c")}
    winners = set()
    for key in ("alpha", "beta", "x", "object-7", "ns/name"):
        p, _ = run(prefs, 1, key=key)
        (winner,) = [c for c, n in p.items() if n == 1]
        winners.add(winner)
    # With 7 different object keys the single replica should not always
    # land on the same cluster.
    assert len(winners) > 1


def test_avoid_disruption_no_move_when_totals_match():
    prefs = {"a": ClusterPref(weight=1), "b": ClusterPref(weight=1)}
    current = {"a": 4, "b": 1}
    p, _ = run(prefs, 5, current=current, avoid_disruption=True)
    # Desired would be ~(3,2) but moving replicas is avoided entirely.
    assert p == current


def test_avoid_disruption_scale_up_targets_shortfall():
    prefs = {"a": ClusterPref(weight=2), "b": ClusterPref(weight=1)}
    p, o = run(
        prefs, 5, current={"a": 0, "b": 0}, capacity={"a": 2}, avoid_disruption=True
    )
    # Desired: a capped at 2 (overflow trimmed since keep=False, all placed),
    # b takes the rest; scale-up from zero reproduces the desired layout.
    assert p == {"a": 2, "b": 3}
    assert o == {}


def test_avoid_disruption_scale_down_removes_excess_only():
    prefs = {"a": ClusterPref(weight=1), "b": ClusterPref(weight=1)}
    p, _ = run(prefs, 2, current={"a": 5, "b": 1}, avoid_disruption=True)
    # 4 replicas must go; only 'a' exceeds its desired share materially.
    assert sum(p.values()) == 2
    assert p["a"] >= p["b"] - 1
    assert p["a"] <= 5 and p["b"] <= 1


def test_avoid_disruption_current_capped_by_capacity():
    prefs = {"a": ClusterPref(weight=1)}
    p, _ = run(prefs, 3, current={"a": 5}, capacity={"a": 2}, avoid_disruption=True)
    # Current is clamped to capacity before comparison; shortfall of 1 has
    # nowhere else to go and a is capacity-capped in desired as well.
    assert p == {"a": 2}


def test_zero_total():
    p, o = run({"a": ClusterPref(weight=1)}, 0)
    assert p == {"a": 0} and o == {}


def test_no_weights_no_distribution():
    p, o = run({"a": ClusterPref(), "b": ClusterPref()}, 5)
    assert p == {"a": 0, "b": 0}
