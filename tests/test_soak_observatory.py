"""Soak observatory (ISSUE 16): telemetry-timeline downsampling
correctness and byte bound, tenant cardinality cap, the /debug
endpoint surface, and a fast in-process slice of the all-stressors
soak (concurrent churn + drift + faults; oracle bit-identity;
evaluator green outside injection windows)."""

import json
import urllib.request

import pytest

from kubeadmiral_tpu.runtime import slo as slo_mod
from kubeadmiral_tpu.runtime import tenancy, timeline
from kubeadmiral_tpu.runtime.healthcheck import (
    HealthCheckRegistry,
    HealthServer,
)
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.timeline import RAW_HORIZON_S, Timeline


def fetch(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, r.read()


def series_points(doc, tier, key):
    return doc["tiers"][tier]["series"][key]["points"]


class TestTimelineDownsampling:
    def test_counter_deltas_and_gauge_carry(self):
        m = Metrics()
        tl = Timeline(metrics=m, interval_s=1.0)
        m.counter("worker_reconciles_total", 3)
        m.store("worker_queue_depth", 7.0)
        tl.sample_now(now=1.0)
        m.counter("worker_reconciles_total", 2)
        tl.sample_now(now=2.0)
        doc = tl.to_doc()
        # Counters become per-interval deltas; gauges pass through.
        assert series_points(
            doc, "raw", "worker_reconciles_total"
        ) == [[1.0, 3.0], [2.0, 2.0]]
        assert series_points(
            doc, "raw", "worker_queue_depth"
        ) == [[1.0, 7.0], [2.0, 7.0]]

    def test_counter_never_negative_after_registry_reset(self):
        # A swapped/reset registry reads LOWER than the previous scrape;
        # the delta must clamp to 0, never go backwards.
        m = Metrics()
        tl = Timeline(metrics=m, interval_s=1.0)
        m.counter("worker_reconciles_total", 10)
        tl.sample_now(now=1.0)
        tl.metrics = Metrics()  # fresh registry: counter reads 0 < 10
        tl.metrics.counter("worker_reconciles_total", 1)
        tl.sample_now(now=2.0)
        pts = series_points(tl.to_doc(), "raw", "worker_reconciles_total")
        assert all(v >= 0 for _, v in pts), pts

    def test_tier_merge_sums_counters_and_maxes_gauges(self):
        m = Metrics()
        tl = Timeline(metrics=m, interval_s=1.0)
        # Six samples inside one 10s-tier slot, then one far beyond the
        # raw horizon to force age promotion.
        for i in range(6):
            m.counter("worker_reconciles_total", 1)
            m.store("worker_queue_depth", float(i))  # max = 5
            tl.sample_now(now=1.0 + i)
        m.store("worker_queue_depth", 0.0)
        tl.sample_now(now=RAW_HORIZON_S + 100.0)
        doc = tl.to_doc(tier="10s")
        merged = {
            key: s["points"]
            for key, s in doc["tiers"]["10s"]["series"].items()
        }
        # The six 1-delta samples merged into one bucket: SUM for the
        # counter, MAX for the gauge — a spike cannot average away.
        assert merged["worker_reconciles_total"][0][1] == 6.0
        assert merged["worker_queue_depth"][0][1] == 5.0

    def test_ring_byte_bound(self):
        m = Metrics()
        tl = Timeline(metrics=m, interval_s=1.0, max_bytes=20_000)
        for i in range(500):
            m.counter("worker_reconciles_total", 1)
            m.store("worker_queue_depth", float(i % 17))
            tl.sample_now(now=float(i))
        doc = tl.to_doc()
        assert doc["approx_bytes"] <= 20_000, doc["approx_bytes"]
        assert doc["samples_total"] == 500
        # Downsampling, not amnesia: the promoted tiers still carry
        # history (or, at worst, terminal-tier drops were counted).
        total_buckets = sum(
            t["buckets"] for t in doc["tiers"].values()
        )
        assert total_buckets > 0
        assert doc["dropped_buckets_total"] >= 0

    def test_disabled_timeline_creates_no_thread(self, monkeypatch):
        monkeypatch.setenv("KT_TIMELINE", "0")
        tl = Timeline(metrics=Metrics(), interval_s=0.01)
        assert tl.start() is False
        assert tl._thread is None
        assert tl.sample_now() is False
        assert tl.to_doc()["enabled"] is False

    def test_sampler_thread_lifecycle(self):
        m = Metrics()
        tl = Timeline(metrics=m, interval_s=0.01)
        assert tl.start() is True
        try:
            import time as _time

            deadline = _time.monotonic() + 5.0
            while _time.monotonic() < deadline:
                if tl.to_doc()["samples_total"] >= 3:
                    break
                _time.sleep(0.01)
            assert tl.to_doc()["samples_total"] >= 3
        finally:
            tl.stop()
        assert tl._thread is None


class TestTenantLedger:
    def test_cardinality_cap_collapses_to_other(self):
        ledger = tenancy.TenantLedger(metrics=Metrics(), max_tenants=2)
        ledger.note_event("alpha", 0.1)
        ledger.note_event("beta", 0.2)
        ledger.note_event("gamma", 0.3)   # over the cap -> ~other
        ledger.note_event("delta", 0.4)   # also ~other
        doc = ledger.summary()
        assert sorted(doc["tenants"]) == ["alpha", "beta", tenancy.OTHER]
        assert doc["overflowed"] is True
        assert doc["tenants"][tenancy.OTHER]["events"] == 2

    def test_burn_and_breaches(self, monkeypatch):
        monkeypatch.setenv("KT_SLO_E2E_P99_S", "1.0")
        ledger = tenancy.TenantLedger(metrics=Metrics())
        ledger.note_event("t", 0.5)   # good
        ledger.note_event("t", 2.0)   # breach
        doc = ledger.summary()["tenants"]["t"]
        assert doc["events"] == 2 and doc["breaches"] == 1
        assert doc["slo_burn"] > 1.0  # 50% bad >> allowed bad fraction

    def test_tenant_of_label_override(self, monkeypatch):
        assert tenancy.tenant_of("ns-a") == "ns-a"
        assert tenancy.tenant_of("") == tenancy.CLUSTER_SCOPED
        assert tenancy.tenant_of_key("ns-b/obj") == "ns-b"
        monkeypatch.setenv("KT_TENANT_LABEL", "team")
        assert tenancy.tenant_of("ns-a", {"team": "alpha"}) == "alpha"
        assert tenancy.tenant_of("ns-a", {"other": "x"}) == "ns-a"


class TestDebugEndpoints:
    def test_index_timeline_and_tenants_served(self):
        m = Metrics()
        m.counter("worker_reconciles_total", 5)
        tl = Timeline(metrics=m, interval_s=1.0)
        tl.sample_now(now=1.0)
        ledger = tenancy.TenantLedger(metrics=m)
        ledger.note_event("team-a", 0.1)
        server = HealthServer(
            HealthCheckRegistry(), metrics=m, timeline=tl, tenants=ledger
        )
        port = server.start()
        try:
            status, body = fetch(port, "/debug")
            assert status == 200
            endpoints = json.loads(body)["endpoints"]
            for route in (
                "/metrics", "/debug/timeline", "/debug/tenants",
                "/debug/slo", "/debug/members",
            ):
                assert route in endpoints, route

            status, body = fetch(port, "/debug/timeline")
            assert status == 200
            doc = json.loads(body)
            assert doc["samples_total"] == 1
            assert "raw" in doc["tiers"] and "60s" in doc["tiers"]
            assert (
                "worker_reconciles_total" in doc["tiers"]["raw"]["series"]
            )

            # ?series= filter narrows, ?tier= selects one tier.
            status, body = fetch(
                port, "/debug/timeline?series=reconciles&tier=raw"
            )
            doc = json.loads(body)
            assert list(doc["tiers"]) == ["raw"]
            assert list(doc["tiers"]["raw"]["series"]) == [
                "worker_reconciles_total"
            ]

            status, body = fetch(port, "/debug/tenants")
            assert status == 200
            doc = json.loads(body)
            assert doc["tenants"]["team-a"]["events"] == 1
        finally:
            server.stop()

    def test_timeline_404_when_not_installed(self):
        server = HealthServer(HealthCheckRegistry(), metrics=Metrics())
        port = server.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                fetch(port, "/debug/timeline")
            assert exc.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as exc:
                fetch(port, "/debug/tenants")
            assert exc.value.code == 404
        finally:
            server.stop()


class TestSoakSlice:
    """A fast in-process slice of the full soak: arrivals + churn +
    drift + a flapping and a hard-down member all concurrently, then
    the two gate properties checked directly."""

    def _run(self, faults, monkeypatch):
        from kubeadmiral_tpu.testing.soakharness import (
            SoakHarness,
            SoakSchedule,
        )

        monkeypatch.setenv("KT_SLO_FRESHNESS_S", "1.0")
        monkeypatch.setenv("KT_SLO_WINDOWS_S", "3,10")
        sched = SoakSchedule(
            rounds=5, arrivals_per_round=3, churn_per_round=2, members=3,
            drift_every=2, flap_window=(1, 4), down_window=(2, 4),
            flap_member_idx=1, down_member_idx=2,
        )
        m = Metrics()
        slo_mod.reset_default()
        ledger = tenancy.TenantLedger(metrics=m)
        tenancy.set_default(ledger)
        tl = Timeline(metrics=m)
        try:
            h = SoakHarness(sched, metrics=m)
            h.attach_timeline(tl)
            for r in range(sched.rounds):
                h.run_round(r, faults=faults)
            h.finish()
            return h.fingerprint(), h.windows, tl.to_doc(), ledger.summary()
        finally:
            tenancy.reset_default()
            slo_mod.reset_default()

    @pytest.mark.slow
    def test_oracle_bit_identity_and_green_outside_windows(
        self, monkeypatch
    ):
        from bench import _soak_red_outside

        oracle_fp, _, _, _ = self._run(False, monkeypatch)
        fp, windows, doc, tenants = self._run(True, monkeypatch)

        # Faults touched only the write path: placements bit-identical.
        assert fp["hash"] == oracle_fp["hash"]
        assert fp["placements"] == oracle_fp["placements"]
        assert fp["objects"] == 5 * 3

        # Both injection windows opened and closed (recovery confirmed).
        assert {(w["kind"], w["t1"] is not None) for w in windows} == {
            ("flap", True), ("down", True),
        }

        # The evaluator was never red outside a declared window.
        assert _soak_red_outside(doc, windows) == []

        # ... and red INSIDE one: the hard-down member must trip the
        # freshness objective (otherwise the gate is vacuous).
        red = [
            (t, v)
            for key, s in doc["tiers"]["raw"]["series"].items()
            if key.startswith("slo_red{")
            for t, v in s["points"]
            if v > 0
        ]
        assert red, "hard-down member never turned the evaluator red"

        # Every tenant namespace got attributed work.
        assert set(sched_tenants(tenants)) >= {
            "team-a", "team-b", "team-c"
        }

    def test_red_outside_window_is_flagged(self):
        from bench import _soak_red_outside

        doc = {
            "tiers": {
                "raw": {
                    "series": {
                        "slo_red{objective=freshness}": {
                            "kind": "gauge",
                            "points": [[5.0, 0.0], [10.0, 1.0]],
                        }
                    }
                }
            }
        }
        inside = [{"member": "m", "kind": "down", "t0": 9.0, "t1": 12.0}]
        outside = [{"member": "m", "kind": "down", "t0": 20.0, "t1": None}]
        assert _soak_red_outside(doc, inside) == []
        flagged = _soak_red_outside(doc, outside)
        assert len(flagged) == 1 and flagged[0]["t"] == 10.0


def sched_tenants(tenants_doc):
    return list((tenants_doc.get("tenants") or {}).keys())
