"""Incremental featurization: cached chunks must never change results.

Every path (full hit, row patch, topology invalidation, resource drift)
is differentially checked against a cache-less engine on the same world.
"""

import dataclasses

import numpy as np

from kubeadmiral_tpu.models.types import (
    ClusterState,
    MODE_DIVIDE,
    SchedulingUnit,
    Taint,
    Toleration,
    parse_resources,
)
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine


def make_world(b=64, c=12):
    clusters = [
        ClusterState(
            name=f"m-{j:03d}",
            labels={"region": "eu" if j % 2 else "us"},
            taints=(Taint("dedicated", "x", "NoSchedule"),) if j % 5 == 0 else (),
            allocatable=parse_resources({"cpu": str(8 + j), "memory": f"{32 + j}Gi"}),
            available=parse_resources({"cpu": str(4 + j // 2), "memory": f"{16 + j}Gi"}),
            api_resources=frozenset({"apps/v1/Deployment"}),
        )
        for j in range(c)
    ]
    units = [
        SchedulingUnit(
            gvk="apps/v1/Deployment",
            namespace=f"ns-{i % 5}",
            name=f"w-{i:04d}",
            scheduling_mode=MODE_DIVIDE if i % 3 else "Duplicate",
            desired_replicas=(i % 20) + 1,
            resource_request=parse_resources({"cpu": f"{(i % 4) * 100}m"}),
            tolerations=(Toleration(key="dedicated", operator="Exists"),)
            if i % 2
            else (),
            avoid_disruption=bool(i % 2),
        )
        for i in range(b)
    ]
    return units, clusters


def results_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x.clusters == y.clusters


class TestEngineCache:
    def test_unchanged_retick_hits_and_matches(self):
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32)
        first = engine.schedule(units, clusters)
        second = engine.schedule(units, clusters)
        assert engine.cache_stats["hit"] >= 2  # both chunks
        results_equal(first, second)

    def test_small_churn_patches_and_matches_fresh(self):
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32)
        engine.schedule(units, clusters)

        churned = list(units)
        for k in (3, 40):
            churned[k] = dataclasses.replace(
                units[k],
                desired_replicas=(units[k].desired_replicas or 1) + 7,
                resource_request=parse_resources({"cpu": "900m"}),
            )
        got = engine.schedule(churned, clusters)
        assert engine.cache_stats["patch"] >= 2
        want = SchedulerEngine(chunk_size=32).schedule(churned, clusters)
        results_equal(got, want)

    def test_resource_drift_keeps_cache_and_matches_fresh(self):
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32)
        engine.schedule(units, clusters)
        drifted = [
            dataclasses.replace(
                cl, available=parse_resources({"cpu": "2", "memory": "8Gi"})
            )
            for cl in clusters
        ]
        got = engine.schedule(units, drifted)
        assert engine.cache_stats["hit"] >= 2
        assert engine.cache_stats["miss"] == 2  # only the cold tick
        want = SchedulerEngine(chunk_size=32).schedule(units, drifted)
        results_equal(got, want)

    def test_topology_change_invalidates(self):
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32)
        engine.schedule(units, clusters)
        relabeled = [
            dataclasses.replace(cl, labels={**cl.labels, "tier": "gold"})
            for cl in clusters
        ]
        got = engine.schedule(units, relabeled)
        assert engine.cache_stats["miss"] >= 4  # cold tick + invalidated
        want = SchedulerEngine(chunk_size=32).schedule(units, relabeled)
        results_equal(got, want)

    def test_mass_churn_falls_back_to_full_featurize(self):
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32)
        engine.schedule(units, clusters)
        churned = [
            dataclasses.replace(u, desired_replicas=50) for u in units
        ]
        got = engine.schedule(churned, clusters)
        assert engine.cache_stats["patch"] == 0
        want = SchedulerEngine(chunk_size=32).schedule(churned, clusters)
        results_equal(got, want)

    def test_delta_fetch_paths_engage_and_match(self):
        """Steady-state re-tick = mask-only fetch; small churn = row
        gather; both must equal a cache-less engine's results."""
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32)
        engine.schedule(units, clusters)
        assert engine.fetch_stats == {
            "noop": 0, "subbatch": 0, "skip": 0, "delta": 0, "full": 2,
        }

        # Identical units + identical cluster view: the dispatch itself
        # is skipped (trigger-hash-skip analogue).
        second = engine.schedule(units, clusters)
        assert engine.fetch_stats["noop"] == 2
        results_equal(second, SchedulerEngine(chunk_size=32).schedule(units, clusters))

        # Same units but drifted resources: must NOT take the no-op path
        # (outputs may change), and every chunk must ride a dispatching
        # path (mask-only, row gather, or full).
        import dataclasses as _dc
        drifted = [
            _dc.replace(cl, available=dict(cl.available)) for cl in clusters
        ]
        drifted[0] = _dc.replace(
            drifted[0], available=parse_resources({"cpu": "1", "memory": "1Gi"})
        )
        before = dict(engine.fetch_stats)
        third = engine.schedule(units, drifted)
        assert engine.fetch_stats["noop"] == before["noop"]
        dispatched = sum(
            engine.fetch_stats[k] - before[k] for k in ("skip", "delta", "full")
        )
        assert dispatched == 2
        results_equal(third, SchedulerEngine(chunk_size=32).schedule(units, drifted))

        # Re-sync to the original cluster list so the next tick compares
        # against an identical ClusterView object.
        engine.schedule(units, clusters)

        # Churn with an unchanged cluster view rides the sub-batch path:
        # only the changed rows are scheduled (row independence).
        churned = list(units)
        churned[5] = dataclasses.replace(
            units[5], desired_replicas=37,
            resource_request=parse_resources({"cpu": "700m"}),
        )
        got = engine.schedule(churned, clusters)
        assert engine.fetch_stats["subbatch"] >= 1
        results_equal(got, SchedulerEngine(chunk_size=32).schedule(churned, clusters))

        # Churn + resource drift in the same tick: every row may change,
        # so the full dispatch runs with the on-device delta gather.
        churned2 = list(churned)
        churned2[7] = dataclasses.replace(
            churned[7], desired_replicas=11,
        )
        drifted = list(clusters)
        drifted[0] = dataclasses.replace(
            clusters[0], available=parse_resources({"cpu": "2", "memory": "4Gi"})
        )
        before = dict(engine.fetch_stats)
        got2 = engine.schedule(churned2, drifted)
        assert engine.fetch_stats["subbatch"] == before["subbatch"]
        assert (
            engine.fetch_stats["skip"]
            + engine.fetch_stats["delta"]
            + engine.fetch_stats["full"]
            > before["skip"] + before["delta"] + before["full"]
        )
        results_equal(
            got2, SchedulerEngine(chunk_size=32).schedule(churned2, drifted)
        )

    def test_results_are_caller_owned_copies(self):
        """Returned dicts must be safe to mutate: the delta path reuses
        cached decodes internally, so it hands out fresh copies."""
        units, clusters = make_world(b=8)
        engine = SchedulerEngine(chunk_size=8)
        first = engine.schedule(units, clusters)
        first[0].clusters["poison"] = 1
        second = engine.schedule(units, clusters)
        assert "poison" not in second[0].clusters

    def test_cache_budget_zero_disables(self):
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32, cache_bytes=0)
        first = engine.schedule(units, clusters)
        second = engine.schedule(units, clusters)
        assert engine.cache_stats["hit"] == 0
        results_equal(first, second)
