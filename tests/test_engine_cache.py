"""Incremental featurization: cached chunks must never change results.

Every path (full hit, row patch, topology invalidation, resource drift)
is differentially checked against a cache-less engine on the same world.
"""

import dataclasses

import numpy as np

from kubeadmiral_tpu.models.types import (
    ClusterState,
    MODE_DIVIDE,
    SchedulingUnit,
    Taint,
    Toleration,
    parse_resources,
)
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine


def make_world(b=64, c=12):
    clusters = [
        ClusterState(
            name=f"m-{j:03d}",
            labels={"region": "eu" if j % 2 else "us"},
            taints=(Taint("dedicated", "x", "NoSchedule"),) if j % 5 == 0 else (),
            allocatable=parse_resources({"cpu": str(8 + j), "memory": f"{32 + j}Gi"}),
            available=parse_resources({"cpu": str(4 + j // 2), "memory": f"{16 + j}Gi"}),
            api_resources=frozenset({"apps/v1/Deployment"}),
        )
        for j in range(c)
    ]
    units = [
        SchedulingUnit(
            gvk="apps/v1/Deployment",
            namespace=f"ns-{i % 5}",
            name=f"w-{i:04d}",
            scheduling_mode=MODE_DIVIDE if i % 3 else "Duplicate",
            desired_replicas=(i % 20) + 1,
            resource_request=parse_resources({"cpu": f"{(i % 4) * 100}m"}),
            tolerations=(Toleration(key="dedicated", operator="Exists"),)
            if i % 2
            else (),
            avoid_disruption=bool(i % 2),
        )
        for i in range(b)
    ]
    return units, clusters


def results_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x.clusters == y.clusters


class TestEngineCache:
    def test_unchanged_retick_hits_and_matches(self):
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32)
        first = engine.schedule(units, clusters)
        # Rebuild one row as an equal-but-distinct object: a plain
        # fresh list now replays through the no-op gate's content-
        # identity arm, and the point here is the PER-CHUNK hit path
        # (equal featurize signature -> cache hit, no re-featurize).
        resubmitted = [dataclasses.replace(units[0])] + list(units[1:])
        second = engine.schedule(resubmitted, clusters)
        assert engine.cache_stats["hit"] >= 2  # both chunks
        results_equal(first, second)

    def test_small_churn_patches_and_matches_fresh(self):
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32)
        engine.schedule(units, clusters)

        churned = list(units)
        for k in (3, 40):
            churned[k] = dataclasses.replace(
                units[k],
                desired_replicas=(units[k].desired_replicas or 1) + 7,
                resource_request=parse_resources({"cpu": "900m"}),
            )
        got = engine.schedule(churned, clusters)
        assert engine.cache_stats["patch"] >= 2
        want = SchedulerEngine(chunk_size=32).schedule(churned, clusters)
        results_equal(got, want)

    def test_resource_drift_keeps_cache_and_matches_fresh(self):
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32)
        engine.schedule(units, clusters)
        drifted = [
            dataclasses.replace(
                cl, available=parse_resources({"cpu": "2", "memory": "8Gi"})
            )
            for cl in clusters
        ]
        got = engine.schedule(units, drifted)
        assert engine.cache_stats["hit"] >= 2
        assert engine.cache_stats["miss"] == 2  # only the cold tick
        want = SchedulerEngine(chunk_size=32).schedule(units, drifted)
        results_equal(got, want)

    def test_topology_change_invalidates(self):
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32)
        engine.schedule(units, clusters)
        relabeled = [
            dataclasses.replace(cl, labels={**cl.labels, "tier": "gold"})
            for cl in clusters
        ]
        got = engine.schedule(units, relabeled)
        assert engine.cache_stats["miss"] >= 4  # cold tick + invalidated
        want = SchedulerEngine(chunk_size=32).schedule(units, relabeled)
        results_equal(got, want)

    def test_mass_churn_falls_back_to_full_featurize(self):
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32)
        engine.schedule(units, clusters)
        churned = [
            dataclasses.replace(u, desired_replicas=50) for u in units
        ]
        got = engine.schedule(churned, clusters)
        assert engine.cache_stats["patch"] == 0
        want = SchedulerEngine(chunk_size=32).schedule(churned, clusters)
        results_equal(got, want)

    def test_delta_fetch_paths_engage_and_match(self):
        """Steady-state re-tick = mask-only fetch; small churn = row
        gather; both must equal a cache-less engine's results."""
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32)
        engine.schedule(units, clusters)
        assert engine.fetch_stats == {
            "noop": 0, "subbatch": 0, "skip": 0, "delta": 0, "full": 2,
        }

        # Identical units + identical cluster view: the dispatch itself
        # is skipped (trigger-hash-skip analogue).
        second = engine.schedule(units, clusters)
        assert engine.fetch_stats["noop"] == 2
        results_equal(second, SchedulerEngine(chunk_size=32).schedule(units, clusters))

        # Same units but drifted resources: must NOT take the no-op path
        # (outputs may change), and every chunk must ride a dispatching
        # path (mask-only, row gather, or full).
        import dataclasses as _dc
        drifted = [
            _dc.replace(cl, available=dict(cl.available)) for cl in clusters
        ]
        drifted[0] = _dc.replace(
            drifted[0], available=parse_resources({"cpu": "1", "memory": "1Gi"})
        )
        before = dict(engine.fetch_stats)
        third = engine.schedule(units, drifted)
        assert engine.fetch_stats["noop"] == before["noop"]
        dispatched = sum(
            engine.fetch_stats[k] - before[k] for k in ("skip", "delta", "full")
        )
        assert dispatched == 2
        results_equal(third, SchedulerEngine(chunk_size=32).schedule(units, drifted))

        # Re-sync to the original cluster list so the next tick compares
        # against an identical ClusterView object.
        engine.schedule(units, clusters)

        # Churn with an unchanged cluster view rides the sub-batch path:
        # only the changed rows are scheduled (row independence).
        churned = list(units)
        churned[5] = dataclasses.replace(
            units[5], desired_replicas=37,
            resource_request=parse_resources({"cpu": "700m"}),
        )
        got = engine.schedule(churned, clusters)
        assert engine.fetch_stats["subbatch"] >= 1
        results_equal(got, SchedulerEngine(chunk_size=32).schedule(churned, clusters))

        # Churn + resource drift in the same tick: every row may change,
        # so the full dispatch runs with the on-device delta gather.
        churned2 = list(churned)
        churned2[7] = dataclasses.replace(
            churned[7], desired_replicas=11,
        )
        drifted = list(clusters)
        drifted[0] = dataclasses.replace(
            clusters[0], available=parse_resources({"cpu": "2", "memory": "4Gi"})
        )
        before = dict(engine.fetch_stats)
        got2 = engine.schedule(churned2, drifted)
        assert engine.fetch_stats["subbatch"] == before["subbatch"]
        assert (
            engine.fetch_stats["skip"]
            + engine.fetch_stats["delta"]
            + engine.fetch_stats["full"]
            > before["skip"] + before["delta"] + before["full"]
        )
        results_equal(
            got2, SchedulerEngine(chunk_size=32).schedule(churned2, drifted)
        )

    def test_results_are_immutable_shared_views(self):
        """The engine shares cached decodes by reference (copying every
        row per tick was the config-5 host floor), so the returned
        results must refuse mutation — both the mappings and the
        attributes — to protect the cache."""
        import dataclasses

        import pytest

        units, clusters = make_world(b=8)
        engine = SchedulerEngine(chunk_size=8)
        first = engine.schedule(units, clusters)
        with pytest.raises(TypeError):
            first[0].clusters["poison"] = 1
        with pytest.raises(dataclasses.FrozenInstanceError):
            first[0].clusters = {}
        second = engine.schedule(units, clusters)
        assert "poison" not in second[0].clusters

    def test_cache_budget_zero_disables(self):
        units, clusters = make_world()
        engine = SchedulerEngine(chunk_size=32, cache_bytes=0)
        first = engine.schedule(units, clusters)
        second = engine.schedule(units, clusters)
        assert engine.cache_stats["hit"] == 0
        results_equal(first, second)


class TestScoresCachePaths:
    """want_scores rides the same cache/delta machinery as placements
    (VERDICT r2 weak #6) and can never replay stale placements when
    toggled across ticks (ADVICE r2 medium #1)."""

    def small_engine(self):
        return SchedulerEngine(chunk_size=32, min_bucket=8, cache_bytes=1 << 30)

    def test_want_scores_toggle_never_replays_stale_placements(self):
        """The ADVICE r2 repro: a want_scores tick that patches cached
        rows, followed by a plain tick, must reflect the patch."""
        units, clusters = make_world(b=24, c=6)
        engine = self.small_engine()
        engine.schedule(units, clusters, want_scores=True)
        churned = list(units)
        # Unit 4 is Divide-mode (make_world: i % 3 != 0), so the plan
        # carries actual replica counts to assert on.
        churned[4] = dataclasses.replace(churned[4], desired_replicas=77)
        with_scores = engine.schedule(churned, clusters, want_scores=True)
        plain = engine.schedule(churned, clusters, want_scores=False)
        fresh = SchedulerEngine(chunk_size=32, min_bucket=8).schedule(
            churned, clusters
        )
        results_equal(plain, fresh)
        results_equal(with_scores, fresh)
        # The changed object's plan actually moved (77 replicas placed).
        placed = sum(v for v in plain[4].clusters.values() if v)
        assert placed >= 77

    def test_want_scores_retick_takes_noop_path_with_scores(self):
        units, clusters = make_world(b=24, c=6)
        engine = self.small_engine()
        first = engine.schedule(units, clusters, want_scores=True)
        second = engine.schedule(units, clusters, want_scores=True)
        assert engine.fetch_stats["noop"] >= 1
        for x, y in zip(first, second):
            assert x.clusters == y.clusters and x.scores == y.scores
        assert any(r.scores for r in second)

    def test_want_scores_churn_takes_subbatch_and_keeps_scores(self):
        units, clusters = make_world(b=32, c=6)
        engine = self.small_engine()
        engine.schedule(units, clusters, want_scores=True)
        churned = list(units)
        churned[5] = dataclasses.replace(churned[5], desired_replicas=9)
        got = engine.schedule(churned, clusters, want_scores=True)
        assert engine.fetch_stats["subbatch"] >= 1
        fresh = SchedulerEngine(chunk_size=32, min_bucket=8).schedule(
            churned, clusters, want_scores=True
        )
        for x, y in zip(got, fresh):
            assert x.clusters == y.clusters
            assert x.scores == y.scores

    def test_plain_cache_upgrades_to_scores_via_full_fetch(self):
        """A plain-cached chunk asked for scores re-fetches fully once,
        then serves scored fast paths."""
        units, clusters = make_world(b=24, c=6)
        engine = self.small_engine()
        engine.schedule(units, clusters)  # prev_has_scores=False
        scored = engine.schedule(units, clusters, want_scores=True)
        assert any(r.scores for r in scored)
        before = dict(engine.fetch_stats)
        again = engine.schedule(units, clusters, want_scores=True)
        assert engine.fetch_stats["noop"] > before["noop"]
        for x, y in zip(scored, again):
            assert x.scores == y.scores


class TestPrewarm:
    def test_prewarm_compiles_and_matches(self):
        units, clusters = make_world(b=24, c=6)
        engine = SchedulerEngine(chunk_size=32, min_bucket=8)
        engine.prewarm(len(units), len(clusters), wait=True)
        got = engine.schedule(units, clusters)
        fresh = SchedulerEngine(chunk_size=32, min_bucket=8).schedule(
            units, clusters
        )
        results_equal(got, fresh)


class TestLazyDeviceRepair:
    def test_drift_after_churn_matches_fresh(self):
        """Churn tick (sub-batch) then cluster-resource drift tick: the
        cached device tensors are scatter-repaired, results exact."""
        units, clusters = make_world(b=48, c=8)
        engine = SchedulerEngine(chunk_size=64, min_bucket=8)
        engine.schedule(units, clusters)
        engine.schedule(units, clusters)  # prev stored, device cached
        churned = list(units)
        for i in (2, 7, 11):
            churned[i] = dataclasses.replace(
                churned[i], desired_replicas=50 + i
            )
        engine.schedule(churned, clusters)
        assert engine.fetch_stats["subbatch"] >= 1
        drifted = list(clusters)
        drifted[0] = dataclasses.replace(
            drifted[0],
            available={k: max(0, v // 3) for k, v in drifted[0].available.items()},
        )
        got = engine.schedule(churned, drifted)
        fresh = SchedulerEngine(chunk_size=64, min_bucket=8).schedule(
            churned, drifted
        )
        results_equal(got, fresh)
        # And a second churn after the repair still merges exactly.
        churned2 = list(churned)
        churned2[5] = dataclasses.replace(churned2[5], desired_replicas=33)
        got2 = engine.schedule(churned2, drifted)
        fresh2 = SchedulerEngine(chunk_size=64, min_bucket=8).schedule(
            churned2, drifted
        )
        results_equal(got2, fresh2)


def test_drift_after_churn_fetches_delta_not_full():
    """The bench's steady sequence: cold tick, a churned tick (sub-batch
    merge patches prev_results host-side), then a cluster drift.  The
    drift dispatch must DELTA-fetch: prev_out survives the sub-batch
    pass, with the patched rows force-gathered via stale_out_rows
    (VERDICT r3 #3 — this was the "6 full of 21" fetch profile)."""
    units, clusters = make_world(b=48, c=10)
    engine = SchedulerEngine(min_bucket=8)
    fresh = SchedulerEngine(min_bucket=8)
    engine.schedule(units, clusters)

    # Churn a couple of rows: rides the sub-batch path.
    churned = list(units)
    churned[3] = dataclasses.replace(churned[3], desired_replicas=40)
    churned[17] = dataclasses.replace(churned[17], desired_replicas=1)
    engine.schedule(churned, clusters)
    assert engine.fetch_stats["subbatch"] >= 1, engine.fetch_stats
    full_before = engine.fetch_stats["full"]

    drifted = [
        dataclasses.replace(
            c, available={k: max(0, v // 2) for k, v in c.available.items()}
        )
        if i == 0
        else c
        for i, c in enumerate(clusters)
    ]
    got = engine.schedule(churned, drifted)
    assert got == fresh.schedule(churned, drifted)  # exactness first
    assert engine.fetch_stats["delta"] >= 1, engine.fetch_stats
    assert engine.fetch_stats["full"] == full_before, engine.fetch_stats


def test_label_churn_miss_carries_prev_outputs():
    """A topology-changing miss with unchanged cluster names (label flip
    on one cluster) keeps the previous outputs armed: the re-dispatch
    skips or delta-fetches instead of refetching the whole chunk."""
    units, clusters = make_world(b=48, c=10)
    engine = SchedulerEngine(min_bucket=8)
    fresh = SchedulerEngine(min_bucket=8)
    engine.schedule(units, clusters)
    full_before = engine.fetch_stats["full"]

    relabeled = [
        dataclasses.replace(c, labels=dict(c.labels, extra="yes"))
        if i == 1
        else c
        for i, c in enumerate(clusters)
    ]
    got = engine.schedule(units, relabeled)
    assert got == fresh.schedule(units, relabeled)
    assert engine.cache_stats["miss"] >= 2, engine.cache_stats  # topo miss
    assert engine.fetch_stats["full"] == full_before, engine.fetch_stats
    assert (
        engine.fetch_stats["delta"] + engine.fetch_stats["skip"] >= 1
    ), engine.fetch_stats


def test_renamed_fleet_never_reuses_stale_decodes():
    """A different fleet with a coincidentally identical output PATTERN
    must not ride the carried-prev delta path: decodes map column
    indices to names, so the carry is gated on unchanged name order."""
    units, _ = make_world(b=4, c=2)
    engine = SchedulerEngine(min_bucket=8)
    fleet_a = [
        ClusterState(
            name=n,
            labels={},
            allocatable=parse_resources({"cpu": "64", "memory": "256Gi"}),
            available=parse_resources({"cpu": "32", "memory": "128Gi"}),
            api_resources=frozenset({"apps/v1/Deployment"}),
        )
        for n in ("slow", "fast")
    ]
    fleet_b = [dataclasses.replace(c, name=n) for c, n in zip(fleet_a, ("small", "big"))]
    res_a = engine.schedule(units, fleet_a)
    res_b = engine.schedule(units, fleet_b)
    names_b = {n for r in res_b for n in r.clusters}
    assert names_b <= {"small", "big"}, names_b
    fresh = SchedulerEngine(min_bucket=8)
    assert res_b == fresh.schedule(units, fleet_b)
    assert res_a != res_b  # same pattern, different names


def test_whole_batch_noop_gate_is_identity_keyed():
    """The SAME units list against the same cluster view replays the
    previous results in O(1); a fresh list with a changed row falls
    through to the real gates."""
    from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

    units, clusters = make_world(40, 6)
    eng = SchedulerEngine(chunk_size=16, min_bucket=8)
    first = eng.schedule(units, clusters)
    noops_before = eng.fetch_stats["noop"]
    again = eng.schedule(units, clusters)
    assert again == first and again is not first  # replayed into a fresh list
    assert again[0] is first[0]  # rows shared (frozen)
    assert eng.fetch_stats["noop"] > noops_before
    assert eng.last_changed == []

    import dataclasses

    churned = list(units)
    row = next(
        i for i, u in enumerate(units) if u.scheduling_mode == "Divide"
    )
    churned[row] = dataclasses.replace(
        churned[row], desired_replicas=(churned[row].desired_replicas or 1) + 5
    )
    changed = eng.schedule(churned, clusters)
    assert changed is not first  # fell through to the real gates
    assert sum(r != f for r, f in zip(changed, first)) >= 1
