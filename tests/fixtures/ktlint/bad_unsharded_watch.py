# ktlint fixture: known-BAD for shard-intake-coverage.
# A watch handler that mutates shared state directly — no ShardIntake
# wrap, no predicate=, and no route through the shard-filtered worker:
# under the sharded control plane every replica would process every
# key, double-scheduling objects it does not own.


class LeakyController:
    def __init__(self, host, fleet, resource):
        self.host = host
        self.cache = {}
        host.watch(resource, self._on_event, replay=True)
        fleet.watch_members(resource, self._on_member_event)

    def _on_event(self, event, obj):
        key = obj["metadata"]["name"]
        self.cache[key] = obj  # direct mutation, no shard check anywhere

    def _on_member_event(self, event, obj):
        self.cache.pop(obj["metadata"]["name"], None)
