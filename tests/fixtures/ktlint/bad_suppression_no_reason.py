# ktlint fixture: known-BAD for suppression-format.
# A suppression without a written justification is itself a violation
# (and does NOT silence the underlying rule).
import jax


@jax.jit  # ktlint: ignore[aot-ledger-coverage]
def sneaky(x):
    return x
