# ktlint fixture: known-GOOD twin for shard-intake-coverage.
# Each intake route the rule accepts: a ShardIntake-wrapped handler
# (direct and via local alias), a predicate= watch, a worker-routed
# handler (direct and transitively through a class helper), and a
# functools.partial-bound worker-routed handler.
import functools

from kubeadmiral_tpu.federation.shardmap import ShardIntake


class RoutedController:
    def __init__(self, host, fleet, resource, worker):
        self.host = host
        self.worker = worker
        host.watch(resource, ShardIntake(self._on_event), replay=True)
        intake = ShardIntake(self._on_event, batch=self._on_events)
        host.watch(resource, intake, replay=False)
        fleet.watch_members(
            resource, self._on_member_event, predicate=self._owns_event
        )
        host.watch(resource, self._on_direct_event, replay=False)
        host.watch(resource, self._on_policy_event, replay=False)
        host.watch(
            resource,
            functools.partial(self._on_scoped_event, "leader"),
            replay=True,
        )

    def _owns_event(self, event, obj):
        return True

    def _on_event(self, event, obj):
        self.worker.enqueue(obj["metadata"]["name"])

    def _on_events(self, events):
        self.worker.enqueue_many(e[1]["metadata"]["name"] for e in events)

    def _on_member_event(self, event, obj):
        self.worker.enqueue(obj["metadata"]["name"])

    def _on_direct_event(self, event, obj):
        self.worker.enqueue(obj["metadata"]["name"])

    def _on_policy_event(self, event, obj):
        self._requeue_matches(obj)

    def _requeue_matches(self, obj):
        self.worker.enqueue_all([obj["metadata"]["name"]])

    def _on_scoped_event(self, role, event, obj):
        self.worker.enqueue(f"{role}|" + obj["metadata"]["name"])
