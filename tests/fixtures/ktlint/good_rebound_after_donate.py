# ktlint fixture: known-GOOD twin for donation-discipline.
# Donated buffers are rebound from the dispatch result (the repair-
# chain threading idiom) or simply never read again; a read in the
# OTHER arm of a branch is an alternative, not a continuation.
import jax


def _tick_impl(inp, prev):
    return inp, prev


class GoodDispatch:
    def _build(self):
        donate = (1,) if self.donate else ()
        self._tick = self._aot.wrap(
            "tick", jax.jit(_tick_impl, donate_argnums=donate)
        )

    def run(self, inp, prev):
        out, mask = self._tick(inp, prev)
        return out

    def run_threaded(self, inp, prev):
        # Rebind-from-result: the returned planes REPLACE the dead ones.
        out, prev = self._tick(inp, prev)
        return out, prev

    def run_branched(self, inp, prev, narrow):
        if narrow:
            out, mask = self._tick(inp, prev)
        else:
            out = self._dense(inp, prev)
        return out
