# ktlint fixture: known-BAD for knob-catalog.
# Reads of KT_* knobs that are not in runtime/knob_catalog.py.
import os


def tuning():
    a = os.environ.get("KT_TOTALLY_UNDECLARED_KNOB", "1")
    b = os.environ["KT_ANOTHER_ROGUE_KNOB"]
    return a, b
