# ktlint fixture: known-GOOD twin for sharding-discipline.
# The same sorts under declared contracts (and one nested helper whose
# enclosing function carries the declaration).
import jax.numpy as jnp
from jax import lax

from kubeadmiral_tpu.parallel import shardguard


@shardguard.rows_first
def rank_clusters(scores):
    comp = scores.astype(jnp.int64)
    return lax.sort(comp, dimension=-1)


@shardguard.rows_only
def pack_plane(plane):
    def inner(p):
        return jnp.cumsum(p, axis=-1)

    return inner(plane)


@shardguard.replicated
def global_rank(totals):
    return jnp.argmax(totals, axis=-1)


def host_only(rows):
    import numpy as np

    return np.sort(rows)  # host numpy: exempt, nothing shards it
