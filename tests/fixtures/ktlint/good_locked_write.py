# ktlint fixture: known-GOOD twin for lock-discipline.
# Every mutation path: lexical with-block, the *_locked convention,
# and an @assumes_held method (runtime-verified under KT_LOCKCHECK).
import threading

from kubeadmiral_tpu.runtime import lockcheck


class GoodShared:
    _shared_fields_ = {"_pending": "_lock", "_seq": "_lock"}

    def __init__(self):
        self._lock = lockcheck.make_lock("good-shared")
        self._pending = []
        self._seq = 0

    def enqueue(self, item):
        with self._lock:
            self._pending.append(item)
            self._seq += 1

    def _drain_locked(self):
        drained = list(self._pending)
        self._pending.clear()
        return drained

    @lockcheck.assumes_held("_lock")
    def reset_seq(self):
        self._seq = 0
