# ktlint fixture: known-GOOD twin for knob-catalog.
# Cataloged knobs, through both the direct and the helper idiom; the
# leading-underscore subprocess sentinel is exempt by convention.
import os


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    return float(raw) if raw else default


def tuning():
    depth = int(os.environ.get("KT_PIPELINE_DEPTH", "16"))
    deadline = _env_float("KT_DISPATCH_DEADLINE_S", 30.0)
    internal = os.environ.get("_KT_DRYRUN_SUBPROCESS")
    return depth, deadline, internal
