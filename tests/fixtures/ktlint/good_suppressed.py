# ktlint fixture: known-GOOD for the suppression mechanism.
# A justified suppression (comment-above form) silences exactly the
# named rule on the next line.
import jax


# ktlint: ignore[aot-ledger-coverage] fixture: oracle entry point outside the dispatch surface
@jax.jit
def oracle_entry(x):
    return x
