# ktlint fixture: known-BAD for aot-ledger-coverage.
# A builder that jits and dispatches without AotStore.wrap or
# _obs_wrap — the program escapes warm-boot preload AND the ledger.
import jax
import jax.numpy as jnp


@jax.jit
def decorated_escape(x):
    return x + 1


class BadEngine:
    def _rogue_program(self):
        fn = jax.jit(lambda x: jnp.sum(x))
        self._cache = fn
        return fn
