# ktlint fixture: known-BAD for donation-discipline.
# `prev` is donated into the tick dispatch, then read afterwards — its
# device buffer is dead (aliased into the outputs).
import jax


def _tick_impl(inp, prev):
    return inp, prev


class BadDispatch:
    def _build(self):
        donate = (1,) if self.donate else ()
        self._tick = self._aot.wrap(
            "tick", jax.jit(_tick_impl, donate_argnums=donate)
        )

    def run(self, inp, prev):
        out, mask = self._tick(inp, prev)
        return out, prev[0].sum()
