# ktlint fixture: known-GOOD twin for aot-ledger-coverage.
# The builder idiom: jit -> AotStore.wrap -> _obs_wrap, plus the
# _build_programs / _instrument_programs split (wrap in another method).
import jax
import jax.numpy as jnp


class GoodEngine:
    def _builder_program(self):
        fn = jax.jit(lambda x: jnp.sum(x))
        fn = self._aot.wrap("builder", fn)
        fn = self._obs_wrap("builder", fn)
        self._cache = fn
        return fn

    def _build_programs(self):
        aot = self._aot.wrap
        self._tick = aot("tick", jax.jit(lambda x: x * 2))

    def _instrument_programs(self):
        self._tick = self._obs_wrap("tick", self._tick)
