# ktlint fixture: known-BAD for sharding-discipline.
# A device sort with no declared contract — under GSPMD a sharded
# cluster axis would shard-sum this silently.
import jax.numpy as jnp
from jax import lax


def rank_clusters(scores):
    comp = scores.astype(jnp.int64)
    return lax.sort(comp, dimension=-1)


def running_share(weights):
    return jnp.cumsum(weights, axis=-1)
