# ktlint fixture: known-BAD for lock-discipline.
# Declared-shared fields mutated without the declared lock — the PR-3
# race class (a worker thread persisting state lock-free).
import threading


class BadShared:
    _shared_fields_ = {"_pending": "_lock", "_seq": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._pending = []
        self._seq = 0

    def enqueue(self, item):
        self._pending.append(item)

    def bump(self):
        self._seq += 1
