"""Auto-migration controller: unschedulable counting, capacity
estimation, and the 3-controller feedback loop with the scheduler
(reference: pkg/controllers/automigration + SURVEY.md §3.5)."""

import json

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.automigration import (
    PODS,
    AutoMigrationController,
    count_unschedulable_pods,
)
from kubeadmiral_tpu.federation.schedulerctl import (
    POD_UNSCHEDULABLE_THRESHOLD,
    SchedulerController,
)
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.testing.fakekube import ClusterFleet


def deployment_ftc():
    return next(f for f in default_ftcs() if f.name == "deployments.apps")


def make_pod(name, unschedulable_since=None, deleting=False, labels=None):
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": "default",
            "labels": labels or {"app": "web"},
        },
        "spec": {},
        "status": {"phase": "Pending", "conditions": []},
    }
    if unschedulable_since is not None:
        pod["status"]["conditions"].append(
            {
                "type": "PodScheduled",
                "status": "False",
                "reason": "Unschedulable",
                "lastTransitionTime": unschedulable_since,
            }
        )
    if deleting:
        pod["metadata"]["deletionTimestamp"] = "now"
    return pod


class TestCounting:
    def test_counts_pods_past_threshold(self):
        pods = [
            make_pod("p1", unschedulable_since=0.0),
            make_pod("p2", unschedulable_since=95.0),
            make_pod("p3"),  # schedulable
            make_pod("p4", unschedulable_since=0.0, deleting=True),
        ]
        count, next_cross = count_unschedulable_pods(pods, now=100.0, threshold=30.0)
        assert count == 1  # only p1 crossed (0 + 30 <= 100)
        assert next_cross == 25.0  # p2 crosses at 125


def make_member_deployment(replicas, ready):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": "web",
            "namespace": "default",
            "labels": {C.MANAGED_LABEL: "true"},
        },
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": "web"}},
        },
        "status": {"replicas": replicas, "readyReplicas": ready},
    }


class TestAutoMigrationController:
    def setup_method(self):
        self.fleet = ClusterFleet()
        self.ftc = deployment_ftc()
        self.now = [1000.0]
        self.ctl = AutoMigrationController(
            self.fleet, self.ftc, clock=lambda: self.now[0]
        )
        for name in ("c1", "c2"):
            self.fleet.add_member(name)
            self.fleet.host.create(
                C.FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": {},
                    "status": {
                        "conditions": [
                            {"type": "Joined", "status": "True"},
                            {"type": "Ready", "status": "True"},
                        ]
                    },
                },
            )

    def make_fed(self, threshold="30s"):
        ann = {pending.PENDING_CONTROLLERS: json.dumps([])}
        if threshold:
            ann[POD_UNSCHEDULABLE_THRESHOLD] = threshold
        return {
            "apiVersion": "types.kubeadmiral.io/v1alpha1",
            "kind": "FederatedDeployment",
            "metadata": {"name": "web", "namespace": "default", "annotations": ann},
            "spec": {
                "template": {"apiVersion": "apps/v1", "kind": "Deployment"},
                "placements": [
                    {
                        "controller": C.SCHEDULER,
                        "placement": [{"cluster": "c1"}, {"cluster": "c2"}],
                    }
                ],
            },
        }

    def test_writes_estimated_capacity(self):
        # c1: 3 desired, 2 pods stuck unschedulable past threshold.
        m1 = self.fleet.member("c1")
        m1.create(self.ftc.source.resource, make_member_deployment(3, 1))
        m1.create(PODS, make_pod("p1", unschedulable_since=0.0))
        m1.create(PODS, make_pod("p2", unschedulable_since=0.0))
        m1.create(PODS, make_pod("p3"))
        # c2 healthy.
        m2 = self.fleet.member("c2")
        m2.create(self.ftc.source.resource, make_member_deployment(2, 2))

        self.fleet.host.create(self.ftc.federated.resource, self.make_fed())
        self.ctl.run_until_idle()

        fed = self.fleet.host.get(self.ftc.federated.resource, "default/web")
        info = json.loads(fed["metadata"]["annotations"][C.AUTO_MIGRATION_INFO])
        assert info["estimatedCapacity"] == {"c1": 1}

    def test_disabled_cleans_annotation(self):
        fed = self.make_fed(threshold=None)
        fed["metadata"]["annotations"][C.AUTO_MIGRATION_INFO] = '{"estimatedCapacity":{"c1":0}}'
        self.fleet.host.create(self.ftc.federated.resource, fed)
        self.ctl.run_until_idle()
        fed = self.fleet.host.get(self.ftc.federated.resource, "default/web")
        assert C.AUTO_MIGRATION_INFO not in fed["metadata"]["annotations"]

    def test_healthy_clusters_write_nothing(self):
        m1 = self.fleet.member("c1")
        m1.create(self.ftc.source.resource, make_member_deployment(3, 3))
        self.fleet.host.create(self.ftc.federated.resource, self.make_fed())
        self.ctl.run_until_idle()
        fed = self.fleet.host.get(self.ftc.federated.resource, "default/web")
        assert C.AUTO_MIGRATION_INFO not in fed["metadata"]["annotations"]


class TestFeedbackLoop:
    """Scheduler → auto-migration → scheduler (SURVEY.md §3.5)."""

    def test_capacity_feedback_moves_replicas(self):
        fleet = ClusterFleet()
        ftc = deployment_ftc()
        import dataclasses

        ftc = dataclasses.replace(
            ftc, controllers=(("kubeadmiral.io/global-scheduler",),)
        )
        now = [1000.0]
        scheduler = SchedulerController(fleet.host, ftc)
        automigration = AutoMigrationController(fleet, ftc, clock=lambda: now[0])

        for name in ("c1", "c2"):
            fleet.add_member(name)
            fleet.host.create(
                C.FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": {},
                    "status": {
                        "conditions": [
                            {"type": "Joined", "status": "True"},
                            {"type": "Ready", "status": "True"},
                        ],
                        "resources": {
                            "allocatable": {"cpu": "64", "memory": "256Gi"},
                            "available": {"cpu": "32", "memory": "128Gi"},
                        },
                        "apiResourceTypes": ["apps/v1/Deployment"],
                    },
                },
            )
        fleet.host.create(
            PROPAGATION_POLICIES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "PropagationPolicy",
                "metadata": {"name": "pp", "namespace": "default"},
                "spec": {
                    "schedulingMode": "Divide",
                    "autoMigration": {"when": {"podUnschedulableFor": "30s"}},
                },
            },
        )
        fleet.host.create(
            ftc.federated.resource,
            {
                "apiVersion": "types.kubeadmiral.io/v1alpha1",
                "kind": "FederatedDeployment",
                "metadata": {
                    "name": "web",
                    "namespace": "default",
                    "labels": {"kubeadmiral.io/propagation-policy-name": "pp"},
                    "annotations": {
                        pending.PENDING_CONTROLLERS: json.dumps(
                            [["kubeadmiral.io/global-scheduler"]]
                        )
                    },
                },
                "spec": {
                    "template": {
                        "apiVersion": "apps/v1",
                        "kind": "Deployment",
                        "metadata": {"name": "web", "namespace": "default"},
                        "spec": {
                            "replicas": 6,
                            "selector": {"matchLabels": {"app": "web"}},
                        },
                    }
                },
            },
        )

        def settle():
            for _ in range(10):
                if not (scheduler.worker.step() | automigration.worker.step()):
                    break

        settle()
        fed = fleet.host.get(ftc.federated.resource, "default/web")
        first = {
            cl: patches[0]["value"]
            for cl, patches in C.get_overrides(fed, C.SCHEDULER).items()
        }
        assert sum(first.values()) == 6
        assert fed["metadata"]["annotations"][POD_UNSCHEDULABLE_THRESHOLD] == "30s"
        c1_share = first.get("c1", 0)
        assert c1_share > 0

        # c1 develops stuck pods: only 1 of its replicas fits.
        m1 = fleet.member("c1")
        m1.create(
            ftc.source.resource, make_member_deployment(c1_share, 1)
        )
        for i in range(c1_share - 1):
            m1.create(PODS, make_pod(f"p{i}", unschedulable_since=0.0))
        m1.create(PODS, make_pod("ok", labels={"app": "web"}))

        settle()
        fed = fleet.host.get(ftc.federated.resource, "default/web")
        info = json.loads(fed["metadata"]["annotations"][C.AUTO_MIGRATION_INFO])
        assert info["estimatedCapacity"]["c1"] == 1

        second = {
            cl: patches[0]["value"]
            for cl, patches in C.get_overrides(fed, C.SCHEDULER).items()
        }
        assert sum(second.values()) == 6
        assert second["c1"] == 1  # capped at estimated capacity
        assert second["c2"] == 5  # overflow moved
