"""Profiling endpoints (the pprof analogue; reference:
cmd/controller-manager/app/controllermanager.go:61-71)."""

import json
import urllib.request

from kubeadmiral_tpu.runtime.healthcheck import HealthCheckRegistry, HealthServer
from kubeadmiral_tpu.runtime.profiling import (
    ProfilingServer,
    collect_profile,
    collect_stacks,
)


def fetch(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, json.loads(r.read())


class TestProfiling:
    def test_collect_profile_samples_other_threads(self):
        """The sampler must see WORKER threads (a tracing profiler only
        sees its own thread — the bug this replaced)."""
        import threading

        stop = threading.Event()

        def busy_loop_for_profile():
            x = 0
            while not stop.is_set():
                x += 1

        t = threading.Thread(target=busy_loop_for_profile, daemon=True)
        t.start()
        try:
            result = collect_profile(seconds=0.3)
        finally:
            stop.set()
            t.join()
        assert result["seconds"] == 0.3
        assert result["samples"] > 0
        assert any(
            "busy_loop_for_profile" in row["function"] for row in result["top"]
        ), result["top"][:5]

    def test_collect_stacks_includes_this_thread(self):
        stacks = collect_stacks()["threads"]
        assert any("collect_stacks" in "".join(s) for s in stacks.values())

    def test_standalone_server(self):
        server = ProfilingServer()
        port = server.start()
        try:
            status, threads = fetch(port, "/debug/threads")
            assert status == 200
            assert any(t["name"] == "MainThread" for t in threads["threads"])
            status, stacks = fetch(port, "/debug/stacks")
            assert status == 200 and stacks["threads"]
            status, prof = fetch(port, "/debug/profile?seconds=0.2")
            assert status == 200 and prof["seconds"] == 0.2
        finally:
            server.stop()

    def test_health_server_mounts_debug(self):
        registry = HealthCheckRegistry()
        server = HealthServer(registry)
        port = server.start()
        try:
            status, threads = fetch(port, "/debug/threads")
            assert status == 200 and threads["threads"]
            status, live = fetch(port, "/livez")
            assert status == 200
        finally:
            server.stop()
