"""Differential tests: batched XLA planner vs the sequential oracle.

Random scheduling problems are generated host-side, run through both the
oracle (planner_oracle.plan) and the device kernel (ops.planner.plan_batch),
and compared elementwise — plan and overflow must match exactly, including
tie-breaks, capacity overflow accounting and avoid-disruption rescaling.
"""

import numpy as np
import pytest

from kubeadmiral_tpu.ops import planner as dev
from kubeadmiral_tpu.ops.planner_oracle import ClusterPref, PlanInput, plan as oracle_plan
from kubeadmiral_tpu.utils.hashing import fnv32_batch, uint32_to_sortable_int32

INF = int(dev.INT32_INF)


def build_case(rng: np.random.Generator, n_clusters: int, key: str):
    names = [f"cluster-{i}" for i in range(n_clusters)]
    member = rng.random(n_clusters) < 0.85
    if not member.any():
        member[0] = True
    weight = rng.integers(0, 6, n_clusters)
    min_r = np.where(rng.random(n_clusters) < 0.3, rng.integers(0, 4, n_clusters), 0)
    has_max = rng.random(n_clusters) < 0.3
    max_r = np.where(has_max, rng.integers(0, 12, n_clusters), INF)
    has_cap = rng.random(n_clusters) < 0.3
    cap = np.where(has_cap, rng.integers(0, 10, n_clusters), INF)
    total = int(rng.integers(0, 40))
    current = np.where(
        rng.random(n_clusters) < 0.5, rng.integers(0, 15, n_clusters), 0
    )
    avoid = bool(rng.random() < 0.5)
    keep = bool(rng.random() < 0.5)

    prefs = {
        names[j]: ClusterPref(
            weight=int(weight[j]),
            min_replicas=int(min_r[j]),
            max_replicas=None if max_r[j] == INF else int(max_r[j]),
        )
        for j in range(n_clusters)
        if member[j]
    }
    oracle_inp = PlanInput(
        prefs=prefs,
        total=total,
        clusters=[names[j] for j in range(n_clusters) if member[j]],
        current={names[j]: int(current[j]) for j in range(n_clusters)},
        capacity={names[j]: int(cap[j]) for j in range(n_clusters) if cap[j] != INF},
        key=key,
        avoid_disruption=avoid,
        keep_unschedulable=keep,
    )

    tiebreak = uint32_to_sortable_int32(fnv32_batch(names, key))
    dev_inp = dict(
        weight=weight,
        min_replicas=min_r,
        max_replicas=max_r,
        scale_max=max_r.copy(),
        capacity=cap,
        tiebreak=tiebreak,
        member=member,
        total=total,
        current=current,
        avoid_disruption=avoid,
        keep_unschedulable=keep,
    )
    return names, member, oracle_inp, dev_inp


def to_batch(cases, n_clusters):
    fields = {}
    b = len(cases)
    for f in dev.PlannerInputs._fields:
        vals = [c[f] for c in cases]
        if f in ("total", "avoid_disruption", "keep_unschedulable"):
            fields[f] = np.asarray(vals)
        else:
            fields[f] = np.stack(vals)
    fields["total"] = fields["total"].astype(np.int32)
    for f in ("weight", "min_replicas", "max_replicas", "scale_max", "capacity", "current"):
        fields[f] = fields[f].astype(np.int32)
    fields["tiebreak"] = fields["tiebreak"].astype(np.int32)
    return dev.PlannerInputs(**fields)


@pytest.mark.parametrize("n_clusters", [1, 2, 5, 8, 17])
def test_device_matches_oracle_random(n_clusters):
    rng = np.random.default_rng(1234 + n_clusters)
    cases = []
    oracles = []
    names_list = []
    for i in range(60):
        key = f"ns-{i}/obj-{i}"
        names, member, oracle_inp, dev_inp = build_case(rng, n_clusters, key)
        cases.append(dev_inp)
        oracles.append(oracle_inp)
        names_list.append((names, member))

    out = dev.plan_batch(to_batch(cases, n_clusters))
    plan_arr = np.asarray(out.plan)
    ovf_arr = np.asarray(out.overflow)

    for i, (oracle_inp, (names, member)) in enumerate(zip(oracles, names_list)):
        want_plan, want_ovf = oracle_plan(oracle_inp)
        for j, name in enumerate(names):
            wp = want_plan.get(name, 0)
            wo = want_ovf.get(name, 0)
            assert plan_arr[i, j] == wp, (
                f"case {i} cluster {name}: plan {plan_arr[i, j]} != {wp}\n"
                f"oracle={oracle_inp}\nplan={want_plan} ovf={want_ovf}\n"
                f"dev_plan={plan_arr[i]} dev_ovf={ovf_arr[i]}"
            )
            assert ovf_arr[i, j] == wo, (
                f"case {i} cluster {name}: overflow {ovf_arr[i, j]} != {wo}\n"
                f"oracle={oracle_inp}"
            )


def test_wildcard_scale_max_is_unbounded():
    # A max provided via the "*" preference applies to the desired plan but
    # not to the avoid-disruption scale-up (reference resolves scale-up max
    # from the directly-named preference only, planner.go:320-324).
    names = ["a", "b"]
    key = "ns/wild"
    prefs = {"*": ClusterPref(weight=1, max_replicas=6)}
    oracle_inp = PlanInput(
        prefs=prefs,
        total=10,
        clusters=names,
        current={"a": 0, "b": 0},
        capacity={},
        key=key,
        avoid_disruption=True,
        keep_unschedulable=False,
    )
    want_plan, _ = oracle_plan(oracle_inp)

    tiebreak = uint32_to_sortable_int32(fnv32_batch(names, key))
    inp = dev.make_inputs(
        1,
        2,
        10,
        weight=np.array([1, 1]),
        max_replicas=np.array([6, 6]),
        scale_max=np.array([INF, INF]),
        tiebreak=tiebreak,
        avoid_disruption=True,
    )
    out = dev.plan_batch(inp)
    for j, name in enumerate(names):
        assert int(out.plan[0, j]) == want_plan.get(name, 0)


def test_large_batch_shapes_compile():
    rng = np.random.default_rng(7)
    b, c = 64, 32
    inp = dev.make_inputs(
        b,
        c,
        rng.integers(0, 100, b),
        weight=rng.integers(0, 10, (b, c)),
        tiebreak=rng.integers(-(2**31), 2**31 - 1, (b, c)),
    )
    out = dev.plan_batch(inp)
    totals = np.asarray(out.plan).sum(axis=1)
    assert (totals == np.asarray(inp.total)).all()


def test_plan_batch_validates_contract():
    inp = dev.make_inputs(1, 2, 10**6, weight=np.array([3000, 3000]))
    with pytest.raises(OverflowError):
        dev.plan_batch(inp)
