"""SchedulingProfile: plugin-set resolution + scheduler wiring
(reference: pkg/controllers/scheduler/profile.go,
pkg/apis/core/v1alpha1/types_schedulingprofile.go; behavioral model
test/e2e/schedulingprofile)."""

import dataclasses

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.clusterctl import (
    FEDERATED_CLUSTERS,
    FederatedClusterController,
    NODES,
)
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.models import profile as PR
from kubeadmiral_tpu.models import types as T
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
from kubeadmiral_tpu.testing.fakekube import ClusterFleet

from test_e2e_slice import make_deployment, make_node, settle


class TestPluginSetResolution:
    def test_defaults_when_no_profile(self):
        filters, scores, selects = PR.resolve_plugins(None)
        assert filters == T.DEFAULT_FILTERS
        assert scores == T.DEFAULT_SCORES
        assert selects == (T.MAX_CLUSTER,)

    def test_disabled_removes_default(self):
        out = PR.reconcile_ext_point(
            T.DEFAULT_FILTERS,
            PR.PluginSet(disabled=(T.TAINT_TOLERATION,)),
        )
        assert T.TAINT_TOLERATION not in out
        assert T.APIRESOURCES in out

    def test_star_disables_all_defaults(self):
        out = PR.reconcile_ext_point(
            T.DEFAULT_FILTERS,
            PR.PluginSet(disabled=("*",), enabled=(T.PLACEMENT_FILTER,)),
        )
        assert out == (T.PLACEMENT_FILTER,)

    def test_enabled_appends_after_defaults(self):
        out = PR.reconcile_ext_point(
            T.DEFAULT_SCORES, PR.PluginSet(enabled=(T.CLUSTER_RESOURCES_MOST,))
        )
        assert out == T.DEFAULT_SCORES + (T.CLUSTER_RESOURCES_MOST,)

    def test_parse_profile(self):
        spec = PR.parse_profile(
            {
                "metadata": {"name": "p", "generation": 3},
                "spec": {
                    "plugins": {
                        "filter": {"disabled": [{"name": "*"}]},
                        "score": {
                            "enabled": [
                                {"name": T.CLUSTER_RESOURCES_MOST},
                            ],
                            "disabled": [
                                {"name": T.CLUSTER_RESOURCES_LEAST},
                            ],
                        },
                    }
                },
            }
        )
        assert spec.generation == 3
        filters, scores, _ = PR.resolve_plugins(spec)
        assert filters == ()
        assert T.CLUSTER_RESOURCES_MOST in scores
        assert T.CLUSTER_RESOURCES_LEAST not in scores


class TestSchedulerProfileWiring:
    def setup_method(self):
        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        self.ftc = dataclasses.replace(
            ftc, controllers=(("kubeadmiral.io/global-scheduler",),)
        )
        self.fleet = ClusterFleet()
        gvk = "apps/v1/Deployment"
        self.clusterctl = FederatedClusterController(
            self.fleet, api_resource_probe=[gvk]
        )
        self.federate = FederateController(self.fleet.host, self.ftc)
        self.scheduler = SchedulerController(self.fleet.host, self.ftc)

        for name in ("c1", "c2", "c3"):
            member = self.fleet.add_member(name)
            member.create(NODES, make_node("n1", "64", "128Gi"))
            cluster = {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "FederatedCluster",
                "metadata": {"name": name},
                "spec": {},
            }
            if name == "c1":
                cluster["spec"]["taints"] = [
                    {"key": "dedicated", "value": "infra", "effect": "NoSchedule"}
                ]
            self.fleet.host.create(FEDERATED_CLUSTERS, cluster)

    def controllers(self):
        return (self.clusterctl, self.federate, self.scheduler)

    def create_policy(self, **spec):
        self.fleet.host.create(
            PROPAGATION_POLICIES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "PropagationPolicy",
                "metadata": {"name": "pp", "namespace": "default"},
                "spec": spec,
            },
        )

    def placement(self):
        fed = self.fleet.host.get(self.ftc.federated.resource, "default/web")
        return C.get_placement(fed, C.SCHEDULER)

    def test_default_profile_respects_taints(self):
        self.create_policy(schedulingMode="Duplicate")
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        settle(*self.controllers())
        assert self.placement() == {"c2", "c3"}

    def test_profile_disabling_taint_filter_admits_tainted_cluster(self):
        self.fleet.host.create(
            PR.SCHEDULING_PROFILES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "SchedulingProfile",
                "metadata": {"name": "no-taints"},
                "spec": {
                    "plugins": {
                        "filter": {"disabled": [{"name": T.TAINT_TOLERATION}]},
                        "score": {"disabled": [{"name": T.TAINT_TOLERATION}]},
                    }
                },
            },
        )
        self.create_policy(schedulingMode="Duplicate", schedulingProfile="no-taints")
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        settle(*self.controllers())
        assert self.placement() == {"c1", "c2", "c3"}

    def test_profile_update_triggers_reschedule(self):
        # Starts with defaults (profile object absent): tainted c1 excluded.
        self.create_policy(schedulingMode="Duplicate", schedulingProfile="later")
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        settle(*self.controllers())
        assert self.placement() == {"c2", "c3"}

        # Profile appears and disables the taint filter: the profile event
        # plus the hashed profile generation force a reschedule.
        self.fleet.host.create(
            PR.SCHEDULING_PROFILES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "SchedulingProfile",
                "metadata": {"name": "later"},
                "spec": {
                    "plugins": {
                        "filter": {"disabled": [{"name": T.TAINT_TOLERATION}]},
                        "score": {"disabled": [{"name": T.TAINT_TOLERATION}]},
                    }
                },
            },
        )
        settle(*self.controllers())
        assert self.placement() == {"c1", "c2", "c3"}

    def test_profile_disabling_maxcluster_lifts_topk_cap(self):
        self.fleet.host.create(
            PR.SCHEDULING_PROFILES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "SchedulingProfile",
                "metadata": {"name": "no-topk"},
                "spec": {
                    "plugins": {"select": {"disabled": [{"name": T.MAX_CLUSTER}]}}
                },
            },
        )
        self.create_policy(
            schedulingMode="Duplicate",
            maxClusters=1,
            schedulingProfile="no-topk",
            tolerations=[{"key": "dedicated", "operator": "Exists"}],
        )
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        settle(*self.controllers())
        assert self.placement() == {"c1", "c2", "c3"}
