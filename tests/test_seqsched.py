"""Differential test: native C++ sequential scheduler vs the Python oracle.

The C++ baseline (native/seqsched.cpp) must agree bit-for-bit with
pipeline_oracle.schedule_one on the full feature space — it is the
number bench.py divides by, so any semantic drift would silently distort
vs_baseline.
"""

import numpy as np
import pytest

from kubeadmiral_tpu.native.seqsched import seq_schedule_batch
from kubeadmiral_tpu.ops.pipeline_oracle import schedule_one

from test_pipeline import R, random_problem, to_tick_inputs


@pytest.mark.parametrize("c", [3, 8, 19])
def test_native_matches_oracle(c):
    rng = np.random.default_rng(7_000 + c)
    names = [f"member-{j}" for j in range(c)]
    shared_alloc = [[int(x) for x in rng.integers(5, 50, R)] for _ in range(c)]
    shared_used = [[int(x) for x in rng.integers(0, 40, R)] for _ in range(c)]
    shared_cpu_a = [int(x) for x in rng.integers(0, 30, c)]
    shared_cpu_v = [int(x) for x in rng.integers(-3, 25, c)]
    problems = []
    for i in range(120):
        p = random_problem(rng, c, f"ns-{i}/workload-{i}", names)
        p.alloc, p.used = shared_alloc, shared_used
        p.cpu_alloc, p.cpu_avail = shared_cpu_a, shared_cpu_v
        problems.append(p)

    out = seq_schedule_batch(to_tick_inputs(problems, c))
    assert out is not None, "native library unavailable"
    selected, replicas, counted = out

    for i, p in enumerate(problems):
        want = schedule_one(p)
        got_idx = set(np.nonzero(selected[i])[0].tolist())
        assert got_idx == set(want.keys()), (
            f"case {i}: native selected {sorted(got_idx)} != "
            f"oracle {sorted(want)}\n{p}"
        )
        for j in got_idx:
            w = want[j]
            g = int(replicas[i, j])
            if w is None:
                assert g == -1, f"case {i} cluster {j}: {g} != nil\n{p}"
                assert not counted[i, j]
            else:
                assert g == w, f"case {i} cluster {j}: {g} != {w}\n{p}\n{want}"
