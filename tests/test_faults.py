"""Fault-matrix suite: member outages must not stall the tick loop.

Run as ``make chaos`` (whole matrix) or inside tier-1 (`-m 'not slow'`
keeps the fast subset).  Covers the fault-injection seam
(transport/faults.py), the per-member circuit breakers
(transport/breaker.py), the stall-proof dispatch fan-out
(federation/dispatch.py), watch-stream recovery (410 relist, reconnect
backoff), and the end-to-end acceptance scenario: one hard-down member
of 8 under the kwok-lite farm, breaker opens after one deadline, ticks
stay fast, ClusterNotReady statuses, and bit-identical convergence on
recovery.
"""

import threading
import time

import pytest

from test_e2e_slice import make_deployment, make_node

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation import dispatch as D
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.testing.fakekube import FakeKube
from kubeadmiral_tpu.transport import breaker as B
from kubeadmiral_tpu.transport.client import TransportError, watch_backoff
from kubeadmiral_tpu.transport.faults import (
    FaultInjector,
    FaultPolicy,
    FaultyKube,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- fault policies -------------------------------------------------------
class TestFaultPolicy:
    def test_schedule_start_and_expiry(self):
        clock = FakeClock()
        inj = FaultInjector(clock=clock)
        inj.set_fault("m", FaultPolicy(partition=True, start_s=1.0, duration_s=2.0))
        assert inj.action("m") is None  # not engaged yet
        clock.advance(1.5)
        act = inj.action("m")
        assert act is not None and act.partition
        clock.advance(2.0)  # past start + duration
        assert inj.action("m") is None
        assert inj.policy("m") is None  # expired policies self-clean

    def test_flap_phases(self):
        clock = FakeClock()
        inj = FaultInjector(clock=clock)
        inj.set_fault(
            "m", FaultPolicy(partition=True, flap_period_s=1.0, flap_duty=0.5)
        )
        clock.advance(0.25)  # phase 0.25 < duty 0.5: partitioned
        assert inj.partitioned("m")
        clock.advance(0.5)  # phase 0.75: healthy half of the period
        assert not inj.partitioned("m")
        clock.advance(0.5)  # next period's partitioned half
        assert inj.partitioned("m")

    def test_error_rate_and_latency(self):
        clock = FakeClock()
        inj = FaultInjector(clock=clock, seed=7)
        inj.set_fault("m", FaultPolicy(error_rate=1.0, latency_s=0.25))
        act = inj.action("m")
        assert act.error and act.latency_s == pytest.approx(0.25)
        inj.set_fault("m", FaultPolicy(error_rate=0.0))
        assert not inj.action("m").error


class TestFaultyKube:
    def test_partition_blocks_briefly_then_raises(self):
        inj = FaultInjector()
        kube = FaultyKube(FakeKube("m"), "m", inj, timeout=0.1)
        inj.set_fault("m", FaultPolicy(partition=True))
        t0 = time.monotonic()
        with pytest.raises(TransportError):
            kube.keys("v1/pods")
        elapsed = time.monotonic() - t0
        assert 0.05 <= elapsed < 1.0  # bounded by the proxy timeout
        assert kube.healthy is False
        inj.clear("m")
        assert kube.keys("v1/pods") == []
        assert kube.healthy is True

    def test_watch_stall_buffers_then_catches_up(self):
        inj = FaultInjector()
        inner = FakeKube("m")
        kube = FaultyKube(inner, "m", inj, timeout=0.1)
        seen = []
        kube.watch("v1/pods", lambda ev, obj: seen.append(obj["metadata"]["name"]))
        inj.set_fault("m", FaultPolicy(watch_stall=True))
        inner.create("v1/pods", {"metadata": {"name": "p1"}})
        inner.create("v1/pods", {"metadata": {"name": "p2"}})
        assert seen == []  # stalled: buffered, not lost
        inj.clear("m")
        kube.drain_stalled()
        assert seen == ["p1", "p2"]  # order preserved
        inner.create("v1/pods", {"metadata": {"name": "p3"}})
        assert seen == ["p1", "p2", "p3"]


# -- circuit breakers -----------------------------------------------------
class TestBreaker:
    def _registry(self, clock, **cfg):
        defaults = dict(
            failure_threshold=3, open_seconds=5.0,
            latency_threshold_s=0, stall_threshold_s=1.0,
        )
        defaults.update(cfg)
        return B.BreakerRegistry(
            metrics=Metrics(), config=B.BreakerConfig(**defaults), clock=clock
        )

    def test_consecutive_failures_open_then_probe_closes(self):
        clock = FakeClock()
        reg = self._registry(clock)
        b = reg.for_member("m")
        for _ in range(2):
            b.record_failure()
        assert b.state == B.CLOSED  # below threshold
        b.record_failure()
        assert b.state == B.OPEN
        assert not b.allow()
        clock.advance(5.1)  # cool-down elapsed: half-open, ONE probe slot
        assert b.allow()
        assert b.state == B.HALF_OPEN
        assert not b.allow()  # second concurrent probe is refused
        b.record_success(0.01)
        assert b.state == B.CLOSED
        assert b.allow()

    def test_stall_opens_immediately(self):
        reg = self._registry(FakeClock())
        b = reg.for_member("m")
        b.record_failure(timeout=True)  # ONE parked deadline is enough
        assert b.state == B.OPEN
        b2 = reg.for_member("m2")
        b2.record_failure(latency_s=2.0)  # slower than stall_threshold_s
        assert b2.state == B.OPEN

    def test_half_open_probe_failure_reopens(self):
        clock = FakeClock()
        reg = self._registry(clock)
        b = reg.for_member("m")
        b.record_failure(timeout=True)
        clock.advance(5.1)
        assert b.allow()
        b.record_failure()
        assert b.state == B.OPEN
        assert not b.allow()  # a fresh cool-down window started

    def test_latency_ewma_opens(self):
        reg = self._registry(FakeClock(), latency_threshold_s=0.5, ewma_alpha=1.0)
        b = reg.for_member("m")
        b.record_success(2.0)  # answers, but slower than the tick can afford
        assert b.state == B.OPEN

    def test_probe_success_respects_cooldown(self):
        clock = FakeClock()
        reg = self._registry(clock)
        b = reg.for_member("m")
        b.record_failure(timeout=True)
        b.record_success(0.01, probe=True)  # heartbeat inside the window
        assert b.state == B.OPEN  # must not defeat load shedding early
        clock.advance(5.1)
        b.record_success(0.01, probe=True)
        assert b.state == B.CLOSED

    def test_registry_transitions_metrics_and_report(self):
        clock = FakeClock()
        metrics = Metrics()
        reg = B.BreakerRegistry(
            metrics=metrics,
            config=B.BreakerConfig(failure_threshold=1, open_seconds=1.0,
                                   latency_threshold_s=0),
            clock=clock,
        )
        transitions = []
        reg.on_transition(lambda name, old, new: transitions.append((name, old, new)))
        reg.for_member("reg-m").record_failure()
        assert transitions == [("reg-m", B.CLOSED, B.OPEN)]
        assert metrics.stores.get("member_breaker_state{cluster=reg-m}") == 2
        reg.count_shed("reg-m", 3)
        reg.count_retry("reg-m", 2)
        snap = reg.snapshot()["reg-m"]
        assert snap["state"] == B.OPEN
        assert snap["shed_writes"] == 3 and snap["dispatch_retries"] == 2
        report = B.members_report()
        assert "reg-m" in report["members"] and "reg-m" in report["open"]
        assert reg.open_members() == ["reg-m"]

    def test_debug_members_route(self):
        import json
        from urllib.request import urlopen

        from kubeadmiral_tpu.runtime.healthcheck import (
            HealthCheckRegistry,
            HealthServer,
        )

        reg = B.BreakerRegistry(metrics=Metrics())
        reg.for_member("route-m").record_failure(timeout=True)
        server = HealthServer(HealthCheckRegistry(), metrics=Metrics())
        port = server.start()
        try:
            body = urlopen(f"http://127.0.0.1:{port}/debug/members").read()
            payload = json.loads(body)
            assert payload["members"]["route-m"]["state"] == B.OPEN
            assert "route-m" in payload["open"]
        finally:
            server.stop()


# -- dispatch retry budget ------------------------------------------------
class _FlakyKube:
    """Raises on the first N batch calls, then delegates to a FakeKube."""

    def __init__(self, failures: int):
        self.inner = FakeKube("flaky")
        self.failures = failures
        self.calls = 0

    def batch(self, ops):
        self.calls += 1
        if self.calls <= self.failures:
            raise TransportError("flaky: connection reset")
        return self.inner.batch(ops)

    def get(self, resource, key):
        return self.inner.get(resource, key)


class TestDispatchRetry:
    def test_retry_delay_jittered_and_capped(self, monkeypatch):
        monkeypatch.setenv("KT_RETRY_BASE_S", "0.1")
        monkeypatch.setenv("KT_RETRY_CAP_S", "1.0")
        import random

        rng = random.Random(42)
        for attempt in range(8):
            span = min(1.0, 0.1 * 2**attempt)
            for _ in range(20):
                d = D.retry_delay(attempt, rng=rng)
                assert span * 0.5 <= d <= span  # jittered within the band
        assert D.retry_delay(30, rng=rng) <= 1.0  # capped

    def test_transport_failures_retried_within_budget(self, monkeypatch):
        monkeypatch.setenv("KT_RETRY_BASE_S", "0.01")
        monkeypatch.setenv("KT_RETRY_CAP_S", "0.02")
        client = _FlakyKube(failures=2)
        reg = B.BreakerRegistry(metrics=Metrics())
        results = D.run_batch_with_retries(
            client,
            [{"verb": "create", "resource": "v1/pods",
              "object": {"metadata": {"name": "p"}}}],
            deadline=time.monotonic() + 5.0,
            cluster="m",
            breakers=reg,
        )
        assert results[0]["code"] == 201
        assert client.calls == 3
        assert reg.snapshot()["m"]["dispatch_retries"] == 2
        assert reg.for_member("m").state == B.CLOSED  # it recovered in-budget

    def test_budget_exhaustion_returns_transport_result(self, monkeypatch):
        monkeypatch.setenv("KT_RETRY_BASE_S", "0.01")
        monkeypatch.setenv("KT_RETRY_MAX", "1")
        client = _FlakyKube(failures=99)
        reg = B.BreakerRegistry(
            metrics=Metrics(),
            config=B.BreakerConfig(failure_threshold=1, latency_threshold_s=0),
        )
        results = D.run_batch_with_retries(
            client,
            [{"verb": "create", "resource": "v1/pods",
              "object": {"metadata": {"name": "p"}}}],
            deadline=time.monotonic() + 5.0,
            cluster="m",
            breakers=reg,
        )
        assert results[0]["code"] == 500
        assert reg.for_member("m").state == B.OPEN

    def test_conflict_refresh_retries_update(self):
        member = FakeKube("m")
        created = member.create(
            "v1/pods", {"metadata": {"name": "p"}, "spec": {"v": 1}}
        )
        # Bump the stored object so the staged update's rv goes stale.
        bump = {"metadata": {"name": "p",
                             "resourceVersion": created["metadata"]["resourceVersion"]},
                "spec": {"v": 2}}
        member.update("v1/pods", bump)
        stale = {"metadata": {"name": "p",
                              "resourceVersion": created["metadata"]["resourceVersion"]},
                 "spec": {"v": 3}}
        results = D.run_batch_with_retries(
            member,
            [{"verb": "update", "resource": "v1/pods", "object": stale}],
            deadline=time.monotonic() + 5.0,
        )
        assert results[0]["code"] == 200  # 409 → refresh rv → retried
        assert member.get("v1/pods", "p")["spec"]["v"] == 3


# -- deadline enforcement on every flush path -----------------------------
class TestDeadlines:
    def _staged_sink(self, sink, cluster="m", n=2):
        outcomes = []
        for i in range(n):
            sink.submit(
                cluster,
                {"verb": "create", "resource": "v1/pods",
                 "object": {"metadata": {"name": f"p{i}"}}},
                outcomes.append,
            )
        return outcomes

    def test_serial_flush_enforces_deadline(self):
        """The satellite-1 bug: the no-pool serial path used to ignore
        its timeout argument entirely — a hung member parked the
        flushing thread forever."""
        inj = FaultInjector()
        proxied = FaultyKube(FakeKube("m"), "m", inj, timeout=0.4)
        inj.set_fault("m", FaultPolicy(partition=True))
        reg = B.BreakerRegistry(metrics=Metrics())
        sink = D.BatchSink(lambda c: proxied, breakers=reg)
        outcomes = self._staged_sink(sink)
        t0 = time.monotonic()
        sink.flush(timeout=0.15)
        elapsed = time.monotonic() - t0
        assert elapsed < 0.35  # returned at the deadline, not the client timeout
        assert outcomes == []  # continuations never ran: *_TIMED_OUT stands
        assert reg.for_member("m").state == B.OPEN  # stall opened the breaker
        assert reg.snapshot()["m"]["shed_writes"] == 2
        # The helper thread dies on the client's own timeout, not ours.
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and any(
            t.name.startswith("dispatch-flush-") for t in threading.enumerate()
        ):
            time.sleep(0.05)
        assert not any(
            t.name.startswith("dispatch-flush-") for t in threading.enumerate()
        )

    def test_serial_flush_stays_inline_for_plain_fakekube(self):
        """The local hot path must not pay a thread spawn per member."""
        member = FakeKube("m")
        sink = D.BatchSink(lambda c: member)
        seen_threads = []
        sink.submit(
            "m",
            {"verb": "create", "resource": "v1/pods",
             "object": {"metadata": {"name": "p"}}},
            lambda res: seen_threads.append(threading.current_thread().name),
        )
        sink.flush(timeout=5.0)
        assert seen_threads == [threading.current_thread().name]

    def test_pooled_single_cluster_flush_honors_timeout(self):
        """Regression: with a pool present but only ONE staged cluster,
        the old code fell into the serial branch and dropped the
        timeout."""
        from concurrent.futures import ThreadPoolExecutor

        inj = FaultInjector()
        proxied = FaultyKube(FakeKube("m"), "m", inj, timeout=1.0)
        inj.set_fault("m", FaultPolicy(partition=True))
        pool = ThreadPoolExecutor(max_workers=2)
        try:
            sink = D.BatchSink(lambda c: proxied, pool=pool,
                               breakers=B.BreakerRegistry(metrics=Metrics()))
            self._staged_sink(sink, n=1)
            t0 = time.monotonic()
            sink.flush(timeout=0.15)
            assert time.monotonic() - t0 < 0.6
        finally:
            pool.shutdown(wait=True)

    def test_immediate_sink_wait_cancels_and_finalizes(self):
        inj = FaultInjector()
        proxied = FaultyKube(FakeKube("m"), "m", inj, timeout=0.6)
        inj.set_fault("m", FaultPolicy(partition=True))
        from concurrent.futures import ThreadPoolExecutor

        pool = ThreadPoolExecutor(max_workers=1)  # second op queues behind
        try:
            sink = D.ImmediateSink(lambda c: proxied, pool=pool)
            outcomes = []
            for i in range(2):
                sink.submit(
                    "m",
                    {"verb": "create", "resource": "v1/pods",
                     "object": {"metadata": {"name": f"p{i}"}}},
                    outcomes.append,
                )
            t0 = time.monotonic()
            sink.wait(timeout=0.1)
            assert time.monotonic() - t0 < 0.5
            # The queued future was cancelled: at most the in-flight op's
            # continuation can still land, the other never will.
            with pytest.raises(RuntimeError):
                sink.submit("m", {"verb": "get", "resource": "v1/pods",
                                  "key": "p0"}, outcomes.append)
        finally:
            pool.shutdown(wait=True, cancel_futures=True)

    def test_immediate_sink_pool_size_knob(self, monkeypatch):
        monkeypatch.setenv("KT_DISPATCH_POOL", "3")
        # Pin the per-op path: under KT_WRITE_COALESCE submits buffer
        # and the pool is created lazily at wait() (torn down before it
        # returns), so the sizing knob is only observable here.
        monkeypatch.setenv("KT_WRITE_COALESCE", "0")
        assert D.dispatch_pool_size() == 3
        sink = D.ImmediateSink(lambda c: FakeKube("m"))
        outcomes = []
        sink.submit("m", {"verb": "create", "resource": "v1/pods",
                          "object": {"metadata": {"name": "p"}}},
                    outcomes.append)
        assert sink._pool._max_workers == 3
        sink.wait(timeout=2.0)
        assert outcomes and outcomes[0]["code"] == 201


# -- watch-stream recovery ------------------------------------------------
class TestWatchRecovery:
    def test_backoff_schedule_capped_and_jittered(self):
        import random

        rng = random.Random(1)
        delays = [watch_backoff(a, base=0.1, cap=5.0, rng=rng) for a in range(12)]
        for a, d in enumerate(delays):
            span = min(5.0, 0.1 * 2**a)
            assert span * 0.5 <= d <= span
        assert max(delays) <= 5.0  # capped
        assert delays[0] < 0.11  # first retry stays prompt
        # Jitter: two seeded schedules differ.
        rng2 = random.Random(2)
        delays2 = [watch_backoff(a, base=0.1, cap=5.0, rng=rng2) for a in range(12)]
        assert delays != delays2

    def test_watch_stall_reconnect_and_410_relist(self):
        """A stalled watch stream goes silent (no heartbeats), the
        client reconnects with backoff, and — with the event log rolled
        over meanwhile — takes the 410 Gone relist to converge."""
        from kubeadmiral_tpu.testing.fakekube import FakeKube as FK
        from kubeadmiral_tpu.transport.apiserver import KubeApiServer
        from kubeadmiral_tpu.transport.client import HttpKube

        inj = FaultInjector()
        store = FK("m")
        server = KubeApiServer(store, event_log_cap=8, fault_injector=inj,
                               fault_name="m")
        client = HttpKube(server.url, name="m", watch_timeout=0.4)
        try:
            seen = {}
            lock = threading.Lock()

            def handler(ev, obj):
                with lock:
                    seen[obj["metadata"]["name"]] = ev

            client.watch("v1/pods", handler)
            store.create("v1/pods", {"metadata": {"name": "before"}})
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and "before" not in seen:
                time.sleep(0.02)
            assert "before" in seen

            inj.set_fault("m", FaultPolicy(watch_stall=True))
            time.sleep(0.6)  # stream goes silent past the watch timeout
            # Roll the event log far past its cap while stalled, so the
            # reconnect's resume rv is evicted → 410 Gone → relist.
            for i in range(40):
                store.create("v1/pods", {"metadata": {"name": f"p{i}"}})
            inj.clear("m")
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline and "p39" not in seen:
                time.sleep(0.05)
            assert "p39" in seen  # converged through relist
            mux = client._mux["v1/pods"]
            assert mux.reconnect_delays  # the silent stream backed off
            assert all(d <= 5.0 for d in mux.reconnect_delays)
        finally:
            client.close()
            server.close()

    def test_reconnect_storm_backs_off_under_partition(self):
        from kubeadmiral_tpu.testing.fakekube import FakeKube as FK
        from kubeadmiral_tpu.transport.apiserver import KubeApiServer
        from kubeadmiral_tpu.transport.client import HttpKube

        inj = FaultInjector(partition_hang_s=0.2)
        store = FK("m")
        server = KubeApiServer(store, fault_injector=inj, fault_name="m")
        client = HttpKube(server.url, name="m", timeout=0.2, watch_timeout=0.3)
        try:
            seen = []
            client.watch("v1/pods", lambda ev, obj: seen.append(obj))
            inj.set_fault("m", FaultPolicy(partition=True))
            time.sleep(2.0)  # let the reflector churn against the partition
            mux = client._mux["v1/pods"]
            delays = list(mux.reconnect_delays)
            assert len(delays) >= 2  # it retried...
            # ...but NOT flat-out: the later delays grew past the first
            # rung, and everything stayed under the cap.
            assert max(delays) > 0.11
            assert all(d <= 5.0 for d in delays)
            inj.clear("m")
            store.create("v1/pods", {"metadata": {"name": "after"}})
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not seen:
                time.sleep(0.05)
            assert seen  # recovered after the partition cleared
        finally:
            client.close()
            server.close()


# -- the acceptance scenario ----------------------------------------------
def _settle(named, deadline_s=60.0, idle_rounds=8):
    """Step every controller until nothing progresses for a few idle
    polls (watch events over sockets arrive asynchronously)."""
    deadline = time.monotonic() + deadline_s
    idle = 0
    while time.monotonic() < deadline and idle < idle_rounds:
        progressed = False
        for _, ctl in named:
            while ctl.worker.step():
                progressed = True
        if progressed:
            idle = 0
        else:
            idle += 1
            time.sleep(0.03)


class TestMemberFaultToleranceE2E:
    """ISSUE 6 acceptance: 1 of 8 members hard-down (connect-timeout
    partition) under the kwok-lite farm — the first post-fault tick may
    pay one deadline, after the breaker opens ticks stay fast, the down
    member's objects carry ClusterNotReady, and on fault clearance the
    half-open probe closes the breaker and shed writes converge with
    placements bit-identical to the pre-fault state."""

    N_MEMBERS = 8
    N_OBJECTS = 10

    def test_hard_down_member_short_circuits_and_recovers(self, monkeypatch):
        monkeypatch.setenv("KT_DISPATCH_DEADLINE_S", "2.0")
        monkeypatch.setenv("KT_BREAKER_OPEN_S", "4.0")
        monkeypatch.setenv("KT_BREAKER_STALL_S", "0.5")
        monkeypatch.setenv("KT_BREAKER_FAILURES", "2")
        monkeypatch.setenv("KT_RETRY_BASE_S", "0.02")
        monkeypatch.setenv("KT_RETRY_CAP_S", "0.05")
        monkeypatch.setenv("KT_RETRY_MAX", "1")

        import dataclasses

        from kubeadmiral_tpu.federation.clusterctl import (
            FEDERATED_CLUSTERS,
            FederatedClusterController,
            NODES,
        )
        from kubeadmiral_tpu.federation.federate import FederateController
        from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
        from kubeadmiral_tpu.federation.sync import SyncController
        from kubeadmiral_tpu.models.ftc import default_ftcs
        from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
        from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm

        ftc = dataclasses.replace(
            next(f for f in default_ftcs() if f.name == "deployments.apps"),
            controllers=(("kubeadmiral.io/global-scheduler",),),
        )
        farm = KwokLiteFarm()
        farm.fleet.factory.timeout = 1.0  # member round trips: 1 s timeout
        fleet = farm.fleet
        try:
            for i in range(self.N_MEMBERS):
                name = f"m{i}"
                member = farm.add_member(name)
                member.create(NODES, make_node("n1", "64", "128Gi"))
                fleet.host.create(
                    FEDERATED_CLUSTERS,
                    {"apiVersion": "core.kubeadmiral.io/v1alpha1",
                     "kind": "FederatedCluster",
                     "metadata": {"name": name},
                     "spec": farm.cluster_spec(name)},
                )
            fleet.host.create(
                PROPAGATION_POLICIES,
                {"apiVersion": "core.kubeadmiral.io/v1alpha1",
                 "kind": "PropagationPolicy",
                 "metadata": {"name": "pp", "namespace": "default"},
                 "spec": {"schedulingMode": "Divide"}},
            )
            named = [
                ("cluster", FederatedClusterController(
                    fleet, api_resource_probe=["apps/v1/Deployment"],
                    resync_seconds=3.0,
                )),
                ("federate", FederateController(fleet.host, ftc)),
                ("schedule", SchedulerController(fleet.host, ftc)),
                ("sync", SyncController(fleet, ftc)),
            ]
            sync = named[-1][1]
            clusterctl = named[0][1]
            _settle(named)  # joins

            for i in range(self.N_OBJECTS):
                fleet.host.create(
                    ftc.source.resource,
                    make_deployment(name=f"app-{i}", replicas=4 + i),
                )
            _settle(named)

            # Pre-fault truth: every object propagated OK somewhere.
            placements: dict[str, set] = {}
            for key in fleet.host.keys(ftc.federated.resource):
                fed = fleet.host.get(ftc.federated.resource, key)
                placed = C.get_placement(fed, C.SCHEDULER)
                assert placed, f"{key} never scheduled"
                placements[key] = set(placed)
                statuses = {
                    e["cluster"]: e["status"]
                    for e in fed.get("status", {}).get("clusters", [])
                }
                assert all(s == "OK" for s in statuses.values()), (key, statuses)
            down = sorted(
                {c for placed in placements.values() for c in placed}
            )[0]
            down_keys = [k for k, p in placements.items() if down in p]
            assert down_keys, "no object placed on the chosen member"

            def timed_sync_tick() -> float:
                sync.worker.enqueue_all(fleet.host.keys(ftc.federated.resource))
                t0 = time.monotonic()
                while sync.worker.step():
                    pass
                return time.monotonic() - t0

            baseline = min(timed_sync_tick() for _ in range(2))

            # -- fault: hard partition (connect-timeout) ------------------
            farm.set_fault(down, FaultPolicy(partition=True))
            breaker = B.for_fleet(fleet).for_member(down)

            first = timed_sync_tick()
            # The first post-fault tick pays (at most) one deadline-ish
            # member read, never the whole fan-out serialized behind it.
            assert first < 2.0 + 2.0 + 1.0, f"first post-fault tick {first:.1f}s"
            assert breaker.state != B.CLOSED, "breaker never opened"

            post = [timed_sync_tick() for _ in range(3)]
            # After the breaker opens, ticks short-circuit: bounded well
            # under the deadline (and within 1.5x-ish of baseline plus
            # scheduling noise).
            for t in post:
                assert t < max(1.0, baseline * 1.5 + 0.5), (
                    f"post-open tick {t:.2f}s vs baseline {baseline:.2f}s"
                )

            # Down member's objects carry ClusterNotReady.
            for key in down_keys:
                fed = fleet.host.get(ftc.federated.resource, key)
                statuses = {
                    e["cluster"]: e["status"]
                    for e in fed.get("status", {}).get("clusters", [])
                }
                assert statuses.get(down) == D.CLUSTER_NOT_READY, (key, statuses)
            assert B.for_fleet(fleet).shed_total() > 0

            # The same tick's breaker transition re-enqueued the cluster:
            # its Ready condition flips without waiting a resync period.
            while clusterctl.worker.step():
                pass
            cluster_obj = fleet.host.get(FEDERATED_CLUSTERS, down)
            conds = {c["type"]: c for c in cluster_obj["status"]["conditions"]}
            assert conds["Ready"]["status"] != "True"

            # No reconcile/flush thread left parked past the budget.
            time.sleep(0.2)
            stuck = [
                t.name for t in threading.enumerate()
                if t.name.startswith("dispatch-flush-")
            ]
            assert not stuck, stuck

            # -- recovery -------------------------------------------------
            farm.clear_fault(down)
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline and breaker.state != B.CLOSED:
                clusterctl.worker.enqueue(down)  # heartbeat = half-open probe
                while clusterctl.worker.step():
                    pass
                time.sleep(0.2)
            assert breaker.state == B.CLOSED, "probe never closed the breaker"

            deadline = time.monotonic() + 30.0
            converged = False
            while time.monotonic() < deadline and not converged:
                _settle(named, deadline_s=10.0, idle_rounds=4)
                converged = True
                for key in fleet.host.keys(ftc.federated.resource):
                    fed = fleet.host.get(ftc.federated.resource, key)
                    statuses = {
                        e["cluster"]: e["status"]
                        for e in fed.get("status", {}).get("clusters", [])
                    }
                    if not statuses or not all(
                        s == "OK" for s in statuses.values()
                    ):
                        converged = False
                        break
            assert converged, "shed writes never converged after recovery"

            # Placements bit-identical to the never-faulted (pre-fault)
            # run, and the down member holds every shed object again.
            for key, placed in placements.items():
                fed = fleet.host.get(ftc.federated.resource, key)
                assert set(C.get_placement(fed, C.SCHEDULER)) == placed, key
            member = fleet.member(down)
            for key in down_keys:
                assert member.try_get(ftc.source.resource, key) is not None, key
        finally:
            farm.close()


@pytest.mark.slow
class TestFlappingMemberChaos:
    """Long scenario: threaded controllers over the kwok-lite farm with
    one member flapping (partition toggling) during churn — the fleet
    must converge after the flap expires with no worker panics and no
    leaked reconcile threads."""

    def test_flapping_member_converges(self, monkeypatch):
        monkeypatch.setenv("KT_DISPATCH_DEADLINE_S", "2.0")
        monkeypatch.setenv("KT_BREAKER_OPEN_S", "0.5")
        monkeypatch.setenv("KT_BREAKER_STALL_S", "0.5")

        import dataclasses
        import random

        from kubeadmiral_tpu.federation.clusterctl import (
            FEDERATED_CLUSTERS,
            FederatedClusterController,
            NODES,
        )
        from kubeadmiral_tpu.federation.federate import FederateController
        from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
        from kubeadmiral_tpu.federation.sync import SyncController
        from kubeadmiral_tpu.models.ftc import default_ftcs
        from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
        from kubeadmiral_tpu.testing.fakekube import (
            AlreadyExists,
            Conflict,
            NotFound,
        )
        from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm

        ftc = dataclasses.replace(
            next(f for f in default_ftcs() if f.name == "deployments.apps"),
            controllers=(("kubeadmiral.io/global-scheduler",),),
        )
        farm = KwokLiteFarm()
        farm.fleet.factory.timeout = 1.0
        fleet = farm.fleet
        controllers = []
        before_threads = {t.ident for t in threading.enumerate()}
        try:
            for name in ("f1", "f2", "f3", "f4"):
                member = farm.add_member(name)
                member.create(NODES, make_node("n1", "64", "128Gi"))
                fleet.host.create(
                    FEDERATED_CLUSTERS,
                    {"apiVersion": "core.kubeadmiral.io/v1alpha1",
                     "kind": "FederatedCluster",
                     "metadata": {"name": name},
                     "spec": farm.cluster_spec(name)},
                )
            fleet.host.create(
                PROPAGATION_POLICIES,
                {"apiVersion": "core.kubeadmiral.io/v1alpha1",
                 "kind": "PropagationPolicy",
                 "metadata": {"name": "pp", "namespace": "default"},
                 "spec": {"schedulingMode": "Divide"}},
            )
            controllers = [
                FederatedClusterController(
                    fleet, api_resource_probe=["apps/v1/Deployment"],
                    resync_seconds=1.0,
                ),
                FederateController(fleet.host, ftc),
                SchedulerController(fleet.host, ftc),
                SyncController(fleet, ftc),
            ]
            for ctl in controllers:
                ctl.worker.run(workers=2)

            rng = random.Random(0)
            # Flap f2 while objects churn: partitioned 40% of every
            # 0.5 s period, expiring after 4 s.
            farm.set_fault(
                "f2",
                FaultPolicy(partition=True, flap_period_s=0.5,
                            flap_duty=0.4, duration_s=4.0),
            )
            for i in range(60):
                name = f"app-{rng.randint(0, 11)}"
                try:
                    if rng.random() < 0.6:
                        fleet.host.create(
                            ftc.source.resource,
                            make_deployment(name=name,
                                            replicas=rng.randint(1, 12)),
                        )
                    else:
                        obj = fleet.host.try_get(
                            ftc.source.resource, f"default/{name}"
                        )
                        if obj is not None:
                            obj["spec"]["replicas"] = rng.randint(1, 12)
                            fleet.host.update(ftc.source.resource, obj)
                except (AlreadyExists, Conflict, NotFound):
                    pass
                time.sleep(0.05)

            def divergence():
                for key in fleet.host.keys(ftc.source.resource):
                    src = fleet.host.try_get(ftc.source.resource, key)
                    if src is None:
                        continue
                    fed = fleet.host.try_get(ftc.federated.resource, key)
                    if fed is None:
                        return f"{key}: no federated object"
                    placed = C.get_placement(fed, C.SCHEDULER)
                    if not placed:
                        return f"{key}: never scheduled"
                    total = 0
                    for cname in placed:
                        obj = fleet.member(cname).try_get(
                            ftc.source.resource, key
                        )
                        if obj is None:
                            return f"{key}: missing in {cname}"
                        total += obj["spec"].get("replicas", 0)
                    if total != src["spec"]["replicas"]:
                        return f"{key}: {total} != {src['spec']['replicas']}"
                return None

            deadline = time.monotonic() + 120.0
            last = "never checked"
            while time.monotonic() < deadline:
                time.sleep(0.5)
                last = divergence()
                if last is None:
                    break
            assert last is None, last
            for ctl in controllers:
                panic_count = ctl.metrics.counters.get(
                    f"{ctl.worker.name}.panic", 0
                )
                assert not panic_count, (
                    f"{ctl.worker.name}: {panic_count} reconcile panics"
                )
        finally:
            for ctl in controllers:
                ctl.worker.stop()
            farm.close()
        # No leaked reconcile threads: everything we started is joined.
        time.sleep(0.5)
        leaked = [
            t.name for t in threading.enumerate()
            if t.ident not in before_threads
            and any(t.name.startswith(p) for p in
                    ("cluster-controller", "federate-", "scheduler-", "sync-"))
            and t.is_alive()
        ]
        assert not leaked, leaked


class TestFaultControlEndpoint:
    """POST /faultz (ISSUE 15): fault injection over the wire, so the
    kwok-lite farm can chaos-inject SUBPROCESS members too."""

    def test_set_and_clear_over_http(self):
        from kubeadmiral_tpu.transport.apiserver import KubeApiServer
        from kubeadmiral_tpu.transport.client import HttpKube

        store = FakeKube("m-f")
        server = KubeApiServer(store, admin_token="tok")
        try:
            client = HttpKube(server.url, token="tok", timeout=2.0)
            store.create("v1/pods", {"metadata": {"name": "p"}, "spec": {}})
            assert client.get("v1/pods", "p")["metadata"]["name"] == "p"
            # Inject a hard error policy via the endpoint.
            status, payload, _ = client._request(
                "POST", "/faultz", {"policy": {"error_rate": 1.0}}
            )
            assert status == 200 and payload["status"] == "ok"
            with pytest.raises(TransportError):
                client.get("v1/pods", "p")
            # Clearing (policy: null) goes through even while faulted —
            # the endpoint is exempt from the fault gate.
            status, payload, _ = client._request(
                "POST", "/faultz", {"policy": None}
            )
            assert status == 200 and payload["status"] == "cleared"
            assert client.get("v1/pods", "p")["metadata"]["name"] == "p"
            # Unknown fields are rejected loudly, not silently dropped.
            status, payload, _ = client._request(
                "POST", "/faultz", {"policy": {"no_such_field": 1}}
            )
            assert status == 400
            client.close()
        finally:
            server.close()

    def test_faultz_requires_auth(self):
        from kubeadmiral_tpu.transport.apiserver import KubeApiServer
        from kubeadmiral_tpu.transport.client import HttpKube

        store = FakeKube("m-f2")
        server = KubeApiServer(store, admin_token="tok")
        try:
            anon = HttpKube(server.url, timeout=2.0)
            status, _, _ = anon._request(
                "POST", "/faultz", {"policy": {"error_rate": 1.0}}
            )
            assert status == 401
            anon.close()
        finally:
            server.close()

    def test_farm_routes_faults_to_inprocess_members(self):
        from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm

        farm = KwokLiteFarm()
        try:
            client = farm.add_member("m-0")
            farm.set_fault("m-0", FaultPolicy(error_rate=1.0))
            with pytest.raises(TransportError):
                client.list("v1/pods")
            farm.clear_fault("m-0")
            assert client.list("v1/pods") == []
        finally:
            farm.close()


@pytest.mark.slow
class TestSubprocessFaultControl:
    def test_subprocess_member_injectable(self):
        """A subprocess farm member honors set_fault/clear_fault through
        the fault-control endpoint (the chaos phase's enabling seam)."""
        from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm

        farm = KwokLiteFarm(member_subprocess=True)
        try:
            client = farm.add_member("m-sub")
            assert client.list("v1/pods") == []
            farm.set_fault("m-sub", FaultPolicy(error_rate=1.0))
            with pytest.raises(TransportError):
                client.list("v1/pods")
            farm.clear_fault("m-sub")
            assert client.list("v1/pods") == []
        finally:
            farm.close()
