"""Pallas slab-front parity (ISSUE 11).

ops/pallas_slab.py hand-fuses the narrow slab's per-cell phase-1 front
(filters, reason bits, score plugins + normalizations) into one
VMEM-resident Pallas pass per row block.  On CPU the kernel runs in
interpreter mode — the SAME kernel body tier-1 can execute — and the
contract is bit-identity with the XLA ``_phase1`` on every plane, so
the narrow solve fed the Pallas triple reproduces the XLA narrow solve
exactly (certificates included).  KT_PALLAS=1 routes the engine's
narrow programs through it; KT_PALLAS=0 (the default) keeps the XLA
path.
"""

import numpy as np
import pytest

from test_drift_replan import _fitflip_world, _quarter_cpu
from test_engine_cache import results_equal
from test_pipeline import random_problem, to_tick_inputs

from kubeadmiral_tpu.ops import pallas_slab as ps
from kubeadmiral_tpu.ops import pipeline as dev
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

PLANES = ("selected", "replicas", "counted", "feasible", "scores", "reasons")


def _random_inputs(rng, b, c, webhook=False, invalid_cols=0):
    names = [f"member-{j}" for j in range(c)]
    problems = [random_problem(rng, c, f"ns/w-{i}", names) for i in range(b)]
    inp = to_tick_inputs(problems, c)
    if webhook:
        inp = inp._replace(
            webhook_ok=rng.random((b, c)) > 0.15,
            webhook_scores=rng.integers(-50, 200, (b, c)).astype(np.int64),
        )
    if invalid_cols:
        valid = np.ones(c, bool)
        valid[-invalid_cols:] = False
        inp = inp._replace(cluster_valid=valid)
    return inp


class TestPhase1Parity:
    @pytest.mark.parametrize(
        "b,c,webhook,invalid",
        [
            (16, 24, False, 0),
            (32, 12, True, 3),    # webhook planes + padded columns
            (13, 40, False, 0),   # odd B: block-rows fallback path
            (8, 200, True, 7),    # wide-ish cluster axis
        ],
    )
    def test_bit_identical_to_xla_phase1(self, b, c, webhook, invalid):
        rng = np.random.default_rng(b * 1000 + c)
        inp = _random_inputs(rng, b, c, webhook=webhook, invalid_cols=invalid)
        f_ref, r_ref, t_ref = dev._phase1(inp)
        f_pl, r_pl, t_pl = ps.phase1_slab(inp, interpret=True)
        assert np.array_equal(np.asarray(f_ref), np.asarray(f_pl))
        assert np.array_equal(np.asarray(r_ref), np.asarray(r_pl))
        assert np.array_equal(np.asarray(t_ref), np.asarray(t_pl))

    def test_narrow_solve_with_pallas_phase1_bit_identical(self):
        rng = np.random.default_rng(42)
        inp = _random_inputs(rng, 48, 32, webhook=True, invalid_cols=2)
        out_x, cert_x = dev.schedule_tick_narrow(inp, 8)
        out_p, cert_p = dev.schedule_tick_narrow(
            inp, 8, phase1=ps.phase1_slab(inp, interpret=True)
        )
        for name, a, b in zip(out_x._fields, out_x, out_p):
            assert np.array_equal(np.asarray(a), np.asarray(b)), name
        assert np.array_equal(np.asarray(cert_x), np.asarray(cert_p))

    def test_i32_keys_path_unchanged(self):
        rng = np.random.default_rng(7)
        inp = _random_inputs(rng, 24, 20)
        out_x, cert_x = dev.schedule_tick_narrow(inp, 8, i32_keys=True)
        out_p, cert_p = dev.schedule_tick_narrow(
            inp, 8, i32_keys=True, phase1=ps.phase1_slab(inp, interpret=True)
        )
        for name, a, b in zip(out_x._fields, out_x, out_p):
            assert np.array_equal(np.asarray(a), np.asarray(b)), name
        assert np.array_equal(np.asarray(cert_x), np.asarray(cert_p))


class TestEngineKnob:
    def _engine(self, **kw):
        kw.setdefault("chunk_size", 64)
        kw.setdefault("min_bucket", 32)
        kw.setdefault("min_cluster_bucket", 8)
        kw.setdefault("narrow_m", 16)
        return SchedulerEngine(**kw)

    def test_kt_pallas_engine_bit_identical(self, monkeypatch):
        """KT_PALLAS=1: cold + churn + fit-flip drift through the
        Pallas-fronted narrow programs equals the default engine."""
        units, clusters = _fitflip_world(b=64, c=20)
        monkeypatch.setenv("KT_PALLAS", "1")
        eng_p = self._engine()
        assert eng_p.pallas
        monkeypatch.setenv("KT_PALLAS", "0")
        eng_x = self._engine()
        assert not eng_x.pallas

        got = eng_p.schedule(units, clusters)
        want = eng_x.schedule(units, clusters)
        results_equal(got, want)

        import dataclasses

        churned = list(units)
        churned[5] = dataclasses.replace(units[5], desired_replicas=77)
        got = eng_p.schedule(churned, clusters)
        want = eng_x.schedule(churned, clusters)
        results_equal(got, want)

        drifted = _quarter_cpu(clusters, 3)
        got = eng_p.schedule(churned, drifted)
        want = eng_x.schedule(churned, drifted)
        results_equal(got, want)

    def test_kt_pallas_default_off(self):
        assert not self._engine().pallas
