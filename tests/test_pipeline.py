"""Differential tests: fused schedule_tick vs the per-object oracle."""

import numpy as np
import pytest

from kubeadmiral_tpu.ops import pipeline as dev
from kubeadmiral_tpu.ops.pipeline_oracle import NIL, OracleProblem, schedule_one
from kubeadmiral_tpu.ops.planner import INT32_INF
from kubeadmiral_tpu.utils.hashing import fnv32_batch, uint32_to_sortable_int32

INF = int(INT32_INF)
R = 4  # cpu, mem, 2 scalar resources


def random_problem(rng, c, key, names):
    divide = bool(rng.random() < 0.7)
    current_n = int(rng.integers(0, c + 1)) if rng.random() < 0.5 else 0
    current_idx = rng.choice(c, size=current_n, replace=False) if current_n else []
    current = {}
    for idx in current_idx:
        current[int(idx)] = None if rng.random() < 0.3 else int(rng.integers(0, 10))

    static_weights = None
    if rng.random() < 0.5:
        static_weights = {
            int(j): int(rng.integers(0, 20)) for j in range(c) if rng.random() < 0.8
        }

    maxc = None
    roll = rng.random()
    if roll < 0.25:
        maxc = int(rng.integers(0, c + 2))
    elif roll < 0.3:
        maxc = -1

    return OracleProblem(
        n_clusters=c,
        filter_enabled=[bool(rng.random() < 0.8) for _ in range(5)],
        score_enabled=[bool(rng.random() < 0.8) for _ in range(5)],
        api_ok=[bool(rng.random() < 0.9) for _ in range(c)],
        taint_ok_new=[bool(rng.random() < 0.85) for _ in range(c)],
        taint_ok_cur=[bool(rng.random() < 0.95) for _ in range(c)],
        selector_ok=[bool(rng.random() < 0.9) for _ in range(c)],
        placement_ok=[bool(rng.random() < 0.7) for _ in range(c)],
        placement_has=bool(rng.random() < 0.4),
        request=[int(x) for x in rng.integers(0, 8, R)]
        if rng.random() < 0.8
        else [0] * R,
        alloc=[[int(x) for x in rng.integers(5, 50, R)] for _ in range(c)],
        used=[[int(x) for x in rng.integers(0, 40, R)] for _ in range(c)],
        taint_counts=[int(x) for x in rng.integers(0, 4, c)],
        affinity_scores=[int(x) for x in rng.integers(0, 60, c)],
        max_clusters=maxc,
        mode_divide=divide,
        sticky=bool(rng.random() < 0.15),
        current=current,
        total=int(rng.integers(0, 30)),
        weights=static_weights,
        min_replicas={
            int(j): int(rng.integers(0, 4)) for j in range(c) if rng.random() < 0.2
        },
        max_replicas={
            int(j): int(rng.integers(0, 10)) for j in range(c) if rng.random() < 0.2
        },
        capacity={
            int(j): int(rng.integers(0, 8)) for j in range(c) if rng.random() < 0.2
        },
        keep_unschedulable=bool(rng.random() < 0.5),
        avoid_disruption=bool(rng.random() < 0.5),
        cluster_names=names,
        key=key,
        cpu_alloc=[int(x) for x in rng.integers(0, 30, c)],
        cpu_avail=[int(x) for x in rng.integers(-3, 25, c)],
    )


def to_tick_inputs(problems, c):
    b = len(problems)
    names = problems[0].cluster_names

    def grid(get, dtype, fill=0):
        out = np.full((b, c), fill, dtype=dtype)
        for i, p in enumerate(problems):
            row = get(p)
            for j, v in row.items() if isinstance(row, dict) else enumerate(row):
                out[i, j] = v
        return out

    tiebreak = np.stack(
        [
            uint32_to_sortable_int32(fnv32_batch(names, p.key)).astype(np.int32)
            for p in problems
        ]
    )
    current_mask = np.zeros((b, c), bool)
    current_replicas = np.full((b, c), dev.NIL_REPLICAS, np.int64)
    for i, p in enumerate(problems):
        for j, v in p.current.items():
            current_mask[i, j] = True
            current_replicas[i, j] = dev.NIL_REPLICAS if v is None else v

    weights_given = np.array([p.weights is not None for p in problems])
    weights = grid(lambda p: p.weights or {}, np.int32)

    return dev.TickInputs(
        filter_enabled=np.array([p.filter_enabled for p in problems]),
        api_ok=grid(lambda p: p.api_ok, bool),
        taint_ok_new=grid(lambda p: p.taint_ok_new, bool),
        taint_ok_cur=grid(lambda p: p.taint_ok_cur, bool),
        selector_ok=grid(lambda p: p.selector_ok, bool),
        placement_has=np.array([p.placement_has for p in problems]),
        placement_ok=grid(lambda p: p.placement_ok, bool),
        request=np.array([p.request for p in problems], np.int64),
        alloc=np.array(problems[0].alloc, np.int64),
        used=np.array(problems[0].used, np.int64),
        score_enabled=np.array([p.score_enabled for p in problems]),
        taint_counts=grid(lambda p: p.taint_counts, np.int64),
        affinity_scores=grid(lambda p: p.affinity_scores, np.int64),
        webhook_ok=np.ones((len(problems), c), bool),
        webhook_scores=np.zeros((len(problems), c), np.int64),
        max_clusters=np.array(
            [INF if p.max_clusters is None else p.max_clusters for p in problems],
            np.int32,
        ),
        mode_divide=np.array([p.mode_divide for p in problems]),
        sticky=np.array([p.sticky for p in problems]),
        current_mask=current_mask,
        current_replicas=current_replicas,
        total=np.array([p.total for p in problems], np.int32),
        weights_given=weights_given,
        weights=weights,
        min_replicas=grid(lambda p: p.min_replicas, np.int32),
        max_replicas=grid(lambda p: p.max_replicas, np.int32, INF),
        scale_max=grid(lambda p: p.max_replicas, np.int32, INF),
        capacity=grid(lambda p: p.capacity, np.int32, INF),
        keep_unschedulable=np.array([p.keep_unschedulable for p in problems]),
        avoid_disruption=np.array([p.avoid_disruption for p in problems]),
        tiebreak=tiebreak,
        cpu_alloc=np.array(problems[0].cpu_alloc, np.int64),
        cpu_avail=np.array(problems[0].cpu_avail, np.int64),
        cluster_valid=np.ones(c, bool),
    )


@pytest.mark.parametrize("c", [3, 8, 19])
def test_tick_matches_oracle(c):
    rng = np.random.default_rng(99 + c)
    names = [f"member-{j}" for j in range(c)]
    problems = []
    # Cluster-level state is shared across the batch (as in a real tick).
    shared_alloc = [[int(x) for x in rng.integers(5, 50, R)] for _ in range(c)]
    shared_used = [[int(x) for x in rng.integers(0, 40, R)] for _ in range(c)]
    shared_cpu_a = [int(x) for x in rng.integers(0, 30, c)]
    shared_cpu_v = [int(x) for x in rng.integers(-3, 25, c)]
    for i in range(80):
        p = random_problem(rng, c, f"ns-{i}/workload-{i}", names)
        p.alloc, p.used = shared_alloc, shared_used
        p.cpu_alloc, p.cpu_avail = shared_cpu_a, shared_cpu_v
        problems.append(p)

    out = dev.schedule_tick(to_tick_inputs(problems, c))
    selected = np.asarray(out.selected)
    replicas = np.asarray(out.replicas)

    for i, p in enumerate(problems):
        want = schedule_one(p)
        got_idx = set(np.nonzero(selected[i])[0].tolist())
        assert got_idx == set(want.keys()), (
            f"case {i}: selected {sorted(got_idx)} != {sorted(want)}\n{p}\n"
            f"scores={np.asarray(out.scores)[i]} feasible={np.asarray(out.feasible)[i]}"
        )
        for j in got_idx:
            w = want[j]
            g = int(replicas[i, j])
            if w is None:
                assert g == NIL, f"case {i} cluster {j}: {g} != nil\n{p}"
            else:
                assert g == w, f"case {i} cluster {j}: {g} != {w}\n{p}\n{want}"


class TestExactIntegerScoreMath:
    """The balanced score and dynamic weights are defined as exact
    rationals (not f64), because axon TPUs demote f64 to f32 and float
    truncation flips values at integer boundaries (caught by the r5
    on-chip batched-vs-native parity check).  Pin the boundary values
    all three implementations (device / oracle / C++) must share."""

    def test_balanced_score_integer_boundary(self):
        import jax.numpy as jnp

        from kubeadmiral_tpu.ops.pipeline_oracle import _balanced
        from kubeadmiral_tpu.ops.scores import balanced_allocation_score

        # f_cpu = 1/2, f_mem = 2/25 -> diff = 0.42 exactly -> score 58.
        # An f64 formulation truncates (1-0.42)*100 = 57.999... to 57.
        request = jnp.array([[1, 2]], dtype=jnp.int64)
        alloc = jnp.array([[2, 25]], dtype=jnp.int64)
        used = jnp.array([[0, 0]], dtype=jnp.int64)
        dev_score = int(balanced_allocation_score(request, alloc, used)[0, 0])
        assert dev_score == 58

        class P:  # _balanced reads request/alloc/used only
            request = [1, 2]
            alloc = [[2, 25]]
            used = [[0, 0]]

        assert _balanced(P, 0) == 58

    def test_balanced_score_range_reduction_large_quantities(self):
        import jax.numpy as jnp

        from kubeadmiral_tpu.ops.scores import balanced_allocation_score

        # Memory in bytes at Ti scale: the cross products only fit int64
        # after the range shift; the exact path must not overflow.
        ac, am = 512_000, 2 * 1024**4  # 512 cores, 2Ti
        rc, rm = 256_000, 1024**4  # half of each -> diff 0, score 100
        request = jnp.array([[rc, rm]], dtype=jnp.int64)
        alloc = jnp.array([[ac, am]], dtype=jnp.int64)
        used = jnp.array([[0, 0]], dtype=jnp.int64)
        assert int(balanced_allocation_score(request, alloc, used)[0, 0]) == 100

    def test_round_half_away_rule(self):
        import jax.numpy as jnp

        from kubeadmiral_tpu.ops.pipeline_oracle import round_half_div
        from kubeadmiral_tpu.ops.weights import _round_half_div

        cases = [(125, 2, 63), (1000, 3, 333), (1000, 7, 143), (62, 4, 16)]
        for num, den, want in cases:
            assert round_half_div(num, den) == want, (num, den)
            got = int(
                _round_half_div(
                    jnp.array([num], dtype=jnp.int64),
                    jnp.array([den], dtype=jnp.int64),
                )[0]
            )
            assert got == want, (num, den, got)

    def test_negative_weight_means_no_share(self):
        """Non-positive weights get no replicas in any implementation:
        the r5 full-shape parity check caught the device planner's ceil
        quotas exploding to INT32_INF-scale plans when the dynamic-
        weight residual went negative at thousands of selected clusters
        (100k x 5k: 2,748 rows)."""
        import jax.numpy as jnp
        import numpy as np

        from kubeadmiral_tpu.ops.planner import (
            INT32_INF, PlannerInputs, plan_batch_jit,
        )
        from kubeadmiral_tpu.ops.planner_oracle import (
            ClusterPref, PlanInput, plan as oracle_plan,
        )

        c = 4
        weight = jnp.array([[5, -50, 3, 0]], jnp.int32)
        member = jnp.ones((1, c), bool)
        inf = jnp.full((1, c), INT32_INF, jnp.int32)
        out = plan_batch_jit(
            PlannerInputs(
                weight=weight,
                min_replicas=jnp.zeros((1, c), jnp.int32),
                max_replicas=inf,
                scale_max=inf,
                capacity=inf,
                tiebreak=jnp.arange(c, dtype=jnp.int32)[None, :],
                member=member,
                total=jnp.array([40], jnp.int32),
                current=jnp.zeros((1, c), jnp.int32),
                avoid_disruption=jnp.array([False]),
                keep_unschedulable=jnp.array([False]),
            )
        )
        plan = np.asarray(out.plan)[0]
        assert plan.sum() == 40, plan
        assert plan[1] == 0, plan  # negative weight: no share
        assert (plan >= 0).all(), plan

        want = oracle_plan(
            PlanInput(
                prefs={
                    "m0": ClusterPref(weight=5),
                    "m1": ClusterPref(weight=-50),
                    "m2": ClusterPref(weight=3),
                    "m3": ClusterPref(weight=0),
                },
                total=40,
                key="default/w",
                clusters=["m0", "m1", "m2", "m3"],
            )
        )
        got = {f"m{i}": int(v) for i, v in enumerate(plan) if v}
        assert got == {k: v for k, v in want[0].items() if v}, (got, want)

    def test_dynamic_weight_residual_clamped_at_zero(self):
        """At thousands of selected clusters the rounded weights sum
        past 1000 and the residual would drive the max cluster negative;
        all implementations clamp it at zero.  Equal shares across 2000
        clusters make every weight round half-up to 1 (sum 2000), so the
        residual is -1000 — far past the max weight of 1."""
        import jax.numpy as jnp
        import numpy as np

        from kubeadmiral_tpu.ops.weights import dynamic_weights

        c = 2000
        sel = jnp.ones((1, c), bool)
        alloc = jnp.full(c, 100, jnp.int64)
        avail = jnp.full(c, 50, jnp.int64)
        w = np.asarray(dynamic_weights(sel, alloc, avail))[0]
        # Every share rounds half-up to 1; the unclamped residual rule
        # would set the first cluster to 1 + (1000 - 2000) = -999.
        assert w.sum() == 1999 and w.max() == 1, (w.sum(), w.max())
        assert (w >= 0).all(), w.min()
