"""Cross-cluster rollout planning (reference:
pkg/controllers/util/rolloutplan.go + rolloutplan_test.go's behavioral
model, applied through the sync dispatcher)."""

import dataclasses

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation import rollout as R
from kubeadmiral_tpu.federation.clusterctl import (
    FEDERATED_CLUSTERS,
    FederatedClusterController,
    NODES,
)
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.retain import CURRENT_REVISION_ANNOTATION
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
from kubeadmiral_tpu.testing.fakekube import ClusterFleet
from kubeadmiral_tpu.testing.membersim import MemberDeploymentSimulator

from test_e2e_slice import make_node, settle


class TestResolveFenceposts:
    def test_ints(self):
        assert R.resolve_fenceposts(2, 1, 10) == (2, 1)

    def test_percent_rounding(self):
        # surge rounds up, unavailable rounds down (k8s intstr semantics).
        assert R.resolve_fenceposts("25%", "25%", 10) == (3, 2)

    def test_both_zero_degenerates_to_one_unavailable(self):
        assert R.resolve_fenceposts(0, 0, 10) == (0, 1)

    def test_none_defaults_to_zero_then_degenerates(self):
        assert R.resolve_fenceposts(None, None, 10) == (0, 1)


def fed_obj(max_surge=0, max_unavailable=2, revision="rev-2"):
    return {
        "metadata": {
            "name": "web",
            "namespace": "default",
            "annotations": {CURRENT_REVISION_ANNOTATION: revision},
        },
        "spec": {
            "template": {
                "spec": {
                    "strategy": {
                        "rollingUpdate": {
                            "maxSurge": max_surge,
                            "maxUnavailable": max_unavailable,
                        }
                    }
                }
            }
        },
    }


def target(cluster, replicas, desired, updated=False, available=None,
           current_new=None, current_new_available=None,
           max_surge=0, max_unavailable=1):
    """A stable member: its newest ReplicaSet is its own template's RS at
    full scale (current_new == replicas) whether or not that template
    matches the fed revision; ``updated`` only controls whether those
    count toward the fed rollout."""
    available = replicas if available is None else available
    current_new = replicas if current_new is None else current_new
    if current_new_available is None:
        current_new_available = current_new if available >= current_new else available
    return R.Target(
        cluster=cluster,
        desired_replicas=desired,
        status=R.TargetStatus(
            replicas=replicas,
            actual_replicas=replicas,
            available_replicas=available,
            updated_replicas=current_new if updated else 0,
            updated_available_replicas=current_new_available if updated else 0,
            current_new_replicas=current_new,
            current_new_available_replicas=current_new_available,
            updated=updated,
            max_surge=max_surge,
            max_unavailable=max_unavailable,
        ),
    )


class TestRolloutPlanner:
    def make_planner(self, targets, max_surge=0, max_unavailable=2, replicas=9):
        planner = R.RolloutPlanner("default/web", fed_obj(max_surge, max_unavailable), replicas)
        for t in targets:
            planner.register(t)
        return planner

    def test_pure_scaling_gives_empty_plans(self):
        planner = self.make_planner(
            [
                target("c1", 3, 5, updated=True),
                target("c2", 3, 3, updated=True),
            ],
            replicas=8,
        )
        plans = planner.plan()
        assert set(plans) == {"c1", "c2"}
        for plan in plans.values():
            assert plan.replicas is None
            assert plan.max_surge is None
            assert plan.max_unavailable is None

    def test_update_budget_serializes_clusters(self):
        # All three need the new template; federation allows 2 unavailable.
        planner = self.make_planner(
            [
                target("c1", 3, 3),
                target("c2", 3, 3),
                target("c3", 3, 3),
            ]
        )
        plans = planner.plan()
        # Only the first (name-ordered) cluster gets the budget.
        assert set(plans) == {"c1"}
        assert plans["c1"].max_unavailable == 2
        assert plans["c1"].max_surge == 0

    def test_completed_cluster_frees_budget_for_next(self):
        planner = self.make_planner(
            [
                target("c1", 3, 3, updated=True),
                target("c2", 3, 3),
                target("c3", 3, 3),
            ]
        )
        plans = planner.plan()
        assert "c2" in plans
        assert plans["c2"].max_unavailable == 2
        # Completed c1 gets the nil-fencepost final plan.
        assert "c1" in plans
        assert plans["c1"].max_surge is None and plans["c1"].max_unavailable is None
        assert "c3" not in plans

    def test_unavailable_replicas_occupy_budget(self):
        # c1 has 2 unavailable replicas mid-update: no remaining budget.
        planner = self.make_planner(
            [
                target("c1", 3, 3, available=1, current_new=2,
                       current_new_available=0, max_unavailable=2),
                target("c2", 3, 3),
                target("c3", 3, 3),
            ]
        )
        plans = planner.plan()
        assert "c2" not in plans and "c3" not in plans

    def test_scale_in_prefers_unavailable_and_funds_upgrade(self):
        # c1 shrinks 6->3: its shrink happens within the unavailability
        # budget; onlyPatchReplicas protects its template.
        planner = self.make_planner(
            [
                target("c1", 6, 3),
                target("c2", 3, 6, updated=True),
            ],
            replicas=9,
            max_unavailable=2,
        )
        plans = planner.plan()
        assert "c1" in plans
        assert plans["c1"].replicas == 4  # shrank by the budget of 2
        assert plans["c1"].only_patch_replicas

    def test_deleted_cluster_drains_through_plan(self):
        planner = self.make_planner(
            [
                target("c1", 3, 0, updated=True),
                target("c2", 3, 3, updated=True),
                target("c3", 3, 3, updated=True),
            ],
            replicas=6,
            max_unavailable=3,
        )
        plans = planner.plan()
        # A pure scaling event yields nil-replica plans; the dispatcher's
        # deletion branch treats nil replicas on a to-delete cluster as
        # "drain now" (managed.go:236-239).
        assert plans["c1"].replicas in (None, 0)

    def test_validate_rejects_overdraining_plans(self):
        planner = self.make_planner([target("c1", 3, 3)], replicas=3)
        bad = {"c1": R.RolloutPlan(replicas=0)}
        assert not planner._validate(bad)


def make_rollout_deployment(replicas=9):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": "web",
            "namespace": "default",
            "labels": {"kubeadmiral.io/propagation-policy-name": "pp"},
        },
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": "web"}},
            "strategy": {
                "type": "RollingUpdate",
                "rollingUpdate": {"maxSurge": 0, "maxUnavailable": 2},
            },
            "template": {
                "metadata": {"labels": {"app": "web"}},
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "image": "nginx:1",
                            "resources": {"requests": {"cpu": "100m"}},
                        }
                    ]
                },
            },
        },
    }


class TestRolloutEndToEnd:
    """Image update across 3 members: at no point may federation-wide
    unavailability exceed the fed maxUnavailable, and no surge is allowed
    with maxSurge 0."""

    def setup_method(self):
        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        self.ftc = dataclasses.replace(
            ftc,
            controllers=(("kubeadmiral.io/global-scheduler",),),
            rollout_plan=True,
        )
        self.fleet = ClusterFleet()
        gvk = "apps/v1/Deployment"
        self.clusterctl = FederatedClusterController(
            self.fleet, api_resource_probe=[gvk]
        )
        self.federate = FederateController(self.fleet.host, self.ftc)
        self.scheduler = SchedulerController(self.fleet.host, self.ftc)
        self.sync = SyncController(self.fleet, self.ftc)
        self.sim = MemberDeploymentSimulator(self.fleet)

        for name in ("c1", "c2", "c3"):
            member = self.fleet.add_member(name)
            member.create(NODES, make_node("n1", "64", "128Gi"))
            self.fleet.host.create(
                FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": {},
                },
            )
        self.fleet.host.create(
            PROPAGATION_POLICIES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "PropagationPolicy",
                "metadata": {"name": "pp", "namespace": "default"},
                "spec": {
                    "schedulingMode": "Divide",
                    "placements": [
                        {"cluster": c, "preferences": {"weight": 1}}
                        for c in ("c1", "c2", "c3")
                    ],
                },
            },
        )

    def controllers(self):
        return (self.clusterctl, self.federate, self.scheduler, self.sync)

    def run_to_convergence(self, max_rounds=60, invariant=None):
        for _ in range(max_rounds):
            progressed = False
            for c in self.controllers():
                progressed |= c.worker.step()
            progressed |= self.sim.step()
            if invariant is not None:
                invariant()
            if not progressed:
                return

    def member_images(self):
        out = {}
        for name in ("c1", "c2", "c3"):
            obj = self.fleet.member(name).try_get(
                self.ftc.source.resource, "default/web"
            )
            out[name] = (
                obj["spec"]["template"]["spec"]["containers"][0]["image"]
                if obj
                else None
            )
        return out

    def test_rollout_respects_federation_invariants(self):
        self.fleet.host.create(
            self.ftc.source.resource, make_rollout_deployment(replicas=9)
        )
        self.run_to_convergence()
        assert self.member_images() == {c: "nginx:1" for c in ("c1", "c2", "c3")}
        assert self.sim.total_unavailable(9) == 0

        src = self.fleet.host.get(self.ftc.source.resource, "default/web")
        src["spec"]["template"]["spec"]["containers"][0]["image"] = "nginx:2"
        self.fleet.host.update(self.ftc.source.resource, src)

        violations = []

        def invariant():
            unavailable = self.sim.total_unavailable(9)
            surge = self.sim.total_surge(9)
            if unavailable > 2 or surge > 0:
                violations.append((unavailable, surge))

        self.run_to_convergence(invariant=invariant)
        assert self.member_images() == {c: "nginx:2" for c in ("c1", "c2", "c3")}
        assert not violations, f"invariant violated: {violations}"
        assert self.sim.total_unavailable(9) == 0

    def test_scale_only_change_skips_rollout_gating(self):
        self.fleet.host.create(
            self.ftc.source.resource, make_rollout_deployment(replicas=9)
        )
        self.run_to_convergence()
        src = self.fleet.host.get(self.ftc.source.resource, "default/web")
        src["spec"]["replicas"] = 12
        self.fleet.host.update(self.ftc.source.resource, src)
        self.run_to_convergence()
        total = 0
        for name in ("c1", "c2", "c3"):
            obj = self.fleet.member(name).get(self.ftc.source.resource, "default/web")
            total += obj["spec"]["replicas"]
        assert total == 12
