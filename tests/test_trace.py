"""Span tracer: nesting, ring bounds, Chrome trace export
(runtime/trace.py)."""

import json
import threading

import pytest

from kubeadmiral_tpu.runtime.trace import Tracer


class TestNesting:
    def test_parent_child_ids(self):
        t = Tracer()
        with t.span("parent") as p:
            with t.span("child") as c:
                pass
            with t.span("sibling") as s:
                pass
        assert c.parent_id == p.span_id
        assert s.parent_id == p.span_id
        assert p.parent_id is None
        # Completion order: children land in the ring before the parent.
        assert [sp.name for sp in t.spans()] == ["child", "sibling", "parent"]

    def test_span_attrs_and_set(self):
        t = Tracer()
        with t.span("work", controller="sync") as sp:
            sp.set(keys=7)
        done = t.spans()[0]
        assert done.args == {"controller": "sync", "keys": 7}
        assert done.end >= done.start

    def test_exception_still_records_and_propagates(self):
        t = Tracer()
        with pytest.raises(ValueError):
            with t.span("boom"):
                raise ValueError("x")
        assert [sp.name for sp in t.spans()] == ["boom"]
        assert t.current() is None  # stack unwound

    def test_threads_get_independent_stacks(self):
        t = Tracer()
        done = threading.Event()

        def other():
            with t.span("other-root"):
                pass
            done.set()

        with t.span("main-root"):
            th = threading.Thread(target=other)
            th.start()
            th.join()
        assert done.wait(1)
        roots = {sp.name: sp.parent_id for sp in t.spans()}
        # The other thread's span is NOT a child of main's open span.
        assert roots == {"other-root": None, "main-root": None}

    def test_ring_is_bounded(self):
        t = Tracer(ring=4)
        for i in range(10):
            with t.span(f"s{i}"):
                pass
        assert [sp.name for sp in t.spans()] == ["s6", "s7", "s8", "s9"]


class TestChromeExport:
    def test_event_shape(self):
        t = Tracer()
        with t.span("outer", ftc="deployments.apps"):
            with t.span("inner"):
                pass
        doc = json.loads(t.chrome_trace_json())
        events = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
        assert set(events) == {"outer", "inner"}
        outer, inner = events["outer"], events["inner"]
        for e in (outer, inner):
            assert e["ph"] == "X"
            assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
            assert e["pid"] > 0 and e["tid"] > 0
        assert inner["args"]["parent_id"] == outer["args"]["span_id"]
        assert outer["args"]["ftc"] == "deployments.apps"
        # Nesting must also hold by timestamps (what chrome://tracing
        # actually renders): inner within [outer.ts, outer.ts+dur].
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3

    def test_thread_metadata_events(self):
        t = Tracer()
        with t.span("x"):
            pass
        doc = t.chrome_trace()
        meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert meta and meta[0]["name"] == "thread_name"
        assert doc["displayTimeUnit"] == "ms"
