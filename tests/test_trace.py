"""Span tracer: nesting, ring bounds, Chrome trace export
(runtime/trace.py)."""

import json
import threading

import pytest

from kubeadmiral_tpu.runtime.trace import Tracer


class TestNesting:
    def test_parent_child_ids(self):
        t = Tracer()
        with t.span("parent") as p:
            with t.span("child") as c:
                pass
            with t.span("sibling") as s:
                pass
        assert c.parent_id == p.span_id
        assert s.parent_id == p.span_id
        assert p.parent_id is None
        # Completion order: children land in the ring before the parent.
        assert [sp.name for sp in t.spans()] == ["child", "sibling", "parent"]

    def test_span_attrs_and_set(self):
        t = Tracer()
        with t.span("work", controller="sync") as sp:
            sp.set(keys=7)
        done = t.spans()[0]
        assert done.args == {"controller": "sync", "keys": 7}
        assert done.end >= done.start

    def test_exception_still_records_and_propagates(self):
        t = Tracer()
        with pytest.raises(ValueError):
            with t.span("boom"):
                raise ValueError("x")
        assert [sp.name for sp in t.spans()] == ["boom"]
        assert t.current() is None  # stack unwound

    def test_threads_get_independent_stacks(self):
        t = Tracer()
        done = threading.Event()

        def other():
            with t.span("other-root"):
                pass
            done.set()

        with t.span("main-root"):
            th = threading.Thread(target=other)
            th.start()
            th.join()
        assert done.wait(1)
        roots = {sp.name: sp.parent_id for sp in t.spans()}
        # The other thread's span is NOT a child of main's open span.
        assert roots == {"other-root": None, "main-root": None}

    def test_ring_is_bounded(self):
        t = Tracer(ring=4)
        for i in range(10):
            with t.span(f"s{i}"):
                pass
        assert [sp.name for sp in t.spans()] == ["s6", "s7", "s8", "s9"]


class TestChromeExport:
    def test_event_shape(self):
        t = Tracer()
        with t.span("outer", ftc="deployments.apps"):
            with t.span("inner"):
                pass
        doc = json.loads(t.chrome_trace_json())
        events = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
        assert set(events) == {"outer", "inner"}
        outer, inner = events["outer"], events["inner"]
        for e in (outer, inner):
            assert e["ph"] == "X"
            assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
            assert e["pid"] > 0 and e["tid"] > 0
        assert inner["args"]["parent_id"] == outer["args"]["span_id"]
        assert outer["args"]["ftc"] == "deployments.apps"
        # Nesting must also hold by timestamps (what chrome://tracing
        # actually renders): inner within [outer.ts, outer.ts+dur].
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3

    def test_thread_metadata_events(self):
        t = Tracer()
        with t.span("x"):
            pass
        doc = t.chrome_trace()
        meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert meta and meta[0]["name"] == "thread_name"
        assert doc["displayTimeUnit"] == "ms"


class TestDeviceLaneMerge:
    """The dispatch ledger's records render as their own per-device
    lanes in the Chrome trace (ISSUE 13 satellite): one trace load shows
    host spans + device timelines, correlated by tick id, on the shared
    trace epoch."""

    def test_ledger_records_become_device_lane_events(self):
        import jax.numpy as jnp

        from kubeadmiral_tpu.runtime import trace as trace_mod
        from kubeadmiral_tpu.runtime.devprof import DispatchLedger

        ledger = DispatchLedger(enabled=True, ring_ticks=4)
        tick = ledger.begin_tick(kind="test")
        out = jnp.arange(8) + 1
        ledger.observe("tick", out)
        ledger.end_tick({"device": 0.001})
        assert ledger.drain(5.0)

        events = ledger.chrome_events(trace_mod.epoch())
        slices = [e for e in events if e["ph"] == "X"]
        assert slices, "no device-lane slices exported"
        device_slice = next(e for e in slices if e["name"] == "tick")
        assert device_slice["args"]["tick"] == tick
        assert device_slice["args"]["shape"] == "8"
        assert device_slice["ts"] >= 0  # on the span tracer's epoch
        lanes = [
            e for e in events
            if e["ph"] == "M" and e["name"] == "thread_name"
        ]
        assert lanes and all(
            e["args"]["name"].startswith("device ") for e in lanes
        )
        # The lane tid is synthetic and shared between the slice and its
        # metadata row.
        assert device_slice["tid"] in {e["tid"] for e in lanes}

    def test_disabled_ledger_exports_nothing(self):
        from kubeadmiral_tpu.runtime import trace as trace_mod
        from kubeadmiral_tpu.runtime.devprof import DispatchLedger

        ledger = DispatchLedger(enabled=False)
        assert ledger.chrome_events(trace_mod.epoch()) == []
