"""The minimum end-to-end slice (SURVEY.md §7 step 3), driven the way the
reference's e2e resourcepropagation suite drives a real control plane
(reference: test/e2e/resourcepropagation/framework.go:91): create member
clusters + a source Deployment + a PropagationPolicy, run every
controller, and observe propagation, replica distribution and status.
"""

import dataclasses

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.clusterctl import (
    FEDERATED_CLUSTERS,
    FederatedClusterController,
    NODES,
)
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.federation.sync import SyncController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
from kubeadmiral_tpu.testing.fakekube import ClusterFleet


def deployment_ftc(pipeline=None):
    ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
    if pipeline is not None:
        ftc = dataclasses.replace(ftc, controllers=pipeline)
    return ftc


def make_node(name, cpu, memory):
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name},
        "spec": {},
        "status": {
            "allocatable": {"cpu": cpu, "memory": memory},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def make_deployment(name="web", replicas=9, labels=None):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": name,
            "namespace": "default",
            "labels": labels or {"kubeadmiral.io/propagation-policy-name": "pp"},
        },
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "image": "nginx",
                            "resources": {"requests": {"cpu": "100m"}},
                        }
                    ]
                },
            },
        },
    }


def settle(*controllers, rounds=20):
    for _ in range(rounds):
        progressed = False
        for c in controllers:
            progressed |= c.worker.step()
        if not progressed:
            return


class TestEndToEndSlice:
    # Hooks overridden by the real-transport variant (test_e2e_http.py):
    # the same tests run over in-process FakeKube and over HTTP apiservers.
    def make_fleet(self):
        return ClusterFleet()

    def add_member(self, name):
        return self.fleet.add_member(name)

    def cluster_spec(self, name) -> dict:
        return {}

    def settle(self, *controllers, rounds=20):
        settle(*controllers, rounds=rounds)

    def setup_method(self):
        # Scheduler-only pipeline: the override controller doesn't run in
        # this slice, so it must not gate sync.
        self.ftc = deployment_ftc(
            pipeline=(("kubeadmiral.io/global-scheduler",),)
        )
        self.fleet = self.make_fleet()
        gvk = "apps/v1/Deployment"
        self.clusterctl = FederatedClusterController(
            self.fleet, api_resource_probe=[gvk]
        )
        self.federate = FederateController(self.fleet.host, self.ftc)
        self.scheduler = SchedulerController(self.fleet.host, self.ftc)
        self.sync = SyncController(self.fleet, self.ftc)

        for name, cpu in (("c1", "64"), ("c2", "32"), ("c3", "32")):
            member = self.add_member(name)
            member.create(NODES, make_node("n1", cpu, "128Gi"))
            self.fleet.host.create(
                FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": self.cluster_spec(name),
                },
            )
        self.fleet.host.create(
            PROPAGATION_POLICIES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "PropagationPolicy",
                "metadata": {"name": "pp", "namespace": "default"},
                "spec": {"schedulingMode": "Divide"},
            },
        )

    def everything(self):
        return (self.clusterctl, self.federate, self.scheduler, self.sync)

    def test_deployment_propagates_with_divided_replicas(self):
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        self.settle(*self.everything())

        fed = self.fleet.host.get(self.ftc.federated.resource, "default/web")
        placed = C.get_placement(fed, C.SCHEDULER)
        assert placed == {"c1", "c2", "c3"}

        total = 0
        for name in ("c1", "c2", "c3"):
            obj = self.fleet.member(name).get(
                self.ftc.source.resource, "default/web"
            )
            assert obj["metadata"]["labels"][C.MANAGED_LABEL] == "true"
            total += obj["spec"]["replicas"]
        assert total == 9

        status = {c["cluster"]: c["status"] for c in fed["status"]["clusters"]}
        assert status == {"c1": "OK", "c2": "OK", "c3": "OK"}

    def test_source_feedback_annotations(self):
        """Scheduling + syncing feedback lands on the source object
        (sourcefeedback/scheduling.go, syncing.go; federate
        controller.go:485-494)."""
        import json

        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        self.settle(*self.everything())
        src = self.fleet.host.get(self.ftc.source.resource, "default/web")
        ann = src["metadata"]["annotations"]
        scheduling = json.loads(ann[C.SOURCE_FEEDBACK_SCHEDULING])
        assert scheduling["placement"] == ["c1", "c2", "c3"]
        syncing = json.loads(ann[C.SOURCE_FEEDBACK_SYNCING])
        assert [c["name"] for c in syncing["clusters"]] == ["c1", "c2", "c3"]
        assert all(c["status"] == "OK" for c in syncing["clusters"])

    def test_source_update_rolls_through(self):
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        self.settle(*self.everything())
        src = self.fleet.host.get(self.ftc.source.resource, "default/web")
        src["spec"]["replicas"] = 15
        src["spec"]["template"]["spec"]["containers"][0]["image"] = "nginx:2"
        self.fleet.host.update(self.ftc.source.resource, src)
        self.settle(*self.everything())

        total = 0
        for name in ("c1", "c2", "c3"):
            obj = self.fleet.member(name).get(
                self.ftc.source.resource, "default/web"
            )
            assert obj["spec"]["template"]["spec"]["containers"][0]["image"] == (
                "nginx:2"
            )
            total += obj["spec"]["replicas"]
        assert total == 15

    def test_source_delete_cascades_everywhere(self):
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        self.settle(*self.everything())
        self.fleet.host.delete(self.ftc.source.resource, "default/web")
        self.settle(*self.everything(), rounds=40)

        assert self.fleet.host.try_get(self.ftc.source.resource, "default/web") is None
        assert (
            self.fleet.host.try_get(self.ftc.federated.resource, "default/web")
            is None
        )
        for name in ("c1", "c2", "c3"):
            assert (
                self.fleet.member(name).try_get(
                    self.ftc.source.resource, "default/web"
                )
                is None
            )


def make_job(name="batch-job", labels=None):
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {
            "name": name,
            "namespace": "default",
            "labels": labels or {"kubeadmiral.io/propagation-policy-name": "pp-dup"},
        },
        "spec": {
            "template": {
                "metadata": {"labels": {"job-name": name}},
                "spec": {
                    "containers": [{"name": "c", "image": "busybox"}],
                    "restartPolicy": "Never",
                },
            },
        },
    }


def make_cronjob(name="nightly", labels=None):
    return {
        "apiVersion": "batch/v1",
        "kind": "CronJob",
        "metadata": {
            "name": name,
            "namespace": "default",
            "labels": labels or {"kubeadmiral.io/propagation-policy-name": "pp-dup"},
        },
        "spec": {
            "schedule": "0 3 * * *",
            "jobTemplate": {
                "spec": {
                    "template": {
                        "spec": {
                            "containers": [{"name": "c", "image": "busybox"}],
                            "restartPolicy": "Never",
                        }
                    }
                }
            },
        },
    }


def make_configmap(name="settings", labels=None):
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": name,
            "namespace": "default",
            "labels": labels or {"kubeadmiral.io/propagation-policy-name": "pp-dup"},
        },
        "data": {"feature": "on", "level": "7"},
    }


class TestMultiKindPropagation:
    """The reference's generic propagation suite parameterized over
    workload kinds (test/e2e/resourcepropagation/framework.go:91 runs
    resourcePropagationTest[T] for Deployments, Jobs and CronJobs):
    create source + policy, run the controllers, observe the object in
    every member, collect status where the FTC enables it, then delete
    and observe cascade.  Overridden hooks (make_fleet/add_member/...)
    let the HTTP transport variant run the same tests over sockets."""

    KINDS = {
        "jobs.batch": make_job,
        "cronjobs.batch": make_cronjob,
        "configmaps": make_configmap,
    }

    def make_fleet(self):
        return ClusterFleet()

    def add_member(self, name):
        return self.fleet.add_member(name)

    def cluster_spec(self, name) -> dict:
        return {}

    def settle(self, *controllers, rounds=30):
        settle(*controllers, rounds=rounds)

    def setup_method(self):
        import dataclasses as _dc

        self.fleet = self.make_fleet()
        self.ftcs = {}
        for ftc in default_ftcs():
            if ftc.name in self.KINDS:
                self.ftcs[ftc.name] = _dc.replace(
                    ftc, controllers=(("kubeadmiral.io/global-scheduler",),)
                )
        gvks = ["batch/v1/Job", "batch/v1/CronJob", "v1/ConfigMap"]
        self.clusterctl = FederatedClusterController(
            self.fleet, api_resource_probe=gvks
        )
        for name in ("c1", "c2", "c3"):
            member = self.add_member(name)
            member.create(NODES, make_node("n1", "32", "64Gi"))
            self.fleet.host.create(
                FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": self.cluster_spec(name),
                },
            )
        self.fleet.host.create(
            PROPAGATION_POLICIES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "PropagationPolicy",
                "metadata": {"name": "pp-dup", "namespace": "default"},
                "spec": {"schedulingMode": "Duplicate"},
            },
        )

    def controllers_for(self, ftc):
        federate = FederateController(self.fleet.host, ftc)
        scheduler = SchedulerController(self.fleet.host, ftc)
        sync = SyncController(self.fleet, ftc)
        return federate, scheduler, sync

    def run_kind(self, ftc_name):
        ftc = self.ftcs[ftc_name]
        source = self.KINDS[ftc_name]()
        federate, scheduler, sync = self.controllers_for(ftc)
        self.fleet.host.create(ftc.source.resource, source)
        self.settle(self.clusterctl, federate, scheduler, sync)
        key = "default/" + source["metadata"]["name"]
        # Propagated to every member, managed-labeled, spec intact.
        for cname in ("c1", "c2", "c3"):
            got = self.fleet.member(cname).get(ftc.source.resource, key)
            assert got["metadata"]["labels"][C.MANAGED_LABEL] == "true"
            if "data" in source:
                assert got["data"] == source["data"]
            else:
                assert got["spec"] is not None and got["spec"] != {}
        return ftc, source, key, (federate, scheduler, sync)

    def test_job_propagates_and_collects_status(self):
        ftc, source, key, ctls = self.run_kind("jobs.batch")
        # Members report Job progress; the status controller collects it
        # into the FederatedJobStatus CR (statusCollection fields).
        from kubeadmiral_tpu.federation.statusctl import StatusController

        status = StatusController(self.fleet, ftc)
        for i, cname in enumerate(("c1", "c2", "c3")):
            member = self.fleet.member(cname)
            obj = member.get(ftc.source.resource, key)
            obj["status"] = {"succeeded": i, "active": 1}
            member.update_status(ftc.source.resource, obj)
        self.settle(*ctls, status)
        collected = self.fleet.host.get(ftc.status.resource, key)
        by_cluster = {
            c["clusterName"]: c for c in collected["clusterStatus"]
        }
        assert set(by_cluster) == {"c1", "c2", "c3"}
        assert by_cluster["c3"]["collectedFields"]["status"]["succeeded"] == 2

    def test_cronjob_propagates(self):
        ftc, source, key, ctls = self.run_kind("cronjobs.batch")
        got = self.fleet.member("c2").get(ftc.source.resource, key)
        assert got["spec"]["schedule"] == "0 3 * * *"

    def test_configmap_propagates_and_deletes(self):
        ftc, source, key, ctls = self.run_kind("configmaps")
        self.fleet.host.delete(ftc.source.resource, key)
        self.settle(self.clusterctl, *ctls)
        for cname in ("c1", "c2", "c3"):
            assert self.fleet.member(cname).try_get(ftc.source.resource, key) is None
        assert self.fleet.host.try_get(ftc.federated.resource, key) is None
