import numpy as np

from kubeadmiral_tpu.utils.hashing import (
    fnv32,
    fnv32a,
    fnv32_batch,
    fnv32_extend,
    stable_json_hash,
    uint32_to_sortable_int32,
)

# Published FNV test vectors (Landon Curt Noll's reference tables).
KNOWN_FNV1 = {b"": 0x811C9DC5, b"a": 0x050C5D7E, b"foobar": 0x31F0B262}
KNOWN_FNV1A = {b"": 0x811C9DC5, b"a": 0xE40C292C, b"foobar": 0xBF9CF968}


def test_fnv1_known_vectors():
    for data, want in KNOWN_FNV1.items():
        assert fnv32(data) == want, data


def test_fnv1a_known_vectors():
    for data, want in KNOWN_FNV1A.items():
        assert fnv32a(data) == want, data


def test_batch_matches_scalar():
    names = ["cluster-1", "cluster-2", "zz"]
    key = "ns/name"
    got = fnv32_batch(names, key)
    assert got.dtype == np.uint32
    for i, n in enumerate(names):
        assert int(got[i]) == fnv32((n + key).encode())


def test_extend_is_streaming():
    state = fnv32(b"abc")
    assert fnv32_extend(state, b"def") == fnv32(b"abcdef")
    states = np.array([fnv32(b"x"), fnv32(b"y")], dtype=np.uint32)
    ext = fnv32_extend(states, b"suffix")
    assert int(ext[0]) == fnv32(b"xsuffix")
    assert int(ext[1]) == fnv32(b"ysuffix")


def test_sortable_int32_preserves_order():
    vals = np.array([0, 1, 2**31 - 1, 2**31, 2**32 - 1], dtype=np.uint32)
    mapped = uint32_to_sortable_int32(vals)
    assert mapped.dtype == np.int32
    assert list(np.argsort(mapped, kind="stable")) == list(range(len(vals)))


def test_stable_json_hash_order_independent():
    a = stable_json_hash({"b": 1, "a": [1, 2]})
    b = stable_json_hash({"a": [1, 2], "b": 1})
    assert a == b
    assert a != stable_json_hash({"a": [2, 1], "b": 1})


def test_stable_json_hash_sets_canonicalized():
    a = stable_json_hash({"s": {"b", "a", "c"}})
    b = stable_json_hash({"s": {"c", "a", "b"}})
    assert a == b == stable_json_hash({"s": ["a", "b", "c"]})


def test_stable_json_hash_rejects_unstable_types():
    import pytest

    with pytest.raises(TypeError):
        stable_json_hash({"x": object()})
