"""Shard-router seam (federation/shardmap.py; ISSUE 18 satellite).

The seam ships with shard_count=1 (identity routing) but the routing
properties the eventual N-replica deployment depends on are pinned NOW:

* process-stable hashing — BLAKE2b digests and shard assignments are
  hardcoded here so a routing change across restarts/upgrades fails
  loudly (Python's builtin ``hash`` is per-process salted and would
  pass a same-process round-trip test while breaking failover);
* uniform spread at 1/2/8 shards;
* jump consistent hashing moves only ~1/(N+1) of keys when a shard is
  added, always onto the new shard;
* the informer/worker boundary (runtime/worker.py) drops keys this
  replica does not own, for single keys, relists, and batched enqueues
  alike.
"""

from __future__ import annotations

from collections import Counter

import pytest

from kubeadmiral_tpu.federation import shardmap as SM
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import BatchWorker, Worker


@pytest.fixture(autouse=True)
def _restore_default():
    """Tests that install a process-default ShardMap must not leak it
    into the rest of the suite (worker construction consults it)."""
    prev = SM.set_default(SM.ShardMap(shard_count=1, shard_index=0))
    try:
        yield
    finally:
        SM.set_default(prev or SM.ShardMap(shard_count=1, shard_index=0))


class TestStableHashing:
    # Hardcoded expectations: if these move, every deployed replica
    # re-routes its keyspace on upgrade (relist storm + split-brain
    # ownership during rollout).  Changing the hash is a migration,
    # not a refactor.
    DIGESTS = {
        "default/web-0": 6683436237858405042,
        "default/web-1": 14565532090106758111,
        "kube-system/coredns": 1657200717086694278,
        "prod/api-42": 10283160909301220081,
        "a": 4681665781835383343,
    }
    SHARDS_8 = {
        "default/web-0": 6,
        "default/web-1": 6,
        "kube-system/coredns": 4,
        "prod/api-42": 1,
        "a": 6,
    }

    def test_digest_is_pinned(self):
        for key, want in self.DIGESTS.items():
            assert SM.key_digest(key) == want, key

    def test_shard_assignment_is_pinned(self):
        m = SM.ShardMap(shard_count=8, shard_index=0)
        for key, want in self.SHARDS_8.items():
            assert m.shard_of(key) == want, key

    def test_two_maps_agree(self):
        """A restarted replica (fresh ShardMap) routes identically."""
        a = SM.ShardMap(shard_count=8, shard_index=3)
        b = SM.ShardMap(shard_count=8, shard_index=3)
        keys = [f"ns-{i % 5}/obj-{i:04d}" for i in range(500)]
        assert [a.shard_of(k) for k in keys] == [b.shard_of(k) for k in keys]


class TestSpreadAndMovement:
    KEYS = [f"ns-{i % 7}/obj-{i:05d}" for i in range(5000)]

    def test_identity_at_one_shard(self):
        m = SM.ShardMap(shard_count=1, shard_index=0)
        assert all(m.shard_of(k) == 0 for k in self.KEYS[:200])
        assert all(m.owns(k) for k in self.KEYS[:200])

    @pytest.mark.parametrize("count", [2, 8])
    def test_uniform_spread(self, count):
        m = SM.ShardMap(shard_count=count, shard_index=0)
        spread = Counter(m.shard_of(k) for k in self.KEYS)
        assert set(spread) == set(range(count))
        ideal = len(self.KEYS) / count
        for shard, n in spread.items():
            assert abs(n - ideal) < 0.15 * ideal, (shard, n, ideal)

    def test_every_key_owned_by_exactly_one_shard(self):
        maps = [SM.ShardMap(shard_count=8, shard_index=i) for i in range(8)]
        for k in self.KEYS[:500]:
            assert sum(m.owns(k) for m in maps) == 1, k

    def test_jump_hash_minimal_movement(self):
        """Growing 8 → 9 shards moves ~1/9 of keys, all onto shard 8."""
        moved = 0
        for k in self.KEYS:
            before = SM.jump_hash(SM.key_digest(k), 8)
            after = SM.jump_hash(SM.key_digest(k), 9)
            if before != after:
                moved += 1
                assert after == 8, k  # only ever onto the NEW shard
        frac = moved / len(self.KEYS)
        assert 0.06 < frac < 0.17, frac  # ~1/9 ± sampling noise


class TestKnobsAndDefault:
    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv("KT_SHARD_COUNT", "4")
        monkeypatch.setenv("KT_SHARD_INDEX", "2")
        m = SM.ShardMap()
        assert (m.shard_count, m.shard_index) == (4, 2)

    def test_clamping(self):
        assert SM.ShardMap(shard_count=0, shard_index=5).shard_count == 1
        assert SM.ShardMap(shard_count=0, shard_index=5).shard_index == 0
        assert SM.ShardMap(shard_count=4, shard_index=99).shard_index == 3
        assert SM.ShardMap(shard_count=4, shard_index=-1).shard_index == 0

    def test_default_lifecycle(self, monkeypatch):
        prev = SM.set_default(SM.ShardMap(shard_count=2, shard_index=1))
        assert prev is not None
        assert SM.get_default().shard_count == 2
        monkeypatch.setenv("KT_SHARD_COUNT", "8")
        monkeypatch.setenv("KT_SHARD_INDEX", "5")
        fresh = SM.reset_default()
        assert (fresh.shard_count, fresh.shard_index) == (8, 5)
        assert SM.get_default() is fresh


class TestWorkerBoundary:
    """runtime/worker.py consults the default map on every intake path."""

    def _split(self, count=2):
        keys = [f"d/k-{i:03d}" for i in range(40)]
        probe = SM.ShardMap(shard_count=count, shard_index=0)
        mine = [k for k in keys if probe.owns(k)]
        theirs = [k for k in keys if not probe.owns(k)]
        assert mine and theirs  # the split is non-trivial
        return keys, mine, theirs

    def test_enqueue_drops_foreign_keys(self):
        keys, mine, _ = self._split()
        SM.set_default(SM.ShardMap(shard_count=2, shard_index=0))
        w = Worker("shard-test", lambda k: None)
        for k in keys:
            w.enqueue(k)
        assert sorted(w.queue.drain_due()) == sorted(mine)

    def test_enqueue_all_filters_relists(self):
        keys, mine, _ = self._split()
        SM.set_default(SM.ShardMap(shard_count=2, shard_index=0))
        w = Worker("shard-test", lambda k: None)
        w.enqueue_all(keys)
        assert sorted(w.queue.drain_due()) == sorted(mine)

    def test_enqueue_many_filters_batches(self):
        keys, mine, _ = self._split()
        SM.set_default(SM.ShardMap(shard_count=2, shard_index=0))
        w = BatchWorker("shard-test", lambda ks: {}, metrics=Metrics())
        w.enqueue_many(keys)
        assert sorted(w.queue.drain_due()) == sorted(mine)

    def test_single_shard_accepts_everything(self):
        SM.set_default(SM.ShardMap(shard_count=1, shard_index=0))
        w = Worker("shard-test", lambda k: None)
        keys = [f"d/k-{i}" for i in range(25)]
        w.enqueue_all(keys)
        assert sorted(w.queue.drain_due()) == sorted(keys)


class TestLiveResize:
    """The 8→9 live resize at the WORKER boundary (ISSUE 20): jump
    hashing hands ~1/9 of the keyspace to the new shard and nothing
    else moves, the handoff set re-enqueues on exactly one new owner,
    and ownership stays a partition (no key double-owned, none lost)
    in both the old and the new generation."""

    KEYS = [f"ns-{i % 7}/obj-{i:04d}" for i in range(1800)]

    def _drain_partition(self, maps):
        """Build one worker per map under its scope, feed EVERY key to
        every worker (the relist/watch firehose), return per-shard
        drained sets."""
        drained = []
        for m in maps:
            with SM.scoped(m):
                w = Worker("resize-test", lambda k: None)
            w.enqueue_all(self.KEYS)
            drained.append(set(w.queue.drain_due()))
        return drained

    def test_resize_8_to_9_at_worker_boundary(self):
        old = [SM.ShardMap(shard_count=8, shard_index=i) for i in range(8)]
        before = self._drain_partition(old)
        # Old generation: a partition — every key owned exactly once.
        assert set().union(*before) == set(self.KEYS)
        assert sum(len(s) for s in before) == len(self.KEYS)

        new = [m.resize(9) for m in old] + [
            SM.ShardMap(shard_count=9, shard_index=8, epoch=old[0].epoch + 1)
        ]
        assert all(m.epoch == old[0].epoch + 1 for m in new[:8])

        # The handoff set: ~1/9 of keys, pairwise disjoint across old
        # owners (each moved key re-enqueues from exactly one replica),
        # and every moved key lands on the NEW shard — jump hashing
        # never shuffles keys between surviving shards.
        moved_per_shard = [m.moved_keys(self.KEYS, m.resize(9)) for m in old]
        moved = [k for ms in moved_per_shard for k in ms]
        assert len(moved) == len(set(moved))
        frac = len(moved) / len(self.KEYS)
        assert 0.5 / 9 < frac < 2.0 / 9, frac
        assert all(new[8].owns(k) for k in moved)

        after = self._drain_partition(new)
        # New generation: still a partition.
        assert set().union(*after) == set(self.KEYS)
        assert sum(len(s) for s in after) == len(self.KEYS)
        # Unmoved keys stayed with their shard; the new shard drained
        # EXACTLY the handoff set — so during the epoch bump a key is
        # owned by its old shard or the new one, never both.
        assert after[8] == set(moved)
        for i in range(8):
            assert before[i] - set(moved) == after[i]

    def test_broadcast_keys_never_move(self):
        old = SM.ShardMap(shard_count=8, shard_index=3)
        keys = ["cluster::m-1", "cluster::m-2", "default/web-1"]
        assert "cluster::m-1" not in old.moved_keys(keys, old.resize(9))
        assert "cluster::m-2" not in old.moved_keys(keys, old.resize(9))
