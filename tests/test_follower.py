"""Follower controller: leader↔follower inference, spec.follows, and
placement union (reference: pkg/controllers/follower)."""

import json

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.follower import (
    ENABLE_FOLLOWER_SCHEDULING,
    FOLLOWERS_ANNOTATION,
    FollowerController,
    followers_from_pod_spec,
)
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.testing.fakekube import FakeKube


def ftc_by_name(name):
    return next(f for f in default_ftcs() if f.name == name)


POD_SPEC = {
    "serviceAccountName": "runner",
    "imagePullSecrets": [{"name": "pull-secret"}],
    "containers": [
        {
            "name": "app",
            "envFrom": [{"configMapRef": {"name": "app-config"}}],
            "env": [
                {
                    "name": "TOKEN",
                    "valueFrom": {"secretKeyRef": {"name": "app-token", "key": "t"}},
                }
            ],
        }
    ],
    "volumes": [
        {"name": "v1", "configMap": {"name": "vol-config"}},
        {"name": "v2", "secret": {"secretName": "vol-secret"}},
        {"name": "v3", "persistentVolumeClaim": {"claimName": "data"}},
    ],
}


class TestInference:
    def test_pod_spec_followers(self):
        refs = followers_from_pod_spec(POD_SPEC, "ns1")
        assert ("/ServiceAccount", "ns1", "runner") in refs
        assert ("/Secret", "ns1", "pull-secret") in refs
        assert ("/Secret", "ns1", "app-token") in refs
        assert ("/Secret", "ns1", "vol-secret") in refs
        assert ("/ConfigMap", "ns1", "app-config") in refs
        assert ("/ConfigMap", "ns1", "vol-config") in refs
        assert ("/PersistentVolumeClaim", "ns1", "data") in refs


def make_fed_deployment(name="web", pod_spec=None, followers_ann=None, placed=("c1",)):
    ann = {
        pending.PENDING_CONTROLLERS: json.dumps([]),
        ENABLE_FOLLOWER_SCHEDULING: "true",
    }
    if followers_ann is not None:
        ann[FOLLOWERS_ANNOTATION] = json.dumps(followers_ann)
    return {
        "apiVersion": "types.kubeadmiral.io/v1alpha1",
        "kind": "FederatedDeployment",
        "metadata": {"name": name, "namespace": "default", "annotations": ann},
        "spec": {
            "template": {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "spec": {"template": {"spec": pod_spec or POD_SPEC}},
            },
            "placements": [
                {
                    "controller": C.SCHEDULER,
                    "placement": [{"cluster": c} for c in placed],
                }
            ],
        },
    }


def make_fed_configmap(name, namespace="default"):
    return {
        "apiVersion": "types.kubeadmiral.io/v1alpha1",
        "kind": "FederatedConfigMap",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "annotations": {pending.PENDING_CONTROLLERS: json.dumps([])},
        },
        "spec": {"template": {"apiVersion": "v1", "kind": "ConfigMap"}},
    }


class TestFollowerController:
    def setup_method(self):
        self.kube = FakeKube()
        self.ftcs = default_ftcs()
        self.ctl = FollowerController(self.kube, self.ftcs)
        self.dep_res = ftc_by_name("deployments.apps").federated.resource
        self.cm_res = ftc_by_name("configmaps").federated.resource

    def test_follower_gets_leader_placement(self):
        self.kube.create(self.cm_res, make_fed_configmap("vol-config"))
        self.kube.create(self.dep_res, make_fed_deployment(placed=("c1", "c2")))
        self.ctl.run_until_idle()

        cm = self.kube.get(self.cm_res, "default/vol-config")
        follows = cm["spec"]["follows"]
        assert follows == [{"group": "apps", "kind": "Deployment", "name": "web"}]
        assert C.get_placement(cm, C.FOLLOWER_CONTROLLER) == {"c1", "c2"}

    def test_leader_deletion_releases_follower(self):
        self.kube.create(self.cm_res, make_fed_configmap("vol-config"))
        self.kube.create(self.dep_res, make_fed_deployment())
        self.ctl.run_until_idle()
        self.kube.delete(self.dep_res, "default/web")
        self.ctl.run_until_idle()
        cm = self.kube.get(self.cm_res, "default/vol-config")
        assert cm["spec"]["follows"] == []
        assert C.get_placement(cm, C.FOLLOWER_CONTROLLER) == set()

    def test_followers_annotation(self):
        self.kube.create(self.cm_res, make_fed_configmap("extra"))
        self.kube.create(
            self.dep_res,
            make_fed_deployment(
                pod_spec={"containers": []},
                followers_ann=[{"group": "", "kind": "ConfigMap", "name": "extra"}],
            ),
        )
        self.ctl.run_until_idle()
        cm = self.kube.get(self.cm_res, "default/extra")
        assert C.get_placement(cm, C.FOLLOWER_CONTROLLER) == {"c1"}

    def test_disabled_follower_scheduling_infers_nothing(self):
        self.kube.create(self.cm_res, make_fed_configmap("vol-config"))
        fed = make_fed_deployment()
        fed["metadata"]["annotations"][ENABLE_FOLLOWER_SCHEDULING] = "false"
        self.kube.create(self.dep_res, fed)
        self.ctl.run_until_idle()
        cm = self.kube.get(self.cm_res, "default/vol-config")
        assert not C.get_placement(cm, C.FOLLOWER_CONTROLLER)

    def test_two_leaders_union_placement(self):
        self.kube.create(self.cm_res, make_fed_configmap("vol-config"))
        self.kube.create(self.dep_res, make_fed_deployment("web1", placed=("c1",)))
        self.kube.create(self.dep_res, make_fed_deployment("web2", placed=("c2",)))
        self.ctl.run_until_idle()
        cm = self.kube.get(self.cm_res, "default/vol-config")
        assert C.get_placement(cm, C.FOLLOWER_CONTROLLER) == {"c1", "c2"}
        assert len(cm["spec"]["follows"]) == 2

    def test_leader_rescale_updates_follower(self):
        self.kube.create(self.cm_res, make_fed_configmap("vol-config"))
        self.kube.create(self.dep_res, make_fed_deployment(placed=("c1",)))
        self.ctl.run_until_idle()
        fed = self.kube.get(self.dep_res, "default/web")
        C.set_placement(fed, C.SCHEDULER, {"c2", "c3"})
        self.kube.update(self.dep_res, fed)
        self.ctl.run_until_idle()
        cm = self.kube.get(self.cm_res, "default/vol-config")
        assert C.get_placement(cm, C.FOLLOWER_CONTROLLER) == {"c2", "c3"}

    def test_leader_pipeline_consumed(self):
        fed = make_fed_deployment()
        fed["metadata"]["annotations"][pending.PENDING_CONTROLLERS] = json.dumps(
            [[C.FOLLOWER_CONTROLLER]]
        )
        self.kube.create(self.dep_res, fed)
        self.ctl.run_until_idle()
        fed = self.kube.get(self.dep_res, "default/web")
        assert pending.get_pending(fed) == []
