"""Sharded-vs-unsharded parity for the fused scheduling tick.

The tick must produce elementwise-identical outputs regardless of the
mesh layout: fully replicated (1x1), object-parallel, cluster-parallel
(which turns score normalization maxima, top-K select and the planner's
cluster-axis scans into XLA collectives), and mixed 2-D meshes.  This is
the multi-chip correctness gate: the same program the driver dry-runs
via ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

import pathlib
import sys

import numpy as np
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax  # noqa: E402  (after conftest env setup)

from kubeadmiral_tpu.ops.pipeline import schedule_tick  # noqa: E402
from kubeadmiral_tpu.parallel import mesh as M  # noqa: E402

from __graft_entry__ import _example_batch  # noqa: E402


@pytest.fixture(scope="module")
def batch():
    # 32x16 divides evenly by every mesh axis below, and mixes
    # Duplicate/Divide modes, taints, affinity, capacity caps and
    # avoidDisruption so planner tie-breaks cross shard boundaries.
    return _example_batch(b=32, c=16)


@pytest.fixture(scope="module")
def unsharded(batch):
    return schedule_tick(batch)


def _assert_sharded_matches(batch, unsharded, objects_axis, clusters_axis):
    """One sharded-vs-unsharded parity harness shared by every shape and
    mesh layout below (and mirrored by dryrun_multichip's large case)."""
    n = objects_axis * clusters_axis
    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"need {n} devices, have {len(devices)}")
    mesh = M.make_mesh(devices[:n], objects_axis=objects_axis)
    assert mesh.devices.shape == (objects_axis, clusters_axis)

    sharded_in = M.shard_inputs(batch, mesh)
    tick = jax.jit(
        schedule_tick.__wrapped__,
        in_shardings=(M.input_shardings(mesh),),
        out_shardings=M.output_shardings(mesh),
    )
    out = tick(sharded_in)
    for name in unsharded._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(out, name)),
            np.asarray(getattr(unsharded, name)),
            err_msg=f"field {name} diverges on mesh "
            f"{objects_axis}x{clusters_axis}",
        )


@pytest.mark.parametrize(
    "objects_axis,clusters_axis",
    [(1, 1), (4, 2), (2, 4), (8, 1), (1, 8)],
)
def test_sharded_tick_matches_unsharded(
    batch, unsharded, objects_axis, clusters_axis
):
    _assert_sharded_matches(batch, unsharded, objects_axis, clusters_axis)


def test_make_mesh_default_layout():
    devices = jax.devices()
    mesh = M.make_mesh(devices)
    assert mesh.axis_names == (M.OBJECTS, M.CLUSTERS)
    assert mesh.devices.size == len(devices)


def test_sharded_tick_matches_unsharded_with_volume():
    """Cluster-axis collectives (normalize maxima, top-K, planner scans)
    with real per-shard volume: 512x128 over the full 8-device mesh —
    the CI-sized sibling of the dryrun's 2048x512 case (VERDICT r3 #5)."""
    batch = _example_batch(b=512, c=128)
    unsharded = schedule_tick(batch)
    assert int(np.asarray(unsharded.selected).sum()) > 0
    _assert_sharded_matches(batch, unsharded, 4, 2)
