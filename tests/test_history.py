"""Revision history: ControllerRevisions + sync wiring
(reference: pkg/controllers/sync/history.go)."""

from kubeadmiral_tpu.federation.history import (
    CONTROLLER_REVISIONS,
    LAST_REVISION_ANNOTATION,
    RevisionManager,
    _revision_name,
)
from kubeadmiral_tpu.federation.retain import CURRENT_REVISION_ANNOTATION
from kubeadmiral_tpu.testing.fakekube import FakeKube

from test_e2e_slice import TestEndToEndSlice, make_deployment, settle


def make_fed(image="nginx:1", history_limit=None, uid="u1"):
    obj = {
        "apiVersion": "types.kubeadmiral.io/v1alpha1",
        "kind": "FederatedDeployment",
        "metadata": {
            "name": "web",
            "namespace": "default",
            "uid": uid,
            "labels": {"app": "web"},
        },
        "spec": {
            "template": {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "spec": {
                    "template": {
                        "metadata": {"labels": {"app": "web"}},
                        "spec": {"containers": [{"name": "c", "image": image}]},
                    }
                },
            }
        },
        "status": {},
    }
    if history_limit is not None:
        obj["spec"]["revisionHistoryLimit"] = history_limit
    return obj


class TestRevisionManager:
    def setup_method(self):
        self.host = FakeKube()
        self.mgr = RevisionManager(self.host)

    def revisions(self):
        return sorted(
            self.host.list(CONTROLLER_REVISIONS), key=lambda r: r["revision"]
        )

    def test_first_sync_creates_revision_one(self):
        collision, last, current = self.mgr.sync_revisions(make_fed())
        assert collision == 0
        assert last == ""
        revs = self.revisions()
        assert len(revs) == 1
        assert revs[0]["revision"] == 1
        assert revs[0]["metadata"]["name"] == current
        assert revs[0]["metadata"]["labels"]["uid"] == "u1"
        assert revs[0]["data"][0]["path"] == "/spec/template/spec/template"

    def test_same_template_is_deduplicated(self):
        self.mgr.sync_revisions(make_fed())
        _, _, current = self.mgr.sync_revisions(make_fed())
        assert len(self.revisions()) == 1
        assert self.revisions()[0]["metadata"]["name"] == current

    def test_template_change_bumps_revision_and_reports_last(self):
        _, _, first = self.mgr.sync_revisions(make_fed("nginx:1"))
        _, last, second = self.mgr.sync_revisions(make_fed("nginx:2"))
        revs = self.revisions()
        assert [r["revision"] for r in revs] == [1, 2]
        assert second != first
        assert last.startswith(first + "|")

    def test_rollback_renumbers_old_revision(self):
        _, _, first = self.mgr.sync_revisions(make_fed("nginx:1"))
        self.mgr.sync_revisions(make_fed("nginx:2"))
        # Roll back to the original template: its revision becomes newest.
        _, last, current = self.mgr.sync_revisions(make_fed("nginx:1"))
        assert current == first
        by_name = {r["metadata"]["name"]: r["revision"] for r in self.revisions()}
        assert by_name[first] == 3
        assert len(by_name) == 2

    def test_history_truncated_to_limit(self):
        for i in range(5):
            self.mgr.sync_revisions(make_fed(f"nginx:{i}", history_limit=2))
        revs = self.revisions()
        # 2 old + the current one survive.
        assert len(revs) == 3
        assert [r["revision"] for r in revs] == [3, 4, 5]

    def test_history_limit_zero_keeps_no_old_revisions(self):
        self.mgr.sync_revisions(make_fed("nginx:1", history_limit=0))
        _, last, _ = self.mgr.sync_revisions(make_fed("nginx:2", history_limit=0))
        assert last == ""
        assert [r["revision"] for r in self.revisions()] == [2]

    def test_owner_label_named_uid_does_not_break_ownership(self):
        fed = make_fed("nginx:1")
        fed["metadata"]["labels"]["uid"] = "liar"
        self.mgr.sync_revisions(fed)
        self.mgr.sync_revisions(fed)
        revs = self.revisions()
        assert len(revs) == 1
        assert revs[0]["metadata"]["labels"]["uid"] == "u1"

    def test_name_collision_bumps_collision_count(self):
        fed = make_fed("nginx:1")
        colliding_name = _revision_name("web",
            [{"op": "replace", "path": "/spec/template/spec/template",
              "value": fed["spec"]["template"]["spec"]["template"]}], 0)
        # A pre-existing revision with the colliding name but different
        # data forces the collision-count retry.
        self.host.create(
            CONTROLLER_REVISIONS,
            {
                "apiVersion": "apps/v1",
                "kind": "ControllerRevision",
                "metadata": {
                    "name": colliding_name,
                    "namespace": "default",
                    "labels": {"uid": "someone-else"},
                },
                "data": [{"op": "replace", "path": "/x", "value": 1}],
                "revision": 9,
            },
        )
        collision, _, current = self.mgr.sync_revisions(fed)
        assert collision == 1
        assert current != colliding_name


class TestSyncRevisionWiring(TestEndToEndSlice):
    """The deployments FTC has revisionHistory enabled: propagation must
    record revisions and annotate objects (controller.go:399-418)."""

    def test_revisions_recorded_through_sync(self):
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        settle(*self.everything())
        fed = self.fleet.host.get(self.ftc.federated.resource, "default/web")
        ann = fed["metadata"]["annotations"]
        current = ann[CURRENT_REVISION_ANNOTATION]
        revs = self.fleet.host.list(CONTROLLER_REVISIONS)
        assert [r["metadata"]["name"] for r in revs] == [current]
        assert fed["status"].get("collisionCount") == 0

        # Member objects carry the current-revision annotation for the
        # rollout planner to pair against.
        for name in ("c1", "c2", "c3"):
            obj = self.fleet.member(name).get(self.ftc.source.resource, "default/web")
            assert obj["metadata"]["annotations"][CURRENT_REVISION_ANNOTATION] == current

        # A template update creates a second revision and records the last.
        src = self.fleet.host.get(self.ftc.source.resource, "default/web")
        src["spec"]["template"]["spec"]["containers"][0]["image"] = "nginx:2"
        self.fleet.host.update(self.ftc.source.resource, src)
        settle(*self.everything())
        fed = self.fleet.host.get(self.ftc.federated.resource, "default/web")
        assert fed["metadata"]["annotations"][LAST_REVISION_ANNOTATION].startswith(
            current + "|"
        )
        assert fed["metadata"]["annotations"][CURRENT_REVISION_ANNOTATION] != current
        assert len(self.fleet.host.list(CONTROLLER_REVISIONS)) == 2
