"""Full-stack differential test: engine (featurize + XLA tick) vs the
sequential per-object baseline over randomized API objects."""

import numpy as np

from kubeadmiral_tpu.bench_support import sequential_schedule
from kubeadmiral_tpu.models.types import (
    AutoMigrationSpec,
    ClusterAffinity,
    ClusterState,
    MODE_DIVIDE,
    PreferredSchedulingTerm,
    SelectorRequirement,
    SelectorTerm,
    SchedulingUnit,
    Taint,
    Toleration,
    parse_resources,
)
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

GVKS = ("apps/v1/Deployment", "batch/v1/Job")
REGIONS = ("us", "eu", "ap")


def random_cluster(rng, j):
    taints = []
    if rng.random() < 0.25:
        taints.append(
            Taint("dedicated", str(rng.choice(["infra", "batch"])), "NoSchedule")
        )
    if rng.random() < 0.15:
        taints.append(Taint("maint", "", "PreferNoSchedule"))
    if rng.random() < 0.1:
        taints.append(Taint("evict", "", "NoExecute"))
    cpu = int(rng.integers(1, 64))
    free = float(rng.uniform(0, 1))
    return ClusterState(
        name=f"m-{j:03d}",
        labels={"region": str(rng.choice(REGIONS)), "idx": str(j % 5)},
        taints=tuple(taints),
        allocatable=parse_resources({"cpu": cpu, "memory": f"{cpu * 4}Gi"}),
        available=parse_resources(
            {"cpu": f"{int(cpu * free * 1000)}m", "memory": f"{int(cpu * 4 * free)}Gi"}
        ),
        api_resources=frozenset(GVKS if j % 7 else GVKS[:1]),
    )


def random_unit(rng, i, cluster_names):
    affinity = None
    if rng.random() < 0.4:
        required = None
        if rng.random() < 0.6:
            required = (
                SelectorTerm(
                    match_expressions=(
                        SelectorRequirement(
                            "region", "In", tuple(rng.choice(REGIONS, 2).tolist())
                        ),
                    )
                ),
            )
        preferred = ()
        if rng.random() < 0.6:
            preferred = (
                PreferredSchedulingTerm(
                    weight=int(rng.integers(1, 100)),
                    preference=SelectorTerm(
                        match_expressions=(
                            SelectorRequirement("idx", "NotIn", ("0", "3")),
                        )
                    ),
                ),
            )
        affinity = ClusterAffinity(required=required, preferred=preferred)

    tolerations = []
    if rng.random() < 0.5:
        tolerations.append(Toleration(key="dedicated", operator="Exists"))
    if rng.random() < 0.3:
        tolerations.append(Toleration(key="maint", operator="Exists"))
    if rng.random() < 0.2:
        tolerations.append(Toleration())  # tolerate-nothing-specific corner

    current = {}
    if rng.random() < 0.4:
        for n in rng.choice(cluster_names, rng.integers(1, 4), replace=False):
            current[str(n)] = None if rng.random() < 0.3 else int(rng.integers(0, 9))

    divide = rng.random() < 0.7
    weights = {}
    if divide and rng.random() < 0.5:
        for n in cluster_names:
            if rng.random() < 0.7:
                weights[n] = int(rng.integers(0, 30))

    auto = None
    if rng.random() < 0.3:
        auto = AutoMigrationSpec(
            keep_unschedulable_replicas=bool(rng.random() < 0.5),
            estimated_capacity={
                str(n): int(rng.integers(0, 12))
                for n in rng.choice(cluster_names, 2, replace=False)
            },
        )

    return SchedulingUnit(
        gvk=GVKS[i % 2],
        namespace=f"ns-{i % 5}",
        name=f"wl-{i}",
        scheduling_mode=MODE_DIVIDE if divide else "Duplicate",
        desired_replicas=int(rng.integers(0, 60)) if divide else None,
        resource_request=parse_resources(
            {"cpu": f"{int(rng.integers(0, 6)) * 500}m", "memory": f"{int(rng.integers(0, 6))}Gi"}
        )
        if rng.random() < 0.8
        else {},
        cluster_selector={"region": str(rng.choice(REGIONS))}
        if rng.random() < 0.2
        else {},
        cluster_names=frozenset(
            str(n) for n in rng.choice(cluster_names, 5, replace=False)
        )
        if rng.random() < 0.3
        else frozenset(),
        affinity=affinity,
        tolerations=tuple(tolerations),
        max_clusters=int(rng.integers(0, 9)) if rng.random() < 0.3 else None,
        min_replicas={
            str(n): int(rng.integers(0, 5))
            for n in rng.choice(cluster_names, 2, replace=False)
        }
        if rng.random() < 0.25
        else {},
        max_replicas={
            str(n): int(rng.integers(0, 15))
            for n in rng.choice(cluster_names, 2, replace=False)
        }
        if rng.random() < 0.25
        else {},
        weights=weights,
        sticky_cluster=bool(rng.random() < 0.15),
        avoid_disruption=bool(rng.random() < 0.5),
        current_clusters=current,
        auto_migration=auto,
    )


def test_engine_matches_sequential_reference():
    rng = np.random.default_rng(424242)
    clusters = [random_cluster(rng, j) for j in range(24)]
    names = [c.name for c in clusters]
    units = [random_unit(rng, i, names) for i in range(120)]

    engine = SchedulerEngine(chunk_size=64, min_bucket=32, min_cluster_bucket=8)
    got = engine.schedule(units, clusters)
    want = sequential_schedule(units, clusters)

    for i, (g, w) in enumerate(zip(got, want)):
        w_named = {names[j]: reps for j, reps in w.items()}
        assert g.clusters == w_named, (
            f"object {i} ({units[i].name}): engine={g.clusters} "
            f"sequential={w_named}\nunit={units[i]}"
        )
