"""Narrow-solve exactness suite (ISSUE 5).

The narrow tick ranks/bin-packs over M candidate columns per row
instead of the full cluster axis, with a per-row certificate; rows the
certificate rejects re-solve through the dense program.  The claims
checked here:

* certified rows of ``schedule_tick_narrow`` are bit-identical to
  ``schedule_tick`` on every output plane;
* the certified-or-fallback merge (what the engine ships) matches the
  sequential oracle — placements (schedule_one), reason rows
  (explain_one) and packed export (pack_one);
* adversarial capacity-spill shapes — spill chains deeper than M,
  score ties at the M boundary, ``max_clusters`` > M, dynamic-weight
  redistribution into low-ranked clusters — force the certificate down
  (never a silent mis-solve), and the engine's fallback keeps results
  identical to a dense engine while counting the rows it re-solved;
* a randomized engine differential (cold / churn / drift sequence)
  against a dense engine.
"""

import dataclasses

import numpy as np
import pytest

from test_engine_cache import make_world, results_equal
from test_engine_vs_sequential import random_cluster, random_unit
from test_pipeline import R, random_problem, to_tick_inputs

from kubeadmiral_tpu.models.types import (
    MODE_DIVIDE,
    AutoMigrationSpec,
    ClusterState,
    SchedulingUnit,
    parse_resources,
)
from kubeadmiral_tpu.ops import pipeline as dev
from kubeadmiral_tpu.ops.pipeline_oracle import (
    NIL,
    explain_one,
    pack_one,
    schedule_one,
)
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

PLANES = ("selected", "replicas", "counted", "feasible", "scores", "reasons")


def random_batch(rng, c, n=80):
    names = [f"member-{j}" for j in range(c)]
    shared_alloc = [[int(x) for x in rng.integers(5, 50, R)] for _ in range(c)]
    shared_used = [[int(x) for x in rng.integers(0, 40, R)] for _ in range(c)]
    shared_cpu_a = [int(x) for x in rng.integers(0, 30, c)]
    shared_cpu_v = [int(x) for x in rng.integers(-3, 25, c)]
    problems = []
    for i in range(n):
        p = random_problem(rng, c, f"ns-{i}/w-{i}", names)
        p.alloc, p.used = shared_alloc, shared_used
        p.cpu_alloc, p.cpu_avail = shared_cpu_a, shared_cpu_v
        problems.append(p)
    return problems


def narrow_and_dense(problems, c, m):
    inp = to_tick_inputs(problems, c)
    dense = dev.schedule_tick(inp)
    narrow, cert = dev.schedule_tick_narrow(inp, m)
    return dense, narrow, np.asarray(cert).astype(bool)


def merged_planes(dense, narrow, cert):
    """What the engine ships: narrow planes with uncertified rows
    replaced by the dense re-solve."""
    out = {}
    for name in PLANES:
        d = np.asarray(getattr(dense, name))
        n = np.asarray(getattr(narrow, name)).copy()
        n[~cert] = d[~cert]
        out[name] = n
    return out


class TestNarrowVsDenseKernel:
    @pytest.mark.parametrize(
        "c,m,seed", [(19, 8, 0), (64, 8, 1), (64, 16, 2), (128, 32, 3)]
    )
    def test_certified_rows_bit_identical(self, c, m, seed):
        rng = np.random.default_rng(7000 + seed)
        dense, narrow, cert = narrow_and_dense(random_batch(rng, c), c, m)
        assert cert.any(), "no row certified — the fast path never engages"
        for name in PLANES:
            d = np.asarray(getattr(dense, name))[cert]
            n = np.asarray(getattr(narrow, name))[cert]
            np.testing.assert_array_equal(d, n, err_msg=name)

    def test_wide_cluster_axis_quantized_planner_key(self):
        """C=2048 puts the planner candidate sort on its quantized-key
        path (53 priority bits + 11 index bits > 63, so the packed key
        drops low tiebreak bits): certified rows must still match dense
        bit-for-bit — quantization may only cost certificates, never
        correctness."""
        rng = np.random.default_rng(7400)
        c = 2048
        dense, narrow, cert = narrow_and_dense(
            random_batch(rng, c, n=24), c, 64
        )
        assert cert.any(), "no row certified — the fast path never engages"
        for name in PLANES:
            d = np.asarray(getattr(dense, name))[cert]
            n = np.asarray(getattr(narrow, name))[cert]
            np.testing.assert_array_equal(d, n, err_msg=name)

    def test_m_at_least_c_is_whole_problem(self):
        """M >= C narrows nothing: every row must certify and match."""
        rng = np.random.default_rng(7100)
        c = 19
        dense, narrow, cert = narrow_and_dense(random_batch(rng, c), c, 32)
        assert cert.all()
        for name in PLANES:
            np.testing.assert_array_equal(
                np.asarray(getattr(dense, name)),
                np.asarray(getattr(narrow, name)),
                err_msg=name,
            )


class TestNarrowVsOracle:
    @pytest.mark.parametrize("c,m", [(19, 8), (64, 16)])
    def test_merged_solve_matches_oracle(self, c, m):
        """The certified-or-fallback merge reproduces the sequential
        oracle row for row: placements, reason rows (explain_one) and
        the packed export (pack_one) — the full fidelity /debug/explain
        and the flight recorder consume."""
        rng = np.random.default_rng(7200 + c)
        problems = random_batch(rng, c, n=60)
        dense, narrow, cert = narrow_and_dense(problems, c, m)
        got = merged_planes(dense, narrow, cert)
        packed = dev.pack_rows(
            got["selected"], got["replicas"], got["counted"],
            got["scores"], got["reasons"], m,
        )
        for i, p in enumerate(problems):
            want = schedule_one(p)
            got_idx = set(np.nonzero(got["selected"][i])[0].tolist())
            assert got_idx == set(want.keys()), (i, p)
            for j in got_idx:
                w = want[j]
                assert int(got["replicas"][i, j]) == (NIL if w is None else w)
            assert got["reasons"][i].tolist() == explain_one(p), (i, p)
            wantp = pack_one(p, min(m, c))
            gotp = {
                "idx": np.asarray(packed.idx)[i].tolist(),
                "rep": np.asarray(packed.rep)[i].tolist(),
                "cnt": np.asarray(packed.cnt)[i].tolist(),
                "sco": np.asarray(packed.sco)[i].tolist(),
                "nsel": int(np.asarray(packed.nsel)[i]),
                "nfeas": int(np.asarray(packed.nfeas)[i]),
                "rsum": np.asarray(packed.rsum)[i].tolist(),
            }
            assert gotp == wantp, (i, gotp, wantp, p)


def spill_world(c=32, capacity=1, total=40, keep=False):
    """Divide-mode rows whose capacity-spill chain is provably deeper
    than a small M: every cluster caps at ``capacity`` replicas, so the
    planner walks ~``total`` columns of its processing order."""
    clusters = [
        ClusterState(
            name=f"m-{j:03d}",
            labels={},
            taints=(),
            allocatable=parse_resources({"cpu": "64", "memory": "256Gi"}),
            available=parse_resources({"cpu": str(8 + j % 7), "memory": "64Gi"}),
            api_resources=frozenset({"apps/v1/Deployment"}),
        )
        for j in range(c)
    ]
    units = [
        SchedulingUnit(
            gvk="apps/v1/Deployment",
            namespace="spill",
            name=f"w-{i:03d}",
            scheduling_mode=MODE_DIVIDE,
            desired_replicas=total,
            resource_request=parse_resources({"cpu": "10m"}),
            auto_migration=AutoMigrationSpec(
                keep_unschedulable_replicas=keep,
                estimated_capacity={f"m-{j:03d}": capacity for j in range(c)},
            ),
        )
        for i in range(12)
    ]
    return units, clusters


class TestAdversarialFallback:
    def test_spill_chain_deeper_than_m_forces_fallback(self):
        """A capacity-spill cascade past column M cannot be solved from
        the narrow slots; the certificate must reject the row (cert
        False), never silently truncate the chain."""
        units, clusters = spill_world()
        dense = SchedulerEngine(chunk_size=64, narrow=False)
        narrow = SchedulerEngine(chunk_size=64, narrow_m=8)
        want = dense.schedule(units, clusters)
        got = narrow.schedule(units, clusters)
        results_equal(got, want)
        assert narrow.narrow_last_m == 8
        assert narrow.narrow_stats["fallback"] > 0, narrow.narrow_stats

    def test_max_clusters_beyond_m_forces_fallback(self):
        """max_clusters > M with more feasible clusters than M: the
        narrow cut cannot see enough candidates to fill K, so the
        select certificate fails and the dense re-solve fills in."""
        units, clusters = make_world(b=24, c=32)
        units = [
            dataclasses.replace(u, max_clusters=20) for u in units
        ]
        dense = SchedulerEngine(chunk_size=64, narrow=False)
        narrow = SchedulerEngine(chunk_size=64, narrow_m=8)
        want = dense.schedule(units, clusters)
        got = narrow.schedule(units, clusters)
        results_equal(got, want)
        # The engine sizes M from the finite maxClusters bound, so with
        # narrow_m=8 and maxClusters=20 it picks M=32 == c_bucket and
        # correctly declines to narrow; force the kernel instead.
        problems = random_batch(np.random.default_rng(7300), 32, n=40)
        for p in problems:
            p.max_clusters = 20
        d, n, cert = narrow_and_dense(problems, 32, 8)
        merged = merged_planes(d, n, cert)
        for name in PLANES:
            np.testing.assert_array_equal(
                merged[name], np.asarray(getattr(d, name)), err_msg=name
            )
        assert (~cert).any(), "max_clusters > M never tripped the certificate"

    def test_score_ties_at_the_m_boundary_stay_exact(self):
        """Columns tying in score across the M boundary: the composite
        (score, index) key is collision-free, so either the narrow cut
        is provably the dense cut (lower indices win) or the row falls
        back — both end bit-identical."""
        rng = np.random.default_rng(7400)
        c = 32
        problems = random_batch(rng, c, n=40)
        for p in problems:
            # Flatten every score signal: equal affinity, no taints, and
            # score plugins disabled -> totals tie at 0 everywhere.
            p.score_enabled = [False] * 5
            p.taint_counts = [0] * c
            p.affinity_scores = [0] * c
            p.max_clusters = int(rng.integers(1, 8))
        d, n, cert = narrow_and_dense(problems, c, 8)
        merged = merged_planes(d, n, cert)
        for name in PLANES:
            np.testing.assert_array_equal(
                merged[name], np.asarray(getattr(d, name)), err_msg=name
            )

    def test_dynamic_weight_redistribution_into_low_ranked_clusters(self):
        """Divide rows without static weights whose dynamic weights push
        replicas into clusters far down the processing order (beyond M
        slots): the planner certificate must reject them, and the dense
        fallback must reproduce the dense engine exactly."""
        units, clusters = make_world(b=24, c=48)
        units = [
            dataclasses.replace(
                u,
                scheduling_mode=MODE_DIVIDE,
                desired_replicas=97,
                weights={},
            )
            for u in units
        ]
        dense = SchedulerEngine(chunk_size=64, narrow=False)
        narrow = SchedulerEngine(chunk_size=64, narrow_m=8)
        want = dense.schedule(units, clusters)
        got = narrow.schedule(units, clusters)
        results_equal(got, want)
        assert narrow.narrow_stats["fallback"] > 0, narrow.narrow_stats

    def test_fallback_rows_counted_in_metrics(self):
        """engine_narrow_rows_total{path=fallback} > 0 on the
        adversarial set — the certificate engaged the fallback, it did
        not silently pass wrong answers."""
        units, clusters = spill_world()
        metrics = Metrics()
        engine = SchedulerEngine(chunk_size=64, narrow_m=8, metrics=metrics)
        engine.schedule(units, clusters)
        fam = metrics.counter_family("engine_narrow_rows_total")
        by_path = {dict(k)["path"]: v for k, v in fam.items()}
        assert by_path.get("fallback", 0) > 0, by_path
        assert by_path.get("fallback", 0) == engine.narrow_stats["fallback"]
        if engine.narrow_stats["rows"]:
            assert by_path.get("narrow", 0) == engine.narrow_stats["rows"]


class TestRandomizedEngineDifferential:
    def test_cold_churn_drift_sequence_matches_dense(self):
        rng = np.random.default_rng(7500)
        clusters = [random_cluster(rng, j) for j in range(24)]
        names = [c.name for c in clusters]
        units = [random_unit(rng, i, names) for i in range(90)]
        dense = SchedulerEngine(chunk_size=48, narrow=False)
        narrow = SchedulerEngine(chunk_size=48, narrow_m=8)
        results_equal(
            narrow.schedule(units, clusters), dense.schedule(units, clusters)
        )
        assert narrow.narrow_last_m == 8, "narrow never engaged"
        # Churn a handful of rows: the sub-batch slabs run the narrow
        # program too (drift recomputes route through the same path).
        churned = list(units)
        for i in (3, 17, 40):
            churned[i] = dataclasses.replace(
                churned[i],
                desired_replicas=(churned[i].desired_replicas or 1) + 5,
            )
        results_equal(
            narrow.schedule(churned, clusters),
            dense.schedule(churned, clusters),
        )
        # Cluster-capacity drift: gate survivors re-solve narrow.
        drifted = list(clusters)
        drifted[0] = dataclasses.replace(
            drifted[0],
            available={
                k: max(0, v // 2) for k, v in drifted[0].available.items()
            },
        )
        results_equal(
            narrow.schedule(churned, drifted),
            dense.schedule(churned, drifted),
        )
        total = narrow.narrow_stats["rows"] + narrow.narrow_stats["fallback"]
        assert total > 0

    def test_kt_narrow_off_reverts_to_dense_programs(self):
        units, clusters = make_world(b=16, c=32)
        off = SchedulerEngine(chunk_size=32, narrow=False)
        on = SchedulerEngine(chunk_size=32, narrow_m=8)
        results_equal(on.schedule(units, clusters), off.schedule(units, clusters))
        assert off.narrow_last_m == 0
        assert off.narrow_stats == {"rows": 0, "fallback": 0}
