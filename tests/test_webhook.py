"""Webhook scheduler plugins (reference:
pkg/controllers/scheduler/extensions/webhook/v1alpha1/plugin_test.go's
fake-HTTP pattern + examples/scheduler/webhook)."""

import dataclasses
import json

import pytest

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.clusterctl import (
    FEDERATED_CLUSTERS,
    FederatedClusterController,
    NODES,
)
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.models import profile as PR
from kubeadmiral_tpu.models import types as T
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
from kubeadmiral_tpu.scheduler import webhook as W
from kubeadmiral_tpu.scheduler.extension_service import ExtensionService
from kubeadmiral_tpu.testing.fakekube import ClusterFleet

from test_e2e_slice import make_deployment, make_node, settle


class FakeClient:
    """Records requests; replies from a canned url-suffix -> dict map."""

    def __init__(self, responses):
        self.responses = responses
        self.requests = []

    def post(self, url, body, timeout):
        self.requests.append((url, json.loads(body)))
        for suffix, response in self.responses.items():
            if url.endswith(suffix):
                return json.dumps(response).encode()
        raise AssertionError(f"unexpected url {url}")


def make_unit(**kw):
    defaults = dict(
        gvk="apps/v1/Deployment",
        namespace="default",
        name="web",
        scheduling_mode=T.MODE_DUPLICATE,
    )
    defaults.update(kw)
    return T.SchedulingUnit(**defaults)


def make_cluster(name, labels=None):
    return T.ClusterState(
        name=name,
        labels=dict(labels or {}),
        allocatable={"cpu": 64000, "memory": 1 << 36},
        available={"cpu": 32000, "memory": 1 << 35},
        api_resources=frozenset({"apps/v1/Deployment"}),
    )


class TestParseDuration:
    def test_formats(self):
        assert W.parse_duration("5s") == 5.0
        assert W.parse_duration("500ms") == 0.5
        assert W.parse_duration("1m30s") == 90.0
        assert W.parse_duration(2) == 2.0
        assert W.parse_duration(None) is None
        assert W.parse_duration("bogus") is None


class TestWebhookPlugin:
    def make_plugin(self, responses):
        config = W.WebhookConfig(
            name="wh",
            url_prefix="http://webhook.example",
            filter_path="/filter",
            score_path="/score",
            select_path="/select",
        )
        client = FakeClient(responses)
        return W.WebhookPlugin(config, client=client), client

    def test_filter_payload_and_response(self):
        plugin, client = self.make_plugin({"/filter": {"selected": True}})
        su = make_unit(desired_replicas=5, scheduling_mode=T.MODE_DIVIDE)
        assert plugin.filter(su, make_cluster("c1", {"region": "eu"}))
        url, body = client.requests[0]
        assert url == "http://webhook.example/filter"
        assert body["schedulingUnit"]["name"] == "web"
        assert body["schedulingUnit"]["schedulingMode"] == "Divide"
        assert body["schedulingUnit"]["desiredReplicas"] == 5
        assert body["cluster"]["metadata"]["name"] == "c1"
        assert body["cluster"]["metadata"]["labels"] == {"region": "eu"}

    def test_score_and_select(self):
        plugin, client = self.make_plugin(
            {
                "/score": {"score": 42},
                "/select": {"selectedClusterNames": ["c2"]},
            }
        )
        su = make_unit()
        assert plugin.score(su, make_cluster("c1")) == 42
        selected = plugin.select(su, [(make_cluster("c1"), 10), (make_cluster("c2"), 20)])
        assert selected == ["c2"]
        _, select_body = client.requests[1]
        assert [cs["score"] for cs in select_body["clusterScores"]] == [10, 20]

    def test_error_field_raises(self):
        plugin, _ = self.make_plugin({"/filter": {"selected": False, "error": "boom"}})
        with pytest.raises(W.WebhookError):
            plugin.filter(make_unit(), make_cluster("c1"))


class TestWebhookScheduling:
    """Webhook plugins wired through profile -> controller -> engine."""

    def setup_method(self):
        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        self.ftc = dataclasses.replace(
            ftc, controllers=(("kubeadmiral.io/global-scheduler",),)
        )
        self.fleet = ClusterFleet()
        self.clusterctl = FederatedClusterController(
            self.fleet, api_resource_probe=["apps/v1/Deployment"]
        )
        self.federate = FederateController(self.fleet.host, self.ftc)
        for name, region in (("c1", "us"), ("c2", "eu"), ("c3", "eu")):
            member = self.fleet.add_member(name)
            member.create(NODES, make_node("n1", "64", "128Gi"))
            self.fleet.host.create(
                FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name, "labels": {"region": region}},
                    "spec": {},
                },
            )

    def create_profile_and_policy(self, webhook_name, points=("filter",)):
        plugins = {}
        for point in points:
            plugins[point] = {
                "enabled": [{"type": "Webhook", "name": webhook_name}]
            }
        self.fleet.host.create(
            PR.SCHEDULING_PROFILES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "SchedulingProfile",
                "metadata": {"name": "with-webhook"},
                "spec": {"plugins": plugins},
            },
        )
        self.fleet.host.create(
            PROPAGATION_POLICIES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "PropagationPolicy",
                "metadata": {"name": "pp", "namespace": "default"},
                "spec": {
                    "schedulingMode": "Duplicate",
                    "schedulingProfile": "with-webhook",
                },
            },
        )

    def placement(self):
        fed = self.fleet.host.get(self.ftc.federated.resource, "default/web")
        return C.get_placement(fed, C.SCHEDULER)

    def test_fake_client_filter_narrows_placement(self):
        responses = {"/filter": None}  # replaced per request below

        class RegionFilter(FakeClient):
            def post(self, url, body, timeout):
                self.requests.append((url, json.loads(body)))
                req = json.loads(body)
                selected = (
                    req["cluster"]["metadata"]["labels"].get("region") == "eu"
                )
                return json.dumps({"selected": selected}).encode()

        client = RegionFilter(responses)
        scheduler = SchedulerController(
            self.fleet.host, self.ftc, webhook_client=client
        )
        self.fleet.host.create(
            W.SCHEDULER_WEBHOOK_CONFIGS,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "SchedulerPluginWebhookConfiguration",
                "metadata": {"name": "eu-only"},
                "spec": {
                    "urlPrefix": "http://webhook.example",
                    "filterPath": "/filter",
                    "payloadVersions": ["v1alpha1"],
                },
            },
        )
        self.create_profile_and_policy("eu-only")
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        settle(self.clusterctl, self.federate, scheduler)
        assert self.placement() == {"c2", "c3"}
        assert client.requests  # the webhook was actually consulted

    def test_live_extension_service_end_to_end(self):
        """Real HTTP round trip: ExtensionService serving filter+select."""
        service = ExtensionService(
            filter_fn=lambda req: {
                "selected": req["cluster"]["metadata"]["labels"].get("region")
                == "eu"
            },
            select_fn=lambda req: {
                "selectedClusterNames": sorted(
                    cs["cluster"]["metadata"]["name"]
                    for cs in req["clusterScores"]
                )[:1]
            },
        )
        service.start()
        try:
            scheduler = SchedulerController(self.fleet.host, self.ftc)
            self.fleet.host.create(
                W.SCHEDULER_WEBHOOK_CONFIGS,
                service.webhook_configuration("eu-picker"),
            )
            self.create_profile_and_policy("eu-picker", points=("filter", "select"))
            self.fleet.host.create(self.ftc.source.resource, make_deployment())
            settle(self.clusterctl, self.federate, scheduler)
            # filter keeps {c2,c3}; select narrows to the first by name.
            assert self.placement() == {"c2"}
        finally:
            service.stop()

    def test_batch_protocol_is_one_post_per_plugin_per_tick(self):
        """A batch-capable server gets the whole (units x clusters) grid
        in ONE POST per extension point per tick — never O(B x C) calls
        (the reference's webhook/v1alpha1/plugin.go:77-251 behavior)."""

        class BatchServer(FakeClient):
            def post(self, url, body, timeout):
                req = json.loads(body)
                self.requests.append((url, req))
                if url.endswith("/filter-batch"):
                    rows = [
                        [
                            c["metadata"]["labels"].get("region") == "eu"
                            for c in req["clusters"]
                        ]
                        for _ in req["schedulingUnits"]
                    ]
                    return json.dumps({"selected": rows}).encode()
                if url.endswith("/score-batch"):
                    rows = [
                        [
                            50 if c["metadata"]["name"] == "c2" else 1
                            for c in req["clusters"]
                        ]
                        for _ in req["schedulingUnits"]
                    ]
                    return json.dumps({"scores": rows}).encode()
                raise AssertionError(f"per-pair call leaked: {url}")

        client = BatchServer({})
        scheduler = SchedulerController(
            self.fleet.host, self.ftc, webhook_client=client
        )
        self.fleet.host.create(
            W.SCHEDULER_WEBHOOK_CONFIGS,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "SchedulerPluginWebhookConfiguration",
                "metadata": {"name": "eu-batch"},
                "spec": {
                    "urlPrefix": "http://webhook.example",
                    "filterPath": "/filter",
                    "scorePath": "/score",
                    "payloadVersions": ["v1alpha1"],
                },
            },
        )
        self.create_profile_and_policy("eu-batch", points=("filter", "score"))
        for i in range(6):
            self.fleet.host.create(
                self.ftc.source.resource, make_deployment(name=f"web-{i}")
            )
        settle(self.clusterctl, self.federate, scheduler)

        for i in range(6):
            fed = self.fleet.host.get(
                self.ftc.federated.resource, f"default/web-{i}"
            )
            assert C.get_placement(fed, C.SCHEDULER) == {"c2", "c3"}

        urls = [u for u, _ in client.requests]
        assert all(u.endswith("-batch") for u in urls), urls
        # One filter + one score POST per scheduling tick; settle may run
        # a couple of ticks but never per-(object, cluster) calls.
        assert len(urls) <= 6, urls
        biggest = max(
            len(req["schedulingUnits"]) for _, req in client.requests
        )
        assert biggest >= 6  # the whole batch travelled together

    def test_reference_protocol_server_falls_back_to_per_pair(self):
        """serve_batch=False emulates a reference-protocol server: the
        client probes the batch endpoint once, then degrades to per-pair
        calls with identical results."""
        service = ExtensionService(
            filter_fn=lambda req: {
                "selected": req["cluster"]["metadata"]["labels"].get("region")
                == "eu"
            },
            serve_batch=False,
        )
        service.start()
        try:
            scheduler = SchedulerController(self.fleet.host, self.ftc)
            self.fleet.host.create(
                W.SCHEDULER_WEBHOOK_CONFIGS,
                service.webhook_configuration("eu-only"),
            )
            self.create_profile_and_policy("eu-only")
            self.fleet.host.create(self.ftc.source.resource, make_deployment())
            settle(self.clusterctl, self.federate, scheduler)
            assert self.placement() == {"c2", "c3"}
        finally:
            service.stop()

    def test_unsupported_payload_version_is_not_registered(self):
        scheduler = SchedulerController(self.fleet.host, self.ftc)
        self.fleet.host.create(
            W.SCHEDULER_WEBHOOK_CONFIGS,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "SchedulerPluginWebhookConfiguration",
                "metadata": {"name": "future"},
                "spec": {
                    "urlPrefix": "http://webhook.example",
                    "filterPath": "/filter",
                    "payloadVersions": ["v99"],
                },
            },
        )
        assert "future" not in scheduler.webhook_plugins
