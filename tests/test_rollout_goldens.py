"""The reference's rollout-planner table tests, replayed bit-for-bit.

Fixtures are machine-translated from
pkg/controllers/util/rolloutplan_test.go (85 suites, 289 targets across
TestPlanWholeProcessWithMaxUnavailable/Both/Surge, Creation, Scale,
EmptyTargets, UnexceptedCases and the 43 recorded production cases of
TestPlanActualCases) — the federation-wide surge/unavailable budget
arithmetic is order-sensitive, so self-consistency isn't enough
(VERDICT r2 #6)."""

import json
import os

import pytest

from kubeadmiral_tpu.federation.rollout import (
    RolloutPlan,
    RolloutPlanner,
    Target,
    TargetStatus,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "rollout_goldens.json")
GOLDENS = json.load(open(FIXTURE))


def build_target(spec) -> Target:
    nums = spec["nums"]
    updated = spec["updated"]
    if spec["kind"] == "":
        replicas, desired, upd, upd_avail, ms, mu = nums
        cur_new = upd if updated else replicas
        status = TargetStatus(
            replicas=replicas, actual_replicas=replicas,
            available_replicas=replicas, updated_replicas=upd,
            updated_available_replicas=upd_avail,
            current_new_replicas=cur_new,
            current_new_available_replicas=cur_new,
            updated=updated, max_surge=ms, max_unavailable=mu,
        )
    elif spec["kind"] == "WithActualInfo":
        replicas, desired, upd, upd_avail, actual, avail, ms, mu = nums
        cur_new = upd if updated else replicas
        status = TargetStatus(
            replicas=replicas, actual_replicas=actual,
            available_replicas=avail, updated_replicas=upd,
            updated_available_replicas=upd_avail,
            current_new_replicas=cur_new,
            current_new_available_replicas=cur_new,
            updated=updated, max_surge=ms, max_unavailable=mu,
        )
    else:  # WithAllInfo
        (replicas, desired, upd, upd_avail, cur_new, cur_new_avail,
         actual, avail, ms, mu) = nums
        status = TargetStatus(
            replicas=replicas, actual_replicas=actual,
            available_replicas=avail, updated_replicas=upd,
            updated_available_replicas=upd_avail,
            current_new_replicas=cur_new,
            current_new_available_replicas=cur_new_avail,
            updated=updated, max_surge=ms, max_unavailable=mu,
        )
    return Target(cluster=spec["name"], status=status, desired_replicas=desired)


CASES = [
    (func, suite)
    for func, data in GOLDENS.items()
    for suite in data["suites"]
]


@pytest.mark.parametrize(
    "func,suite", CASES, ids=[f"{f}::{s['name']}" for f, s in CASES]
)
def test_reference_golden(func, suite):
    planner = RolloutPlanner.from_params(
        suite["replicas"], suite["max_surge"], suite["max_unavailable"]
    )
    for spec in suite["targets"]:
        planner.register(build_target(spec))
    got = planner.plan()
    want = {
        cluster: RolloutPlan(
            replicas=v[0], max_surge=v[1], max_unavailable=v[2],
            only_patch_replicas=v[3],
        )
        for cluster, v in suite["plans"].items()
    }
    assert got == want, f"{func}:{suite['name']}\n got: {got}\nwant: {want}"


def test_empty_targets_literal_planners():
    """TestPlanEmptyTargets constructs planners directly: both (0, 25,
    replicas=100) and (0, 0) must plan nothing for no targets."""
    assert RolloutPlanner.from_params(100, 0, 25).plan() == {}
    assert RolloutPlanner.from_params(0, 0, 0).plan() == {}
