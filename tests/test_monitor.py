"""Monitor controller + eventsink (reference: pkg/controllers/monitor,
pkg/controllers/util/eventsink)."""

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.monitor import MonitorController
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.runtime.eventsink import (
    EVENTS,
    FEDERATED_OBJECT_ANNOTATION,
    DefederatingRecorderMux,
    EventRecorder,
)
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.testing.fakekube import FakeKube


def deployment_ftc():
    return next(f for f in default_ftcs() if f.name == "deployments.apps")


def make_fed(name, propagated, clusters=("c1",), generation=1):
    conditions = [{"type": "Propagation", "status": "True" if propagated else "False"}]
    return {
        "apiVersion": "types.kubeadmiral.io/v1alpha1",
        "kind": "FederatedDeployment",
        "metadata": {"name": name, "namespace": "default", "generation": generation},
        "spec": {
            "template": {},
            "placements": [
                {
                    "controller": C.SCHEDULER,
                    "placement": [{"cluster": c} for c in clusters],
                }
            ],
        },
        "status": {
            "conditions": conditions,
            "clusters": [
                {"cluster": c, "status": "OK" if propagated else "Waiting"}
                for c in clusters
            ],
        },
    }


class TestMonitor:
    def setup_method(self):
        self.host = FakeKube()
        self.metrics = Metrics()
        self.now = [100.0]
        self.ctl = MonitorController(
            self.host,
            deployment_ftc(),
            metrics=self.metrics,
            interval=30.0,
            clock=lambda: self.now[0],
        )
        self.resource = deployment_ftc().federated.resource

    def tick(self):
        self.ctl._report()

    def test_periodic_tick_via_worker_and_fake_clock(self):
        self.host.create(self.resource, make_fed("a", True))
        assert self.ctl.worker.step()  # first tick reports immediately
        assert self.metrics.stores["monitor.deployments.apps.total"] == 1
        assert not self.ctl.worker.step()  # requeued 30s out
        self.now[0] += 31.0
        assert self.ctl.worker.step()  # fake clock reaches the interval

    def test_propagation_gauges(self):
        self.host.create(self.resource, make_fed("a", True))
        self.host.create(self.resource, make_fed("b", False))
        self.tick()
        assert self.metrics.stores["monitor.deployments.apps.total"] == 2
        assert self.metrics.stores["monitor.deployments.apps.propagated"] == 1
        assert self.metrics.stores["monitor.deployments.apps.unpropagated"] == 1

    def test_sync_latency_measured_per_generation(self):
        self.host.create(self.resource, make_fed("a", False))
        self.tick()
        self.now[0] += 42.0
        obj = self.host.get(self.resource, "default/a")
        obj["status"] = make_fed("a", True)["status"]
        self.host.update_status(self.resource, obj)
        self.tick()
        latencies = self.metrics.durations["monitor.deployments.apps.sync_latency"]
        assert latencies == [42.0]
        assert self.metrics.stores["monitor.deployments.apps.out_of_sync_seconds"] == 0

    def test_out_of_sync_age_tracks_oldest(self):
        self.host.create(self.resource, make_fed("a", False))
        self.tick()
        self.now[0] += 60.0
        self.tick()
        assert (
            self.metrics.stores["monitor.deployments.apps.out_of_sync_seconds"]
            == 60.0
        )

    def test_cluster_ready_gauges(self):
        for name, ready in (("c1", True), ("c2", False)):
            self.host.create(
                C.FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": {},
                    "status": {
                        "conditions": [
                            {"type": "Ready", "status": "True" if ready else "False"}
                        ]
                    },
                },
            )
        self.tick()
        assert self.metrics.stores["monitor.clusters.total"] == 2
        assert self.metrics.stores["monitor.clusters.ready"] == 1


class TestEventSink:
    def setup_method(self):
        self.host = FakeKube()

    def test_event_created_and_deduplicated(self):
        recorder = EventRecorder(self.host, "sync-controller", clock=lambda: 1.0)
        dep = {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "default"},
        }
        recorder.event(dep, "Normal", "Updating", "updating cluster c1")
        recorder.event(dep, "Normal", "Updating", "updating cluster c1")
        events = self.host.list(EVENTS)
        assert len(events) == 1
        assert events[0]["count"] == 2
        assert events[0]["involvedObject"]["kind"] == "Deployment"

    def test_defederating_mux_targets_source_too(self):
        mux = DefederatingRecorderMux(self.host, "scheduler", clock=lambda: 1.0)
        fed = {
            "apiVersion": "types.kubeadmiral.io/v1alpha1",
            "kind": "FederatedDeployment",
            "metadata": {
                "name": "web",
                "namespace": "default",
                "annotations": {FEDERATED_OBJECT_ANNOTATION: "1"},
            },
            "spec": {"template": {"apiVersion": "apps/v1", "kind": "Deployment"}},
        }
        mux.event(fed, "Normal", "Scheduled", "placed on c1,c2")
        kinds = {
            e["involvedObject"]["kind"] for e in self.host.list(EVENTS)
        }
        assert kinds == {"FederatedDeployment", "Deployment"}

    def test_non_federated_object_gets_single_event(self):
        mux = DefederatingRecorderMux(self.host, "scheduler", clock=lambda: 1.0)
        dep = {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "default"},
        }
        mux.event(dep, "Warning", "Failed", "boom")
        assert len(self.host.list(EVENTS)) == 1
