"""Compact featurization parity: expand_compact(featurize_compact(w))
must reproduce the dense featurizer's planes bit-for-bit, and the fused
tick must produce identical outputs over either format."""

import dataclasses

import numpy as np
import pytest

from kubeadmiral_tpu.models.types import (
    AutoMigrationSpec,
    ClusterAffinity,
    ClusterState,
    MODE_DIVIDE,
    PreferredSchedulingTerm,
    SelectorRequirement,
    SelectorTerm,
    SchedulingUnit,
    Taint,
    Toleration,
    parse_resources,
)
from kubeadmiral_tpu.ops.pipeline import expand_compact, schedule_tick
from kubeadmiral_tpu.scheduler.compact import (
    CompactVocab,
    VocabOverflow,
    featurize_compact,
)
from kubeadmiral_tpu.scheduler.featurize import _build_cluster_view, featurize


def rich_world(b=48, c=14, seed=7):
    rng = np.random.default_rng(seed)
    regions = ("us", "eu", "ap")
    clusters = []
    for j in range(c):
        clusters.append(
            ClusterState(
                name=f"member-{j:03d}",
                labels={"region": regions[j % 3], "tier": str(j % 4)},
                taints=(Taint("dedicated", "batch", "NoSchedule"),)
                if j % 5 == 0
                else ((Taint("gpu", "only", "NoExecute"),) if j % 7 == 0 else ()),
                allocatable=parse_resources(
                    {"cpu": str(8 + j), "memory": f"{32 + j}Gi",
                     "nvidia.com/gpu": str(j % 4)}
                ),
                available=parse_resources(
                    {"cpu": str(4 + j // 2), "memory": f"{16 + j}Gi",
                     "nvidia.com/gpu": str(j % 3)}
                ),
                api_resources=frozenset(
                    {"apps/v1/Deployment"}
                    | ({"apps/v1/StatefulSet"} if j % 2 else set())
                ),
            )
        )
    names = [cl.name for cl in clusters]
    affinity = ClusterAffinity(
        required=(
            SelectorTerm(
                match_expressions=(
                    SelectorRequirement("region", "In", ("eu", "us")),
                )
            ),
        ),
        preferred=(
            PreferredSchedulingTerm(
                weight=25,
                preference=SelectorTerm(
                    match_expressions=(
                        SelectorRequirement("tier", "In", ("0", "1")),
                    )
                ),
            ),
        ),
    )
    units = []
    for i in range(b):
        divide = i % 3 != 0
        current = {}
        if i % 4 == 0:
            picks = rng.integers(0, c, 3)
            current = {
                names[int(p)]: (None if i % 8 == 0 else int(rng.integers(1, 9)))
                for p in picks
            }
        units.append(
            SchedulingUnit(
                gvk="apps/v1/Deployment" if i % 2 else "apps/v1/StatefulSet",
                namespace=f"ns-{i % 5}",
                name=f"w-{i:04d}",
                scheduling_mode=MODE_DIVIDE if divide else "Duplicate",
                desired_replicas=(i % 30) + 1 if divide else None,
                resource_request=parse_resources(
                    {"cpu": f"{(i % 4) * 150}m", "memory": f"{(i % 6) * 128}Mi",
                     **({"nvidia.com/gpu": "1"} if i % 6 == 0 else {})}
                ),
                tolerations=(Toleration(key="dedicated", operator="Exists"),)
                if i % 2
                else (),
                affinity=affinity if i % 4 == 1 else None,
                cluster_selector={"region": "eu"} if i % 7 == 0 else {},
                cluster_names=(names[0], names[3]) if i % 9 == 0 else (),
                sticky_cluster=i % 11 == 0,
                current_clusters=current,
                max_clusters=(i % 5) + 1 if i % 5 == 0 else None,
                min_replicas={names[1]: 2} if i % 6 == 2 else {},
                max_replicas={names[2]: 5} if i % 6 == 3 else {},
                weights={names[1]: 3, names[4]: 7} if i % 6 == 4 else {},
                avoid_disruption=bool(i % 2),
                auto_migration=AutoMigrationSpec(
                    keep_unschedulable_replicas=bool(i % 2),
                    estimated_capacity={names[i % c]: i % 13},
                )
                if i % 5 == 1
                else None,
            )
        )
    return units, clusters


class TestCompactParity:
    def test_planes_match_dense_bit_for_bit(self):
        units, clusters = rich_world()
        view = _build_cluster_view(clusters, units)
        dense = featurize(units, clusters, view=view).inputs
        vocab = CompactVocab(view)
        ci = featurize_compact(units, view, vocab)
        expanded = expand_compact(ci)
        for name in dense._fields:
            want = np.asarray(getattr(dense, name))
            got = np.asarray(getattr(expanded, name))
            assert got.shape == want.shape, name
            np.testing.assert_array_equal(
                got.astype(np.int64), want.astype(np.int64), err_msg=name
            )

    def test_tick_outputs_match(self):
        units, clusters = rich_world(b=32, c=10, seed=11)
        view = _build_cluster_view(clusters, units)
        dense_out = schedule_tick(featurize(units, clusters, view=view).inputs)
        ci = featurize_compact(units, view, CompactVocab(view))
        compact_out = schedule_tick(expand_compact(ci))
        for name in dense_out._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(compact_out, name)),
                np.asarray(getattr(dense_out, name)),
                err_msg=name,
            )

    def test_vocab_overflow_raises(self):
        units, clusters = rich_world(b=8, c=6)
        view = _build_cluster_view(clusters, units)
        vocab = CompactVocab(view, sel_cap=1)
        with pytest.raises(VocabOverflow):
            featurize_compact(units, view, vocab)

    def test_vocab_grows_in_place_ids_stable(self):
        """Table growth must not invalidate previously issued ids (the
        engine caches CompactInputs referencing the same arrays)."""
        units, clusters = rich_world(b=20, c=8)
        view = _build_cluster_view(clusters, units)
        vocab = CompactVocab(view)
        first = featurize_compact(units[:10], view, vocab)
        v1 = vocab.version
        second = featurize_compact(units[10:], view, vocab)
        assert vocab.version >= v1
        # first's tables are the same (grown) arrays.
        assert first.sel_matrix is vocab.sel_matrix
        out1 = schedule_tick(expand_compact(first))
        dense1 = schedule_tick(
            featurize(units[:10], clusters, view=view).inputs
        )
        np.testing.assert_array_equal(
            np.asarray(out1.selected), np.asarray(dense1.selected)
        )
        out2 = schedule_tick(expand_compact(second))
        dense2 = schedule_tick(
            featurize(units[10:], clusters, view=view).inputs
        )
        np.testing.assert_array_equal(
            np.asarray(out2.selected), np.asarray(dense2.selected)
        )


class TestEngineVocabLifecycle:
    def test_topology_flap_keeps_cached_ids_valid(self):
        """A -> B -> A cluster-topology flap: chunk caches built against
        topology A's vocabulary must still decode correctly when A
        returns (ids are meaningless against a different vocabulary
        instance — the engine must reuse or invalidate, never mix)."""
        from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

        units, clusters_a = rich_world(b=40, c=10)
        clusters_b = [
            dataclasses.replace(cl, labels={**cl.labels, "flap": "yes"})
            for cl in clusters_a[:6]
        ]
        engine = SchedulerEngine(chunk_size=16, min_bucket=8)
        first_a = engine.schedule(units, clusters_a)
        engine.schedule(units[:12], clusters_b)  # fewer chunks: stale tails
        back_a = engine.schedule(units, clusters_a)
        fresh = SchedulerEngine(chunk_size=16, min_bucket=8).schedule(
            units, clusters_a
        )
        assert [r.clusters for r in back_a] == [r.clusters for r in fresh]
        assert [r.clusters for r in first_a] == [r.clusters for r in fresh]

    def test_prewarm_width_hints(self):
        """key_len / policy_entries hints compile the buckets the real
        workload will use."""
        from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

        engine = SchedulerEngine(chunk_size=32, min_bucket=8)
        engine.prewarm(
            16, 6, key_len=100, policy_entries=12, webhooks=True, wait=True
        )
        units, clusters = rich_world(b=16, c=6)
        got = engine.schedule(units, clusters)
        fresh = SchedulerEngine(chunk_size=32, min_bucket=8).schedule(
            units, clusters
        )
        assert [r.clusters for r in got] == [r.clusters for r in fresh]


class TestDenseFallback:
    def test_vocab_overflow_falls_back_dense_and_matches(self):
        """A chunk whose policies exceed a vocabulary cap must schedule
        through the dense path with identical results — and the engine's
        fast paths (noop, sub-batch) must keep working on it."""
        from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

        units, clusters = rich_world(b=36, c=8)
        tiny = SchedulerEngine(
            chunk_size=64, min_bucket=8, vocab_caps={"sel_cap": 1}
        )
        got = tiny.schedule(units, clusters)
        assert tiny._chunk_cache[0].fmt == "dense"
        fresh = SchedulerEngine(chunk_size=64, min_bucket=8).schedule(
            units, clusters
        )
        assert [r.clusters for r in got] == [r.clusters for r in fresh]
        # noop path on a dense-cached chunk
        again = tiny.schedule(units, clusters)
        assert tiny.fetch_stats["noop"] >= 1
        assert [r.clusters for r in again] == [r.clusters for r in fresh]
        # sub-batch path on a dense-cached chunk
        churned = list(units)
        churned[4] = dataclasses.replace(churned[4], desired_replicas=71)
        got2 = tiny.schedule(churned, clusters)
        assert tiny.fetch_stats["subbatch"] >= 1
        fresh2 = SchedulerEngine(chunk_size=64, min_bucket=8).schedule(
            churned, clusters
        )
        assert [r.clusters for r in got2] == [r.clusters for r in fresh2]

    def test_topology_level_overflow_uses_dense_everywhere(self):
        """Too many distinct taint sets for the cap: the whole topology
        schedules dense (vocab None)."""
        from kubeadmiral_tpu.models.types import Taint
        from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

        units, clusters = rich_world(b=16, c=8)
        spiky = [
            dataclasses.replace(
                cl, taints=(Taint(f"k{j}", f"v{j}", "PreferNoSchedule"),)
            )
            for j, cl in enumerate(clusters)
        ]
        tol = SchedulerEngine(
            chunk_size=32, min_bucket=8, vocab_caps={"taint_cap": 2}
        )
        got = tol.schedule(units, spiky)
        assert tol._chunk_cache[0].fmt == "dense"
        fresh = SchedulerEngine(chunk_size=32, min_bucket=8).schedule(
            units, spiky
        )
        assert [r.clusters for r in got] == [r.clusters for r in fresh]
