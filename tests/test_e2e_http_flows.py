"""Socket-level e2e for cluster lifecycle, auto-migration and scheduling
profiles (VERDICT r3 #8) — the flows the reference runs as e2e suites
(reference: test/e2e/federatedcluster/, test/e2e/automigration/,
test/e2e/schedulingprofile/), here driven against the kwok-lite farm:
every apiserver a real HTTP server, member clients built from join
secrets, watches over chunked streams.
"""

import json
import time

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.automigration import (
    POD_UNSCHEDULABLE_THRESHOLD,
    AutoMigrationController,
)
from kubeadmiral_tpu.federation.clusterctl import (
    CLUSTER_UID_ANNOTATION,
    FED_SYSTEM_NAMESPACE,
    FEDERATED_CLUSTERS,
    JOINED,
    NAMESPACES,
    NODES,
    FederatedClusterController,
    get_condition,
)
from kubeadmiral_tpu.federation.federate import FederateController
from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
from kubeadmiral_tpu.models import profile as PR
from kubeadmiral_tpu.models import types as T
from kubeadmiral_tpu.models.ftc import default_ftcs
from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm

from test_e2e_slice import deployment_ftc, make_deployment, make_node

PODS = "v1/pods"


from test_e2e_http_scale import settle as _settle_list


def settle_http(*controllers, timeout=60.0, grace=12):
    """Drive controllers to quiescence over async HTTP watches (shared
    deadline/idle-grace loop from the scale suite)."""
    _settle_list(controllers, timeout=timeout, grace=grace)


class _FarmTest:
    def setup_method(self):
        self.farm = KwokLiteFarm()
        self.fleet = self.farm.fleet

    def teardown_method(self):
        self.farm.close()

    def make_cluster(self, name, taints=None, conditions=None):
        obj = {
            "apiVersion": "core.kubeadmiral.io/v1alpha1",
            "kind": "FederatedCluster",
            "metadata": {"name": name},
            "spec": self.farm.cluster_spec(name),
        }
        if taints:
            obj["spec"]["taints"] = taints
        if conditions:
            obj["status"] = {"conditions": conditions}
        return obj


class TestClusterLifecycleHTTP(_FarmTest):
    """Join handshake, readiness + resource aggregation, removal —
    reference: test/e2e/federatedcluster/{join,clusterstatus,unjoin}.go,
    over real sockets with SA-token minting."""

    def test_join_collects_status_and_unjoin_cleans_up(self):
        gvk = "apps/v1/Deployment"
        ctl = FederatedClusterController(self.fleet, api_resource_probe=[gvk])
        member = self.farm.add_member("c1")
        member.create(NODES, make_node("n1", "48", "96Gi"))
        member.create(NODES, make_node("n2", "16", "32Gi"))
        self.fleet.host.create(FEDERATED_CLUSTERS, self.make_cluster("c1"))
        settle_http(ctl)

        cluster = self.fleet.host.get(FEDERATED_CLUSTERS, "c1")
        # Joined + Ready conditions (clusterjoin.go / clusterstatus.go).
        assert get_condition(cluster, JOINED)["status"] == "True"
        assert get_condition(cluster, "Ready")["status"] == "True"
        # The member-side system namespace is stamped with the cluster
        # UID — the ownership handshake that makes re-joins idempotent
        # and foreign ownership detectable.
        ns = member.get(NAMESPACES, FED_SYSTEM_NAMESPACE)
        assert ns["metadata"]["annotations"][CLUSTER_UID_ANNOTATION] == (
            cluster["metadata"]["uid"]
        )
        # Aggregated schedulable resources from the Node objects served
        # over HTTP (clusterstatus.go collectClusterStatus).
        res = cluster["status"]["resources"]
        assert res["schedulableNodes"] == 2
        assert res["allocatable"]["cpu"] in ("64", "64000m")
        # API types advertised through the FTC probe gate scheduling.
        assert gvk in cluster["status"]["apiResourceTypes"]

        # Unjoin: deleting the FederatedCluster runs the cleanup
        # finalizer — member system namespace removed, then the object
        # actually disappears from the host (federatedcluster_controller
        # handleTerminatingCluster).
        self.fleet.host.delete(FEDERATED_CLUSTERS, "c1")
        settle_http(ctl)
        assert self.fleet.host.try_get(FEDERATED_CLUSTERS, "c1") is None
        assert member.try_get(NAMESPACES, FED_SYSTEM_NAMESPACE) is None

    def test_unreachable_member_goes_not_ready(self):
        ctl = FederatedClusterController(
            self.fleet, api_resource_probe=[], clock=time.monotonic
        )
        member = self.farm.add_member("c1")
        member.create(NODES, make_node("n1", "8", "16Gi"))
        self.fleet.host.create(FEDERATED_CLUSTERS, self.make_cluster("c1"))
        settle_http(ctl)
        assert (
            get_condition(self.fleet.host.get(FEDERATED_CLUSTERS, "c1"), "Ready")[
                "status"
            ]
            == "True"
        )
        # Kill the member apiserver: the next heartbeat must flip the
        # cluster to not-Ready/offline instead of wedging the controller.
        self.farm.member_servers["c1"].close()
        ctl.worker.enqueue("c1")  # force the heartbeat now, not at resync
        settle_http(ctl)
        ready = get_condition(self.fleet.host.get(FEDERATED_CLUSTERS, "c1"), "Ready")
        assert ready["status"] != "True"


class TestAutoMigrationHTTP(_FarmTest):
    """Unschedulable pods in a member surface as estimatedCapacity on
    the federated object — reference: test/e2e/automigration/auto_migration.go,
    with the pod informer reading member pods over HTTP."""

    def test_stuck_pods_write_estimated_capacity(self):
        ftc = deployment_ftc()
        now = [1000.0]
        ctl = AutoMigrationController(self.fleet, ftc, clock=lambda: now[0])
        ready = [
            {"type": "Joined", "status": "True"},
            {"type": "Ready", "status": "True"},
        ]
        members = {}
        for name in ("c1", "c2"):
            members[name] = self.farm.add_member(name)
            self.fleet.host.create(
                FEDERATED_CLUSTERS, self.make_cluster(name, conditions=ready)
            )

        def member_deploy(desired, ready_reps):
            return {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {
                    "name": "web",
                    "namespace": "default",
                    "labels": {C.MANAGED_LABEL: "true"},
                },
                "spec": {
                    "replicas": desired,
                    "selector": {"matchLabels": {"app": "web"}},
                },
                "status": {"readyReplicas": ready_reps},
            }

        def pod(name, unschedulable):
            obj = {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": name,
                    "namespace": "default",
                    "labels": {"app": "web"},
                },
                "spec": {},
                "status": {"phase": "Pending"},
            }
            if unschedulable:
                obj["status"]["conditions"] = [
                    {
                        "type": "PodScheduled",
                        "status": "False",
                        "reason": "Unschedulable",
                        "lastTransitionTime": "1970-01-01T00:00:00Z",
                    }
                ]
            return obj

        members["c1"].create(ftc.source.resource, member_deploy(3, 1))
        members["c1"].create(PODS, pod("p1", True))
        members["c1"].create(PODS, pod("p2", True))
        members["c2"].create(ftc.source.resource, member_deploy(2, 2))

        fed = {
            "apiVersion": "types.kubeadmiral.io/v1alpha1",
            "kind": "FederatedDeployment",
            "metadata": {
                "name": "web",
                "namespace": "default",
                "annotations": {
                    pending.PENDING_CONTROLLERS: json.dumps([]),
                    POD_UNSCHEDULABLE_THRESHOLD: "30s",
                },
            },
            "spec": {
                "template": {"apiVersion": "apps/v1", "kind": "Deployment"},
                "placements": [
                    {
                        "controller": C.SCHEDULER,
                        "placement": [{"cluster": "c1"}, {"cluster": "c2"}],
                    }
                ],
            },
        }
        self.fleet.host.create(ftc.federated.resource, fed)
        now[0] += 60.0  # past the unschedulable threshold
        settle_http(ctl)

        got = self.fleet.host.get(ftc.federated.resource, "default/web")
        info = json.loads(got["metadata"]["annotations"][C.AUTO_MIGRATION_INFO])
        assert info["estimatedCapacity"] == {"c1": 1}


class TestSchedulingProfileHTTP(_FarmTest):
    """SchedulingProfile plugin-set switches observed through real
    placement — reference: test/e2e/schedulingprofile/."""

    def setup_method(self):
        super().setup_method()
        ftc = deployment_ftc(pipeline=(("kubeadmiral.io/global-scheduler",),))
        self.ftc = ftc
        gvk = "apps/v1/Deployment"
        self.clusterctl = FederatedClusterController(
            self.fleet, api_resource_probe=[gvk]
        )
        self.federate = FederateController(self.fleet.host, ftc)
        self.scheduler = SchedulerController(self.fleet.host, ftc)
        for name in ("c1", "c2", "c3"):
            member = self.farm.add_member(name)
            member.create(NODES, make_node("n1", "64", "128Gi"))
            taints = (
                [{"key": "dedicated", "value": "infra", "effect": "NoSchedule"}]
                if name == "c1"
                else None
            )
            self.fleet.host.create(
                FEDERATED_CLUSTERS, self.make_cluster(name, taints=taints)
            )

    def placement(self):
        fed = self.fleet.host.get(self.ftc.federated.resource, "default/web")
        return C.get_placement(fed, C.SCHEDULER)

    def test_profile_switch_admits_tainted_cluster(self):
        # Default profile: the taint filter excludes c1.
        self.fleet.host.create(
            PROPAGATION_POLICIES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "PropagationPolicy",
                "metadata": {"name": "pp", "namespace": "default"},
                "spec": {"schedulingMode": "Duplicate"},
            },
        )
        self.fleet.host.create(self.ftc.source.resource, make_deployment())
        settle_http(self.clusterctl, self.federate, self.scheduler)
        assert self.placement() == {"c2", "c3"}

        # A profile disabling the taint plugins re-schedules onto c1 too
        # (the profile generation is part of the trigger hash).
        self.fleet.host.create(
            PR.SCHEDULING_PROFILES,
            {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "SchedulingProfile",
                "metadata": {"name": "no-taints"},
                "spec": {
                    "plugins": {
                        "filter": {"disabled": [{"name": T.TAINT_TOLERATION}]},
                        "score": {"disabled": [{"name": T.TAINT_TOLERATION}]},
                    }
                },
            },
        )
        policy = self.fleet.host.get(PROPAGATION_POLICIES, "default/pp")
        policy["spec"]["schedulingProfile"] = "no-taints"
        self.fleet.host.update(PROPAGATION_POLICIES, policy)
        settle_http(self.clusterctl, self.federate, self.scheduler)
        assert self.placement() == {"c1", "c2", "c3"}
