#!/usr/bin/env python
"""obs-smoke: the fleet-observatory end-to-end check (`make obs-smoke`).

One subprocess kwok-farm round with telemetry spill on, then assemble
the merged cross-process trace and assert the propagation contract:

* the manager's ``dispatch.member_write`` span must have a server-side
  child span recorded in the MEMBER process's ring, under the same
  trace id, joined across the process boundary by the traceparent
  header (runtime/trace.py <-> transport/client.py <-> apiserver.py);
* both processes' spans land on one merged timeline via the wall-epoch
  anchor (tools/trace_assemble.py);
* spill segments survive member teardown and carry every fully-framed
  record (runtime/telespill.py);
* the fleet scraper merges every member's /metrics page with zero
  scrape errors (runtime/fleetscrape.py -> GET /debug/fleet).

Runs CPU-only in a few seconds; wired into `make test`.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main() -> int:
    tmpdir = tempfile.mkdtemp(prefix="kt-obs-smoke-")
    spill_dir = os.path.join(tmpdir, "telemetry")
    # Children inherit the env: member subprocesses spill their span
    # rings (the server-side halves) into the same directory.
    os.environ["KT_TELEMETRY_DIR"] = spill_dir
    os.environ.setdefault("KT_SPILL_INTERVAL_S", "0.2")

    from kubeadmiral_tpu.federation import dispatch
    from kubeadmiral_tpu.runtime import fleetscrape, telespill, trace
    from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm

    sys.path.insert(0, str(REPO / "tools"))
    import trace_assemble

    farm = KwokLiteFarm(member_subprocess=True)
    failures: list[str] = []
    try:
        farm.spawn_members(["m-0", "m-1"])
        clients = {name: farm.add_member(name) for name in ("m-0", "m-1")}

        # One member-write round per member, exactly the sync dispatch
        # shape: a dispatch.member_write span over run_member_batches
        # (whose pipelined chunks and HTTP requests must inherit it).
        deadline = time.monotonic() + 30.0
        for name, client in clients.items():
            ops = [
                {
                    "verb": "create",
                    "resource": "v1/configmaps",
                    "object": {
                        "apiVersion": "v1",
                        "kind": "ConfigMap",
                        "metadata": {"name": f"cm-{i}", "namespace": "default"},
                        "data": {"round": str(i)},
                    },
                }
                for i in range(8)
            ]
            with trace.span("dispatch.member_write", cluster=name, ops=len(ops)):
                results = dispatch.run_member_batches(
                    client, ops, deadline, cluster=name
                )
            bad = [r for r in results if r.get("code") not in (200, 201)]
            if bad:
                failures.append(f"{name}: {len(bad)} failed writes: {bad[:2]}")

        # Fleet pane: every member's /metrics merges with zero errors.
        scraper = fleetscrape.FleetScraper(roster=farm.scrape_roster)
        pane = scraper.scrape()
        if pane["scrape_errors"]:
            failures.append(f"fleet scrape errors: {pane}")
        for name in clients:
            inst = pane["instances"].get(name) or {}
            if not inst.get("up") or not inst.get("samples"):
                failures.append(f"fleet instance {name} not scraped: {inst}")

        # Spill the manager's ring, then give member spillers one
        # interval to persist their server-side spans.
        spiller = telespill.TelemetrySpiller(
            directory=spill_dir, instance="manager"
        )
        if spiller.spill_now() <= 0:
            failures.append("manager spill wrote no records")
        time.sleep(0.5)
    finally:
        farm.close()  # members final-spill on teardown

    merged_path = os.path.join(tmpdir, "merged.trace.json")
    doc = trace_assemble.assemble([spill_dir])
    with open(merged_path, "w") as fh:
        json.dump(doc, fh)
    summary = doc["summary"]

    if summary["lanes"] < 3:
        failures.append(
            f"expected >=3 process lanes (manager + 2 members), got "
            f"{summary['lanes']}: {summary['events_per_lane']}"
        )
    joins = [
        j
        for j in summary["join_examples"]
        if str(j["parent"]).startswith("dispatch.")
        and str(j["child"]).startswith("apiserver.")
    ]
    if summary["cross_process_joins"] < 1 or not joins:
        failures.append(
            "no cross-process dispatch->apiserver join in the merged "
            f"trace: {summary}"
        )

    if failures:
        print("obs-smoke FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        print(f"artifacts kept in {tmpdir}", file=sys.stderr)
        return 1
    join = joins[0]
    print(
        f"obs-smoke: ok — {summary['events']} events, "
        f"{summary['lanes']} lanes, {summary['cross_process_joins']} "
        f"cross-process joins (e.g. {join['parent']} -> {join['child']} "
        f"under trace {join['trace_id'][:8]}...)"
    )
    shutil.rmtree(tmpdir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
