#!/usr/bin/env python
"""bench-gate: fail when the latest BENCH artifact regresses.

Compares the newest ``BENCH_r*.json`` round against the best prior
round of the SAME metric and platform (a TPU number is never judged
against a cpu-fallback number):

* throughput (``parsed.value``, objects/s) must be at least
  ``(1 - tolerance) * best prior``;
* steady-state tick latency (``parsed.detail.tick_ms``) must be at most
  ``(1 + tolerance) * best prior`` (checked only when both rounds
  report it);
* device-stage latency (``stage_ms.device`` and
  ``drift_stage_ms.device``) gates the same way — a select/planner
  regression must fail here even when an unchanged tick total hides it
  behind fetch/decode wins (ISSUE 5);
* the end-to-end p99 event→placement-written latency
  (``BENCH_E2E*.json`` ``detail.slo.e2e_p99_ms``, ISSUE 13) gates as a
  latency ceiling with the gate_wait-style absolute slack
  (``gate_e2e``).

Rounds that failed to run (``rc != 0`` or no parsed value) are skipped;
with no comparable prior round the gate passes trivially.

Run as ``make bench-gate``.  Tolerance defaults to 10%; override with
``--tolerance`` or ``KT_BENCH_GATE_TOL`` (fraction, e.g. ``0.25``).
For an INTENTIONAL regression (e.g. trading throughput for a required
feature), run with ``KT_BENCH_GATE_TOL`` raised for that invocation and
record the rationale in the BENCH artifact/PR — the next round then
gates against the new best, not the pre-regression one.

Exit status: 0 pass, 1 regression, 2 malformed artifacts.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
_ROUND_RE = re.compile(r"^BENCH_r(\d+)\.json$")


def _platform_key(detail: dict) -> str:
    """The baseline key a round gates within: platform + device count
    (ISSUE 12).  A 4-device round must never gate against single-device
    baselines (its throughput story is different physics) nor seed
    them; artifacts predating the device_count field are single-device
    by construction (bench never forced host devices before ISSUE 12).
    The first round at a new (platform, device_count) triggers the
    PR-8 "NOTHING GATED" loud warning, exactly like a platform move."""
    platform = detail.get("platform") or "unknown"
    return f"{platform}/d{detail.get('device_count') or 1}"


def load_rounds(root: Path) -> list[dict]:
    """[{round, path, metric, platform, value, tick_ms}], skipping
    failed/unparseable rounds (with a note)."""
    rounds = []
    for path in sorted(root.glob("BENCH_r*.json")):
        m = _ROUND_RE.match(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench-gate: {path.name}: unreadable ({e})", file=sys.stderr)
            raise SystemExit(2)
        parsed = doc.get("parsed") or {}
        value = parsed.get("value")
        if doc.get("rc", 0) != 0 or value is None:
            print(f"bench-gate: skipping {path.name} (failed or no value)")
            continue
        detail = parsed.get("detail") or {}
        rounds.append(
            {
                "round": int(m.group(1)),
                "path": path.name,
                "metric": parsed.get("metric", ""),
                "platform": _platform_key(detail),
                "value": float(value),
                "tick_ms": detail.get("tick_ms"),
                # Gated when present (ISSUE 16): the per-tick obj/s
                # MEDIAN — robust to one outlier tick (GC pause, first
                # sub-batch compile) the run-mean value is not.
                "median": detail.get("objs_per_sec_median"),
                # Informational fields carried through (never gated, and
                # absent in pre-packed rounds): the fetch wire format and
                # per-tick transfer volume of the packed-export work, and
                # the full-revalidation latency of the megachunk+drift-
                # gate work (ISSUE 4).
                "fetch_format": detail.get("fetch_format"),
                "fetch_bytes": detail.get("fetch_bytes"),
                "tick_overflow": (detail.get("stage_ms") or {}).get(
                    "fetch_overflow_rows_tick"
                ),
                "drift_overflow": (detail.get("stage_ms") or {}).get(
                    "drift_overflow_rows"
                ),
                "narrow": detail.get("narrow"),
                # Informational (ISSUE 8): the dispatch ledger's
                # per-program device-time attribution — first landing is
                # informational; per-program gating can follow once a
                # few rounds carry it.
                "device_attr": detail.get("device_attr"),
                # GATED since ISSUE 10 (the replan/score-only work's
                # acceptance): the full drift-tick latency, with the
                # same absolute slack as gate_wait.
                "drift_tick_ms": (detail.get("stage_ms") or {}).get(
                    "drift_tick_ms"
                ),
                # GATED (ISSUE 10): the drift tick's featurize stage —
                # a silent return of full [B, C] re-featurization on
                # the drift path must fail here.
                "drift_featurize_ms": (
                    (detail.get("stage_ms") or {}).get("drift_stage_ms") or {}
                ).get("featurize"),
                # Informational: per-phase featurize_ms + rows split.
                "featurize_attr": (detail.get("stage_ms") or {}).get(
                    "featurize_attr"
                ),
                # Gated like tick_ms (lower is better): the heavy XLA
                # stages of the steady tick and of the drift recompute.
                "device_ms": (detail.get("stage_ms") or {}).get("device"),
                "drift_device_ms": (
                    (detail.get("stage_ms") or {}).get("drift_stage_ms") or {}
                ).get("device"),
                # GATED (not informational): the drift tick's gate-wait
                # attribution.  r08 measured 60.4s of a 98.8s c5 drift
                # tick blocked on gate compute; the streaming-scheduler
                # work drove it to ~0, and this gate keeps that
                # regression class from silently returning.
                "drift_gate_wait_ms": (
                    (detail.get("stage_ms") or {}).get("drift_stage_ms") or {}
                ).get("gate_wait"),
            }
        )
    rounds.sort(key=lambda r: r["round"])
    return rounds


def gate(rounds: list[dict], tolerance: float) -> int:
    if not rounds:
        print("bench-gate: no BENCH_r*.json artifacts; trivially ok")
        return 0
    latest = rounds[-1]
    priors = [
        r
        for r in rounds[:-1]
        if r["metric"] == latest["metric"]
        and r["platform"] == latest["platform"]
    ]
    if not priors:
        # Pass, but LOUDLY: nothing was actually gated this round (the
        # first artifact on a new platform — e.g. the first TPU round
        # after a CPU-only stretch — must not read as a green gate).
        print(
            f"bench-gate: WARNING: {latest['path']} "
            f"({latest['metric']}, platform={latest['platform']}) has no "
            f"prior same-platform baseline — NOTHING GATED this round; "
            f"this artifact becomes the baseline the next round gates "
            f"against"
        )
        return 0
    best_value = max(r["value"] for r in priors)
    floor = best_value * (1.0 - tolerance)
    ok = True
    print(
        f"bench-gate: {latest['path']} value={latest['value']:.1f} vs best "
        f"prior {best_value:.1f} (floor {floor:.1f}, tol {tolerance:.0%})"
    )
    if latest.get("fetch_format") is not None:
        prior_bytes = [
            r["fetch_bytes"] for r in priors if r.get("fetch_bytes") is not None
        ]
        note = (
            f" (best prior {min(prior_bytes)})" if prior_bytes else ""
        )
        print(
            f"bench-gate: fetch_format={latest['fetch_format']} "
            f"fetch_bytes={latest['fetch_bytes']}{note} — informational, "
            f"not gated"
        )
    if latest.get("tick_overflow") is not None or latest.get(
        "drift_overflow"
    ) is not None:
        print(
            f"bench-gate: overflow rows/tick={latest.get('tick_overflow')} "
            f"drift={latest.get('drift_overflow')} — adaptive-K watch, "
            f"informational"
        )
    if latest.get("featurize_attr"):
        fa = latest["featurize_attr"]
        print(
            "bench-gate: featurize_attr "
            + " ".join(
                f"{phase}={spec.get('ms')}ms/rows={spec.get('rows')}"
                for phase, spec in fa.items()
            )
            + " — cold/steady informational (drift gated below)"
        )
    median_priors = [
        r["median"] for r in priors if r.get("median") is not None
    ]
    if latest.get("median") is not None and median_priors:
        # Median-of-rounds gating (ISSUE 16): once both sides carry the
        # per-tick median, the throughput floor moves to median-vs-
        # median — one outlier tick can no longer sink or save a round
        # the way it could skew the run mean.  The mean stays printed
        # above, informational.
        best_median = max(median_priors)
        floor_median = best_median * (1.0 - tolerance)
        print(
            f"bench-gate: median objs/s {latest['median']:.1f} vs best "
            f"prior median {best_median:.1f} (floor {floor_median:.1f}) "
            f"— gating on MEDIAN; run-mean value is informational"
        )
        if latest["median"] < floor_median:
            print(
                f"bench-gate: THROUGHPUT REGRESSION (median): "
                f"{latest['median']:.1f} < {floor_median:.1f} — raise "
                f"KT_BENCH_GATE_TOL only for an intentional, documented "
                f"regression",
                file=sys.stderr,
            )
            ok = False
    elif latest["value"] < floor:
        print(
            f"bench-gate: THROUGHPUT REGRESSION: {latest['value']:.1f} < "
            f"{floor:.1f} — raise KT_BENCH_GATE_TOL only for an "
            f"intentional, documented regression",
            file=sys.stderr,
        )
        ok = False
    if latest.get("narrow") is not None:
        nr = latest["narrow"]
        print(
            f"bench-gate: narrow m={nr.get('m')} rows={nr.get('rows')} "
            f"fallback_rows={nr.get('fallback_rows')} — informational, "
            f"not gated"
        )
    if latest.get("device_attr"):
        da = latest["device_attr"]
        for phase in ("steady", "drift"):
            attr = da.get(phase) or {}
            if not attr.get("records"):
                continue
            progs = ", ".join(
                f"{k}={v.get('device_ms')}ms"
                for k, v in sorted(
                    (attr.get("by_program") or {}).items(),
                    key=lambda kv: -kv[1].get("device_ms", 0),
                )[:6]
            )
            print(
                f"bench-gate: device_attr[{phase}]: "
                f"device_ms={attr.get('device_ms')} "
                f"queue_ms={attr.get('queue_ms')} "
                f"reconcile={attr.get('reconcile_pct')}% of "
                f"stage device {attr.get('stage_device_ms')}ms; "
                f"per-program: {progs} — informational, not gated"
            )
    for key, label in (
        ("tick_ms", "tick_ms"),
        ("device_ms", "stage_ms.device"),
        ("drift_device_ms", "drift_stage_ms.device"),
        ("drift_gate_wait_ms", "drift_stage_ms.gate_wait"),
        ("drift_tick_ms", "drift_tick_ms"),
        ("drift_featurize_ms", "drift_stage_ms.featurize"),
    ):
        prior_vals = [r.get(key) for r in priors if r.get(key) is not None]
        if latest.get(key) is None:
            continue
        if not prior_vals:
            # The satellite fix (ISSUE 8): a gated metric with no prior
            # same-platform baseline must WARN, not silently skip — the
            # first TPU round after a CPU stretch carries gated metrics
            # that nothing checks.
            print(
                f"bench-gate: WARNING: {label}={latest[key]:.1f} has no "
                f"prior same-platform baseline — not gated this round"
            )
            continue
        best = min(prior_vals)
        ceil = best * (1.0 + tolerance)
        if key in ("drift_gate_wait_ms", "drift_tick_ms", "drift_featurize_ms"):
            # These sit near zero (gate_wait) or in the hundreds of ms
            # at small configs once the survivor paths land; a pure
            # percentage ceiling would fail on timer jitter.  The
            # absolute slack still catches the regression classes the
            # gates exist for (60.4s gate_wait at r08, 50.4s drift tick
            # at r09, multi-second full re-featurizes) by 1-2 orders of
            # magnitude.
            ceil += 250.0
        print(
            f"bench-gate: {label}={latest[key]:.1f} vs best prior "
            f"{best:.1f} (ceiling {ceil:.1f})"
        )
        if latest[key] > ceil:
            print(
                f"bench-gate: LATENCY REGRESSION: {label} "
                f"{latest[key]:.1f}ms > {ceil:.1f}ms",
                file=sys.stderr,
            )
            ok = False
    print("bench-gate: ok" if ok else "bench-gate: FAILED")
    return 0 if ok else 1


_CHURN_RE = re.compile(r"^BENCH_CHURN_r(\d+)\.json$")


def gate_churn(root: Path, tolerance: float) -> int:
    """Gate the sustained-churn scenario artifacts (BENCH_CHURN_r*.json,
    written by ``make bench-churn``): sustained objects-revalidated/s is
    gated like the main throughput metric; event->placement latency p99
    is GATED (promoted from the PR-7 first-landing informational state:
    best-prior ceiling + the gate_wait-style absolute slack), as is the
    per-flush featurize cost (informational only until a prior round
    carries it)."""
    rounds = []
    for path in sorted(root.glob("BENCH_CHURN_r*.json")):
        m = _CHURN_RE.match(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench-gate: {path.name}: unreadable ({e})", file=sys.stderr)
            return 2
        parsed = doc.get("parsed") or {}
        if doc.get("rc", 0) != 0 or parsed.get("value") is None:
            continue
        detail = parsed.get("detail") or {}
        rounds.append(
            {
                "round": int(m.group(1)),
                "path": path.name,
                "metric": parsed.get("metric", ""),
                "platform": _platform_key(detail),
                "value": float(parsed["value"]),
                "p99": detail.get("latency_ms_p99"),
                "featurize": detail.get("featurize_per_flush_ms"),
                "featurize_rows": detail.get("featurize_rows"),
                # ISSUE 11: the unified-kernel shape block + the
                # stale-repair phase split (informational), and the
                # marker that this round ran the unified-kernel code
                # (the hard absolute gates key off its presence).
                "survivor_kernel": detail.get("survivor_kernel"),
                "stale_repair_rows": detail.get("stale_repair_rows"),
            }
        )
    if not rounds:
        return 0
    rounds.sort(key=lambda r: r["round"])
    latest = rounds[-1]
    priors = [
        r
        for r in rounds[:-1]
        if r["metric"] == latest["metric"]
        and r["platform"] == latest["platform"]
    ]
    if not priors:
        print(
            f"bench-gate: {latest['path']} ({latest['metric']}) has no "
            f"comparable prior churn round; informational only"
        )
        return 0
    ok = True
    best = max(r["value"] for r in priors)
    floor = best * (1.0 - tolerance)
    print(
        f"bench-gate: churn {latest['path']} value={latest['value']:.1f} "
        f"vs best prior {best:.1f} (floor {floor:.1f})"
    )
    if latest["value"] < floor:
        print(
            f"bench-gate: CHURN THROUGHPUT REGRESSION: "
            f"{latest['value']:.1f} < {floor:.1f}",
            file=sys.stderr,
        )
        ok = False
    for key, label in (("p99", "latency_ms_p99"),
                       ("featurize", "featurize_per_flush_ms")):
        prior_vals = [r[key] for r in priors if r.get(key) is not None]
        if latest.get(key) is None:
            continue
        if prior_vals:
            ceil = min(prior_vals) * (1.0 + tolerance) + 250.0
            print(
                f"bench-gate: churn {label}={latest[key]:.1f} vs "
                f"best prior {min(prior_vals):.1f} (ceiling {ceil:.1f})"
            )
            if latest[key] > ceil:
                print(
                    f"bench-gate: CHURN LATENCY REGRESSION: {label} "
                    f"{latest[key]:.1f}ms > {ceil:.1f}ms",
                    file=sys.stderr,
                )
                ok = False
        else:
            print(
                f"bench-gate: churn {label}={latest[key]:.1f} — "
                f"informational (first round carrying it)"
            )
    if latest.get("featurize_rows") is not None:
        print(
            f"bench-gate: churn featurize_rows={latest['featurize_rows']} "
            f"— delta-only expected mid-stream, informational"
        )
    if latest.get("survivor_kernel") is not None:
        sk = latest["survivor_kernel"]
        print(
            f"bench-gate: churn survivor_kernel rows={sk.get('rows')} "
            f"groups={sk.get('groups')} "
            f"padding_ratio={sk.get('padding_ratio')} "
            f"fallback_rows={sk.get('fallback_rows')} "
            f"stale_repair={latest.get('stale_repair_rows')} — "
            f"informational"
        )
        # HARD absolute gates (ISSUE 11) — engaged only for rounds that
        # carry the unified-kernel block (older artifacts predate the
        # work and must not retro-fail).  The throughput floor is 3x the
        # r03 baseline of 11031 obj/s at the bench-churn config; the
        # p99 ceiling holds the r03 value + slack.  KT_CHURN_FLOOR /
        # KT_CHURN_P99_CEIL_MS override (0 disables).
        hard_floors = {"churn_objs_per_sec_4096x256": 3.0 * 11031.0}
        hard_floor = float(
            os.environ.get(
                "KT_CHURN_FLOOR",
                str(hard_floors.get(latest["metric"], 0.0)),
            )
        )
        p99_ceil = float(os.environ.get("KT_CHURN_P99_CEIL_MS", "3000"))
        if hard_floor > 0:
            print(
                f"bench-gate: churn HARD floor "
                f"{latest['value']:.1f} >= {hard_floor:.1f} obj/s"
            )
            if latest["value"] < hard_floor:
                print(
                    f"bench-gate: CHURN HARD-FLOOR FAILURE: "
                    f"{latest['value']:.1f} < {hard_floor:.1f} obj/s "
                    f"(3x the r03 baseline; KT_CHURN_FLOOR overrides)",
                    file=sys.stderr,
                )
                ok = False
        if p99_ceil > 0 and latest.get("p99") is not None:
            print(
                f"bench-gate: churn HARD p99 ceiling "
                f"{latest['p99']:.1f} <= {p99_ceil:.1f} ms"
            )
            if latest["p99"] > p99_ceil:
                print(
                    f"bench-gate: CHURN HARD-P99 FAILURE: "
                    f"{latest['p99']:.1f}ms > {p99_ceil:.1f}ms "
                    f"(KT_CHURN_P99_CEIL_MS overrides)",
                    file=sys.stderr,
                )
                ok = False
    return 0 if ok else 1


_RESTART_RE = re.compile(r"^BENCH_RESTART_r(\d+)\.json$")


def gate_restart(root: Path, tolerance: float) -> int:
    """Gate the restart-to-first-tick scenario artifacts
    (BENCH_RESTART_r*.json, written by ``make bench-restart``): the
    warm ``restart_to_first_tick_ms`` value is gated like a latency
    (ceiling vs the best prior same-metric+platform round, plus a
    250 ms absolute slack for timer jitter); snapshot size / write-ms
    and the AOT program counts are carried informationally.  A warm
    boot that silently stopped loading AOT programs or parity-failed
    fails OUTRIGHT, prior round or not."""
    rounds = []
    for path in sorted(root.glob("BENCH_RESTART_r*.json")):
        m = _RESTART_RE.match(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench-gate: {path.name}: unreadable ({e})", file=sys.stderr)
            return 2
        parsed = doc.get("parsed") or {}
        if doc.get("rc", 0) != 0 or parsed.get("value") is None:
            continue
        detail = parsed.get("detail") or {}
        rounds.append(
            {
                "round": int(m.group(1)),
                "path": path.name,
                "metric": parsed.get("metric", ""),
                "platform": _platform_key(detail),
                "device_count": detail.get("device_count") or 1,
                "multidevice": detail.get("multidevice"),
                "value": float(parsed["value"]),
                "cold_boot_ms": detail.get("cold_boot_ms"),
                "ratio": detail.get("warm_vs_cold_pct"),
                "snapshot_bytes": detail.get("snapshot_bytes"),
                "snapshot_write_ms": detail.get("snapshot_write_ms"),
                "aot": detail.get("aot"),
                "parity": detail.get("parity"),
                "memory": detail.get("memory"),
            }
        )
    if not rounds:
        return 0
    rounds.sort(key=lambda r: r["round"])
    latest = rounds[-1]
    ok = True
    print(
        f"bench-gate: restart {latest['path']} "
        f"restart_to_first_tick_ms={latest['value']:.1f} "
        f"(cold {latest['cold_boot_ms']}, {latest['ratio']}% of cold); "
        f"snapshot {latest['snapshot_bytes']}B / "
        f"{latest['snapshot_write_ms']}ms write, aot={latest['aot']} — "
        f"snapshot/aot informational"
    )
    if latest.get("memory"):
        mem = latest["memory"]
        print(
            f"bench-gate: restart memory: warm peak RSS "
            f"{mem.get('warm_peak_rss_mb')}MB vs cold "
            f"{mem.get('cold_peak_rss_mb')}MB, device buffers "
            f"{mem.get('warm_device_buffer_bytes')}B vs "
            f"{mem.get('cold_device_buffer_bytes')}B — the AOT "
            f"no-donation cost, informational"
        )
    if latest.get("parity") is False:
        print("bench-gate: RESTART PARITY FAILURE", file=sys.stderr)
        ok = False
    aot = latest.get("aot") or {}
    if latest.get("device_count", 1) > 1:
        # Multi-device topology: AOT is live-trace-only BY DESIGN
        # (exports pin topology — scheduler/aot.py), so traced>0 /
        # loaded=0 is the honest expected shape, not a regression.
        print(
            f"bench-gate: restart at device_count="
            f"{latest['device_count']}: AOT live-trace-only by design "
            f"(traced={aot.get('traced')}, loaded={aot.get('loaded')}) "
            f"— preload check not applicable"
        )
    elif aot.get("loaded", 0) == 0 or aot.get("traced", 0) > 0:
        print(
            f"bench-gate: RESTART AOT REGRESSION: warm boot traced "
            f"{aot.get('traced')} program(s), loaded {aot.get('loaded')} — "
            f"the trace ladder is back on the failover path",
            file=sys.stderr,
        )
        ok = False
    if latest.get("multidevice"):
        md = latest["multidevice"]
        if md.get("error"):
            print(
                f"bench-gate: restart multidevice probe errored: "
                f"{md['error']} — informational",
            )
        else:
            print(
                f"bench-gate: restart multidevice probe: "
                f"N={md.get('device_count')} "
                f"warm_boot_ms={md.get('warm_boot_ms')} "
                f"(prewarm {md.get('prewarm_s')}s, first tick "
                f"{md.get('first_tick_ms')}ms, aot={md.get('aot')}) — "
                f"the live-trace ladder cost a multi-device failover "
                f"pays; informational"
            )
    priors = [
        r for r in rounds[:-1]
        if r["metric"] == latest["metric"] and r["platform"] == latest["platform"]
    ]
    if not priors:
        print(
            f"bench-gate: WARNING: {latest['path']} ({latest['metric']}) has "
            f"no prior same-platform baseline — value not gated this round"
        )
        return 0 if ok else 1
    best = min(r["value"] for r in priors)
    ceil = best * (1.0 + tolerance) + 250.0
    print(
        f"bench-gate: restart_to_first_tick_ms={latest['value']:.1f} vs "
        f"best prior {best:.1f} (ceiling {ceil:.1f})"
    )
    if latest["value"] > ceil:
        print(
            f"bench-gate: RESTART LATENCY REGRESSION: "
            f"{latest['value']:.1f}ms > {ceil:.1f}ms",
            file=sys.stderr,
        )
        ok = False
    return 0 if ok else 1


_CENSUS_RE = re.compile(r"^BENCH_CENSUS_r(\d+)\.json$")


def gate_census(root: Path) -> int:
    """Gate the c6 memory-census artifacts (BENCH_CENSUS_r*.json,
    written by ``bench.py --scenario census``): the RESOLVED
    configuration (compression and/or sharding engaged) must be under
    the HBM budget, and the model must validate against the live
    engine — either failing fails the round.  The raw verdict /
    per-device numbers are surfaced every round."""
    latest = None
    for path in sorted(root.glob("BENCH_CENSUS_r*.json")):
        if not _CENSUS_RE.match(path.name):
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench-gate: {path.name}: unreadable ({e})", file=sys.stderr)
            return 2
        parsed = doc.get("parsed") or {}
        if doc.get("rc", 0) != 0 or parsed.get("value") is None:
            continue
        latest = (path.name, parsed)
    if latest is None:
        return 0
    name, parsed = latest
    detail = parsed.get("detail") or {}
    decision = detail.get("decision") or {}
    validation = detail.get("validation") or {}
    gib = 1 << 30
    print(
        f"bench-gate: census {name} shape={detail.get('census_shape')} "
        f"verdict={decision.get('verdict')} "
        f"resolved per_device={parsed['value'] / gib:.2f}GiB @"
        f"{decision.get('min_devices')}dev "
        f"(budget {detail.get('budget_gb')}GiB, requested "
        f"{detail.get('requested_devices')}dev: "
        f"i32 {(decision.get('per_device_i32') or 0) / gib:.2f} / "
        f"f16 {(decision.get('per_device_f16') or 0) / gib:.2f}GiB; "
        f"model err {validation.get('prev_planes_err_pct')}%)"
    )
    ok = True
    if detail.get("over_budget"):
        print(
            f"bench-gate: CENSUS OVER BUDGET: the resolved configuration "
            f"({parsed['value'] / gib:.2f}GiB/device) exceeds "
            f"{detail.get('budget_gb')}GiB — no compress-or-shard "
            f"configuration fits; raise KT_HBM_BUDGET_GB only for real "
            f"hardware",
            file=sys.stderr,
        )
        ok = False
    if validation.get("ok") is False:
        print(
            f"bench-gate: CENSUS MODEL INVALID: live-vs-model prev-plane "
            f"error {validation.get('prev_planes_err_pct')}% exceeds "
            f"tolerance — the projection cannot be trusted",
            file=sys.stderr,
        )
        ok = False
    return 0 if ok else 1


_E2E_RE = re.compile(r"^BENCH_E2E(?:_[A-Z]+)?_r(\d+)\.json$")


def _e2e_baseline_key(detail: dict, metric: str) -> str:
    """The baseline key an e2e round gates within: platform/devices +
    TRANSPORT + MEMBER COUNT, the way device_count was folded in for
    engine rounds (ISSUE 15) — a 500-member HTTP-farm round must never
    gate against (or silently seed) an in-process 50-member baseline.
    Artifacts predating the ``members`` detail field derive it from the
    NxC metric suffix; the first round at a new (transport, members)
    key trips the loud NOTHING-GATED warning, exactly like a platform
    move."""
    transport = detail.get("transport") or (
        "http" if metric.endswith("_http") else "inproc"
    )
    members = detail.get("members")
    if members is None:
        m = re.search(r"_(\d+)x(\d+)", metric)
        members = m.group(2) if m else "unknown"
    # Shard-count axis (ISSUE 20): an N=4 sharded control plane must
    # never gate against (or seed) an unsharded baseline.  Artifacts
    # predating the field are unsharded by construction → s1.
    shards = detail.get("shards") or 1
    return f"{_platform_key(detail)}/{transport}/m{members}/s{shards}"


def gate_e2e(root: Path, tolerance: float) -> int:
    """Gate the end-to-end p99 event→placement-written latency
    (BENCH_E2E*_r*.json, ``detail.slo.e2e_p99_ms`` — ISSUE 13): ceiling
    vs the best prior same-metric+platform round carrying it, with the
    gate_wait-style 250 ms absolute slack for timer jitter.  Rounds
    predating the SLO layer carry no block and are skipped as priors;
    the first round that DOES carry it passes with the loud
    NOTHING-GATED warning and becomes the baseline.  Throughput and the
    stage split are surfaced informationally."""
    rounds = []
    for path in sorted(root.glob("BENCH_E2E*.json")):
        m = _E2E_RE.match(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench-gate: {path.name}: unreadable ({e})", file=sys.stderr)
            return 2
        parsed = doc.get("parsed") or {}
        value = parsed.get("value")
        detail = parsed.get("detail") or doc.get("detail") or {}
        metric = parsed.get("metric") or doc.get("metric") or ""
        if value is None:
            value = doc.get("value")
        if doc.get("rc", 0) != 0 or value is None:
            continue
        slo = detail.get("slo") or {}
        rounds.append(
            {
                "round": int(m.group(1)),
                "path": path.name,
                "metric": metric,
                "platform": _e2e_baseline_key(detail, metric),
                "value": float(value),
                "p99": slo.get("e2e_p99_ms"),
                "p50": slo.get("e2e_p50_ms"),
                "decomp_err": slo.get("decomposition_err_pct"),
                "stages": slo.get("stages_ms"),
                "transport": detail.get("transport", "inproc"),
                "sync_s": (detail.get("stages_s") or {}).get("sync"),
                # Same-day re-baseline (see gate logic below): p99 of the
                # PRIOR code re-measured on the machine state that also
                # produced this round.
                "same_day_p99": (detail.get("same_day_ab") or {}).get(
                    "baseline_e2e_p99_ms"
                ),
                "shards": detail.get("shards") or 1,
                "ab": detail.get("sharded_ab"),
            }
        )
    if not rounds:
        return 0
    rounds.sort(key=lambda r: r["round"])
    # Gate the LATEST round of every (metric, transport/members) group:
    # an inproc round and a scaled HTTP-farm round landing together each
    # gate against their own baselines (and a first round at a new key
    # trips its own loud NOTHING-GATED warning, never silence).
    groups: dict[tuple[str, str], list[dict]] = {}
    for r in rounds:
        groups.setdefault((r["metric"], r["platform"]), []).append(r)
    # Sharded speedup ladder (ISSUE 20): the latest same-day interleaved
    # speedup per (metric, key-without-sN, shards).  Speedups compare
    # safely across days — each one is internally same-day — so N=4 is
    # held to at least N=2's multiplier even when benched on different
    # machine weather.
    ab_speedups: dict[tuple[str, str, int], float] = {}
    for (metric, platform), group in groups.items():
        latest = group[-1]
        ab = latest.get("ab") or {}
        if ab.get("speedup") is not None:
            base = re.sub(r"/s\d+$", "", platform)
            ab_speedups[(metric, base, latest["shards"])] = ab["speedup"]
    ok = True
    for (metric, platform), group in sorted(groups.items()):
        latest = group[-1]
        if latest["p99"] is None:
            print(
                f"bench-gate: {latest['path']} ({metric}) carries no "
                f"detail.slo block (pre-SLO round) — e2e p99 not gated"
            )
            continue
        print(
            f"bench-gate: e2e {latest['path']} [{platform}] "
            f"value={latest['value']:.1f} objects/s, event→written "
            f"p50={latest['p50']}ms p99={latest['p99']:.1f}ms "
            f"(decomposition err {latest['decomp_err']}%) — throughput "
            f"informational"
        )
        if latest.get("stages"):
            print(
                "bench-gate: e2e stage p99 ms: "
                + " ".join(
                    f"{stage}={spec.get('p99')}"
                    for stage, spec in latest["stages"].items()
                )
            )
        ab = latest.get("ab")
        if ab:
            med = ab.get("arm_medians") or {}
            speedup = ab.get("speedup")
            parity = ab.get("parity") or {}
            print(
                f"bench-gate: e2e sharded A/B [{platform}] arm medians "
                f"{med} objects/s over {ab.get('pairs')} interleaved "
                f"pair(s) — speedup {speedup}x, parity {parity}"
            )
            # Correctness before speed: the union of N shards' scheduler
            # output (placements AND flight-recorder reason counts) must
            # be bit-identical to the unsharded oracle.
            for dim in ("placements", "reasons"):
                got = parity.get(dim)
                if got not in ("bit-identical", "not-recorded"):
                    print(
                        f"bench-gate: SHARDED PARITY BROKEN [{platform}]: "
                        f"{dim} parity is {got!r} — the sharded control "
                        f"plane diverged from the unsharded oracle",
                        file=sys.stderr,
                    )
                    ok = False
            # Parallel speedup needs parallel hardware: on a host with
            # fewer runnable cores than shard replicas (this container
            # pins to 1), the GIL-threaded replica drains serialize and
            # N stacks can only cost overhead.  Gate that the overhead
            # is BOUNDED there (N=2 may not fall below 0.5x, N>2 below
            # 0.35x) instead of demanding a physically impossible 1.4x;
            # parity above stays hard either way.
            cores = ab.get("cpu_cores") or 0
            starved = cores and cores < latest["shards"]
            if starved and "/http/" in platform:
                # Subprocess replicas over the HTTP farm on a starved
                # host: N whole controller-stack PROCESSES time-share
                # the core(s) with the farm and the host apiserver, so
                # even an overhead floor has no stable meaning (a 2x
                # time-slice tax is the OS scheduler, not the sharding
                # layer).  Parity above stays the hard gate; throughput
                # is reported informationally.
                print(
                    f"bench-gate: NOTE [{platform}]: host has {cores} "
                    f"core(s) for {latest['shards']} subprocess shard "
                    f"replicas + farm — speedup/overhead floors WAIVED "
                    f"(informational: {speedup}x); parity still "
                    f"hard-gated"
                )
            elif starved:
                floor = 0.5 if latest["shards"] == 2 else 0.35
                print(
                    f"bench-gate: NOTE [{platform}]: host has {cores} "
                    f"core(s) for {latest['shards']} shard replicas — "
                    f"parallel speedup floor (1.4x) WAIVED, gating "
                    f"bounded overhead (floor {floor}x) instead; parity "
                    f"still hard-gated"
                )
                if speedup is not None and speedup < floor:
                    print(
                        f"bench-gate: SHARDED OVERHEAD REGRESSION "
                        f"[{platform}]: N={latest['shards']} delivers "
                        f"{speedup}x on a {cores}-core host (overhead "
                        f"floor {floor}x) — replica bookkeeping is "
                        f"eating more than the core-starved budget",
                        file=sys.stderr,
                    )
                    ok = False
            elif (
                speedup is not None
                and latest["shards"] == 2
                and speedup < 1.4
            ):
                print(
                    f"bench-gate: SHARDED SPEEDUP REGRESSION [{platform}]: "
                    f"N=2 delivers {speedup}x over the same-day interleaved "
                    f"N=1 median (floor 1.4x)",
                    file=sys.stderr,
                )
                ok = False
            elif speedup is not None and latest["shards"] > 2:
                base = re.sub(r"/s\d+$", "", platform)
                s2 = ab_speedups.get((metric, base, 2))
                if s2 is None:
                    print(
                        f"bench-gate: WARNING: {latest['path']} "
                        f"(key={platform}) has no N=2 round to ladder "
                        f"against — speedup monotonicity not gated"
                    )
                elif speedup < s2:
                    print(
                        f"bench-gate: SHARDED SCALING REGRESSION "
                        f"[{platform}]: N={latest['shards']} speedup "
                        f"{speedup}x fell below N=2's {s2}x — extra "
                        f"replicas made the control plane slower",
                        file=sys.stderr,
                    )
                    ok = False
        priors = [r for r in group[:-1] if r.get("p99") is not None]
        if not priors:
            print(
                f"bench-gate: WARNING: {latest['path']} ({metric}, "
                f"key={platform}) has no prior round carrying e2e p99 — "
                f"NOTHING GATED for this key; this artifact becomes the "
                f"baseline the next round gates against"
            )
            continue
        best = min(r["p99"] for r in priors)
        # Wall-clock gates on a shared machine need a re-baselining
        # protocol: when the round records a SAME-DAY re-measurement of
        # the prior code (detail.same_day_ab.baseline_e2e_p99_ms, i.e.
        # the pre-change tree benched back-to-back with this round) that
        # is SLOWER than the stale best prior, the stale absolute is not
        # reproducible on this machine state and the same-day number is
        # the honest ceiling base.  A same-day baseline FASTER than the
        # best prior never loosens the gate.
        if latest.get("same_day_p99") is not None and latest["same_day_p99"] > best:
            print(
                f"bench-gate: e2e [{platform}] same-day re-baseline: "
                f"prior-code p99 re-measures at "
                f"{latest['same_day_p99']:.1f}ms today (stale best prior "
                f"{best:.1f}ms not reproducible on this machine state)"
            )
            best = latest["same_day_p99"]
        ceil = best * (1.0 + tolerance) + 250.0
        print(
            f"bench-gate: e2e p99={latest['p99']:.1f}ms vs best prior "
            f"{best:.1f}ms (ceiling {ceil:.1f})"
        )
        if latest["p99"] > ceil:
            print(
                f"bench-gate: E2E P99 REGRESSION [{platform}]: "
                f"{latest['p99']:.1f}ms > {ceil:.1f}ms — the "
                f"event→placement-written SLO regressed",
                file=sys.stderr,
            )
            ok = False
        # Inproc sync-stage wall clock (ISSUE 18): the store/notify
        # rewrite's e2e claim is that sync stops being the largest
        # inproc stage — hold the line with a ceiling vs the best prior
        # round carrying the split (same gate_wait-style absolute slack
        # for timer jitter).
        if latest.get("sync_s") is not None and latest["transport"] != "http":
            sync_priors = [
                r["sync_s"] for r in group[:-1] if r.get("sync_s") is not None
            ]
            if not sync_priors:
                print(
                    f"bench-gate: WARNING: {latest['path']} ({metric}, "
                    f"key={platform}) has no prior round carrying "
                    f"stages.sync — sync stage NOTHING GATED this round"
                )
            else:
                best_sync = min(sync_priors)
                sync_ceil = best_sync * (1.0 + tolerance) + 0.25
                print(
                    f"bench-gate: e2e inproc sync stage "
                    f"{latest['sync_s']:.2f}s vs best prior "
                    f"{best_sync:.2f}s (ceiling {sync_ceil:.2f})"
                )
                if latest["sync_s"] > sync_ceil:
                    print(
                        f"bench-gate: SYNC STAGE REGRESSION [{platform}]: "
                        f"{latest['sync_s']:.2f}s > {sync_ceil:.2f}s — the "
                        f"store/notify hot path regressed",
                        file=sys.stderr,
                    )
                    ok = False
    return 0 if ok else 1


_STORE_RE = re.compile(r"^BENCH_STORE_r(\d+)\.json$")


def gate_store(root: Path, tolerance: float) -> int:
    """Gate the store/notify microbench artifacts (BENCH_STORE_r*.json,
    written by ``make bench-store`` — ISSUE 18): columnar batch writes/s
    floors and notify fan-out µs/event ceilings against the best
    same-platform prior.  The first landing trips the loud
    NOTHING-GATED warning and seeds the baseline."""
    rounds = []
    for path in sorted(root.glob("BENCH_STORE_r*.json")):
        m = _STORE_RE.match(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench-gate: {path.name}: unreadable ({e})", file=sys.stderr)
            return 2
        value = doc.get("value")
        detail = doc.get("detail") or {}
        if value is None:
            continue
        rounds.append(
            {
                "round": int(m.group(1)),
                "path": path.name,
                "platform": _platform_key(detail),
                "value": float(value),
                "notify_us": detail.get("notify_us_per_event"),
            }
        )
    if not rounds:
        return 0
    rounds.sort(key=lambda r: r["round"])
    latest = rounds[-1]
    priors = [
        r for r in rounds[:-1] if r["platform"] == latest["platform"]
    ]
    print(
        f"bench-gate: store {latest['path']} [{latest['platform']}] "
        f"batch={latest['value']:.0f} writes/s "
        f"notify={latest['notify_us']}µs/event"
    )
    if not priors:
        print(
            f"bench-gate: WARNING: {latest['path']} "
            f"(platform={latest['platform']}) has no prior same-platform "
            f"store round — NOTHING GATED this round; this artifact "
            f"becomes the baseline the next round gates against"
        )
        return 0
    ok = True
    best = max(r["value"] for r in priors)
    floor = best * (1.0 - tolerance)
    print(
        f"bench-gate: store writes/s {latest['value']:.0f} vs best prior "
        f"{best:.0f} (floor {floor:.0f})"
    )
    if latest["value"] < floor:
        print(
            f"bench-gate: STORE THROUGHPUT REGRESSION: "
            f"{latest['value']:.0f} < {floor:.0f} writes/s — the columnar "
            f"commit path regressed",
            file=sys.stderr,
        )
        ok = False
    notify_priors = [
        r["notify_us"] for r in priors if r.get("notify_us") is not None
    ]
    if latest.get("notify_us") is not None and notify_priors:
        best_us = min(notify_priors)
        ceil_us = best_us * (1.0 + tolerance) + 1.0  # +1µs timer slack
        print(
            f"bench-gate: store notify {latest['notify_us']}µs/event vs "
            f"best prior {best_us} (ceiling {ceil_us:.3f})"
        )
        if latest["notify_us"] > ceil_us:
            print(
                f"bench-gate: STORE NOTIFY REGRESSION: "
                f"{latest['notify_us']}µs/event > {ceil_us:.3f} — watch "
                f"fan-out cost regressed",
                file=sys.stderr,
            )
            ok = False
    return 0 if ok else 1


def report_e2e_chaos(root: Path) -> None:
    """Informational: surface the newest e2e artifact's degraded-fleet
    (chaos) numbers — tick-stall p99 and shed-write counts — next to
    the gate output.  Never gated: chaos rounds measure fault handling,
    not steady-state throughput."""
    candidates = sorted(
        root.glob("BENCH_E2E*.json"), key=lambda p: p.stat().st_mtime
    )
    for path in reversed(candidates):
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        detail = (doc.get("parsed") or {}).get("detail") or doc.get("detail") or {}
        chaos = detail.get("chaos")
        if not chaos:
            continue
        if chaos.get("skipped"):
            print(f"bench-gate: {path.name} chaos skipped ({chaos['skipped']})")
            return
        print(
            f"bench-gate: {path.name} chaos: down={chaos.get('down_member')} "
            f"flap={chaos.get('flapping_member')} "
            f"stall_p99_s={chaos.get('stall_p99_s')} "
            f"shed_writes={chaos.get('shed_writes')} "
            f"breaker_opens={chaos.get('breaker_opens')} — informational, "
            f"not gated"
        )
        return


_SOAK_RE = re.compile(r"^SOAK_r(\d+)\.json$")


def gate_soak(root: Path, tolerance: float) -> int:
    """Gate the all-stressors soak (ISSUE 16, SOAK_r<n>.json from
    ``bench.py --scenario soak``).

    Two properties fail OUTRIGHT, with or without priors — they are
    correctness claims, not perf trends:

    * ``oracle_match`` — the post-failover placements must be
      bit-identical to the uninterrupted oracle run's;
    * ``red_outside_windows`` — the burn-rate evaluator must never be
      red outside a declared fault-injection window (evaluated from the
      recorded telemetry timeline of BOTH the killed victim and the
      successor).

    Against best prior same-platform rounds: soak obj/s floors at
    best*(1-tol); event-to-written p99 ceilings at min*(1+tol) plus the
    same 250ms absolute slack the other latency gates use (the soak's
    p99 is dominated by fault-window stalls, deliberately).  The first
    landing trips the loud NOTHING-GATED warning."""
    rounds = []
    for path in sorted(root.glob("SOAK_r*.json")):
        m = _SOAK_RE.match(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench-gate: {path.name}: unreadable ({e})", file=sys.stderr)
            raise SystemExit(2)
        parsed = doc.get("parsed") or {}
        if doc.get("rc", 0) != 0 or parsed.get("value") is None:
            print(f"bench-gate: skipping {path.name} (failed or no value)")
            continue
        detail = parsed.get("detail") or {}
        rounds.append(
            {
                "round": int(m.group(1)),
                "path": path.name,
                "metric": parsed.get("metric", ""),
                # Shards fold into the soak baseline key exactly like
                # the e2e key (ISSUE 20): a 2-replica soak runs two
                # whole control-plane processes, so its obj/s never
                # gates against (or seeds) the unsharded baseline.
                # Pre-sharding artifacts are s1 by construction.
                "platform": (
                    f"{_platform_key(detail)}/s{detail.get('shards') or 1}"
                ),
                "value": float(parsed["value"]),
                "oracle_match": detail.get("oracle_match"),
                "mismatched": detail.get("mismatched_keys") or [],
                "red_outside": detail.get("red_outside_windows") or [],
                "red_source": detail.get("red_outside_source"),
                "failover": detail.get("failover"),
                "p99_ms": detail.get("event_p99_ms"),
                "restore": detail.get("restore"),
                "timeline": detail.get("timeline") or {},
            }
        )
    rounds.sort(key=lambda r: r["round"])
    if not rounds:
        print("bench-gate: no SOAK_r*.json artifacts; soak not gated")
        return 0
    latest = rounds[-1]
    ok = True
    if latest["oracle_match"] is not True:
        print(
            f"bench-gate: SOAK ORACLE MISMATCH in {latest['path']}: "
            f"post-failover placements differ from the uninterrupted "
            f"run ({len(latest['mismatched'])}+ keys, e.g. "
            f"{latest['mismatched'][:3]}) — scheduling determinism is "
            f"broken, this fails regardless of priors",
            file=sys.stderr,
        )
        ok = False
    if latest["red_outside"]:
        sample = latest["red_outside"][:3]
        print(
            f"bench-gate: SOAK EVALUATOR RED OUTSIDE INJECTION WINDOWS "
            f"in {latest['path']}: {len(latest['red_outside'])} "
            f"sample(s), e.g. {sample} — fails regardless of priors",
            file=sys.stderr,
        )
        ok = False
    failover = latest.get("failover")
    if failover is not None:
        # Spill-recovered failover gap (last victim record -> first
        # successor record): a correctness bound like oracle_match —
        # an unbounded gap means the successor never actually picked
        # the telemetry (and the work) up.
        if failover.get("bounded") is not True:
            print(
                f"bench-gate: SOAK FAILOVER GAP UNBOUNDED in "
                f"{latest['path']}: gap={failover.get('gap_s')}s "
                f"(bound {failover.get('bound_s')}s) — fails regardless "
                f"of priors",
                file=sys.stderr,
            )
            ok = False
        else:
            print(
                f"bench-gate: soak failover gap "
                f"{failover.get('gap_s')}s (bound "
                f"{failover.get('bound_s')}s, "
                f"red source={latest.get('red_source')}) — ok"
            )
    tl = latest["timeline"]
    print(
        f"bench-gate: soak {latest['path']} restore={latest['restore']} "
        f"timeline samples={tl.get('samples_total')} "
        f"bytes={tl.get('approx_bytes')} "
        f"sampler_cost_s={tl.get('sample_seconds_total')} — informational"
    )
    priors = [
        r
        for r in rounds[:-1]
        if r["metric"] == latest["metric"]
        and r["platform"] == latest["platform"]
    ]
    if not priors:
        print(
            f"bench-gate: WARNING: {latest['path']} ({latest['metric']}, "
            f"platform={latest['platform']}) has no prior same-platform "
            f"baseline — soak obj/s and event p99 NOT GATED this round; "
            f"this artifact becomes the baseline the next round gates "
            f"against"
        )
        return 0 if ok else 1
    best_value = max(r["value"] for r in priors)
    floor = best_value * (1.0 - tolerance)
    print(
        f"bench-gate: soak objs/s {latest['value']:.1f} vs best prior "
        f"{best_value:.1f} (floor {floor:.1f})"
    )
    if latest["value"] < floor:
        print(
            f"bench-gate: SOAK THROUGHPUT REGRESSION: "
            f"{latest['value']:.1f} < {floor:.1f}",
            file=sys.stderr,
        )
        ok = False
    prior_p99 = [r["p99_ms"] for r in priors if r.get("p99_ms") is not None]
    if latest.get("p99_ms") is not None:
        if prior_p99:
            ceil = min(prior_p99) * (1.0 + tolerance) + 250.0
            print(
                f"bench-gate: soak event_p99={latest['p99_ms']:.1f}ms vs "
                f"best prior {min(prior_p99):.1f}ms (ceiling {ceil:.1f})"
            )
            if latest["p99_ms"] > ceil:
                print(
                    f"bench-gate: SOAK LATENCY REGRESSION: event p99 "
                    f"{latest['p99_ms']:.1f}ms > {ceil:.1f}ms",
                    file=sys.stderr,
                )
                ok = False
        else:
            print(
                f"bench-gate: WARNING: soak event_p99="
                f"{latest['p99_ms']:.1f}ms has no prior same-platform "
                f"baseline — not gated this round"
            )
    print("bench-gate: soak ok" if ok else "bench-gate: soak FAILED")
    return 0 if ok else 1


def gate_ktlint(root: Path) -> int:
    """Fail when a previously-clean static-analysis rule regresses
    (ISSUE 14).  Every BENCH_r*.json embeds ``detail.ktlint`` — the
    per-rule violation counts of ``make lint`` at bench time.  The
    newest round must report 0 for any rule that was 0 in EVERY prior
    round that reported it; a rule first seen this round (a new rule
    family) seeds the baseline instead of gating.  Rounds predating the
    embed are skipped on both sides."""
    reported: list[tuple[str, dict]] = []
    for path in sorted(root.glob("BENCH_r*.json")):
        if not _ROUND_RE.match(path.name):
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue  # load_rounds already reported malformed artifacts
        kt = ((doc.get("parsed") or {}).get("detail") or {}).get("ktlint")
        if isinstance(kt, dict) and "error" not in kt:
            reported.append((path.name, kt))
        elif isinstance(kt, dict):
            print(
                f"bench-gate: WARNING: {path.name} ktlint summary errored "
                f"({kt.get('error')}) — static-analysis NOT gated for it"
            )
    if not reported:
        print("bench-gate: no rounds embed detail.ktlint yet; not gated")
        return 0
    latest_name, latest = reported[-1]
    priors = reported[:-1]
    ok = True
    for rule, count in sorted(latest.items()):
        prior_counts = [kt[rule] for _, kt in priors if rule in kt]
        if not prior_counts:
            if count:
                print(
                    f"bench-gate: note: new ktlint rule {rule!r} seeds "
                    f"with {count} violation(s); it gates from the next "
                    f"round"
                )
            continue
        if min(prior_counts) == 0 and count > 0:
            ok = False
            print(
                f"bench-gate: FAIL {latest_name}: ktlint rule {rule!r} "
                f"regressed to {count} violation(s) — it was clean in a "
                f"prior round; fix the violations (or suppress with a "
                f"written reason) before the round can gate green"
            )
    if ok:
        print(
            f"bench-gate: ktlint summary ok ({latest_name}: "
            f"{sum(latest.values())} violation(s) across "
            f"{len(latest)} rules)"
        )
    return 0 if ok else 1


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--tolerance",
        type=float,
        default=float(os.environ.get("KT_BENCH_GATE_TOL", "0.10")),
        help="allowed fractional regression (default 0.10 or "
        "$KT_BENCH_GATE_TOL)",
    )
    parser.add_argument(
        "--root", type=Path, default=REPO, help="artifact directory"
    )
    args = parser.parse_args()
    rc = gate(load_rounds(args.root), args.tolerance)
    churn_rc = gate_churn(args.root, args.tolerance)
    restart_rc = gate_restart(args.root, args.tolerance)
    census_rc = gate_census(args.root)
    e2e_rc = gate_e2e(args.root, args.tolerance)
    store_rc = gate_store(args.root, args.tolerance)
    soak_rc = gate_soak(args.root, args.tolerance)
    ktlint_rc = gate_ktlint(args.root)
    report_e2e_chaos(args.root)
    return (
        rc or churn_rc or restart_rc or census_rc or e2e_rc or store_rc
        or soak_rc or ktlint_rc
    )


if __name__ == "__main__":
    raise SystemExit(main())
