#!/usr/bin/env python
"""metrics-lint: fail on metric emissions outside the catalog.

Walks the source tree's ASTs for calls of the Metrics emission surface
(``counter``/``rate``/``store``/``gauge``/``duration``/``histogram``/
``timer``) on a metrics-shaped receiver, extracts the metric-name
argument (f-string interpolations become "*"), and checks every name
against ``kubeadmiral_tpu.runtime.metric_catalog``.  Run as
``make metrics-lint``; part of the default verify path, so a new metric
name must be cataloged (and thereby documented in
docs/observability.md) before it can merge.

The same walk keeps the decision vocabulary cataloged:

* ``.event(obj, type, reason, message)`` calls — literal event reasons
  must be in ``metric_catalog.EVENT_REASONS``;
* the flight recorder's record schema
  (``runtime.flightrec.DecisionRecord``) must equal
  ``metric_catalog.FLIGHT_RECORDER_FIELDS``;
* the reason-slug set (``ops.reasons.REASON_NAMES``) must equal
  ``metric_catalog.DECISION_REASONS`` — so the strings /debug/explain
  serves (and events embed) never drift from docs/observability.md;
* the /debug surface (ISSUE 17): every route the profiling module
  dispatches ↔ ``profiling.DEBUG_INDEX`` ↔ the docs/observability.md
  route table, all three ways — the GET /debug discovery index can
  never under- or over-promise.

Exit status: 0 clean, 1 violations (listed one per line), 2 on a file
that fails to parse.
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from kubeadmiral_tpu.runtime.metric_catalog import (  # noqa: E402
    DECISION_REASONS,
    EVENT_REASONS,
    FLIGHT_RECORDER_FIELDS,
    SLO_OBJECTIVES,
    SLO_STAGES,
    is_cataloged,
)

EMITTERS = {"counter", "rate", "store", "gauge", "duration", "histogram", "timer"}

SCAN_ROOTS = ("kubeadmiral_tpu", "bench.py", "bench_e2e.py")

# The emission receiver must look like a metrics registry: `metrics.x`,
# `self.metrics.x`, `<anything>.metrics.x`, or a local alias `m.x`.
_RECEIVER_NAMES = {"metrics", "m"}


def _is_metrics_receiver(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id in _RECEIVER_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr == "metrics"
    return False


def _name_pattern(node: ast.AST) -> str | None:
    """The metric-name argument as a lintable string; f-string
    interpolations become "*"; non-literal names return None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for value in node.values:
            if isinstance(value, ast.Constant):
                parts.append(str(value.value))
            else:
                parts.append("*")
        return "".join(parts)
    return None


def lint_file(path: Path) -> list[str]:
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as e:
        print(f"{path}: parse error: {e}", file=sys.stderr)
        raise
    errors = []
    rel = path.relative_to(REPO)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        # Event-reason vocabulary: .event(obj, type, reason, message) on
        # any recorder-shaped receiver.  Only literal reasons are
        # checkable (the eventsink's own forwarding call passes a
        # variable and is skipped).
        if func.attr == "event" and len(node.args) >= 4:
            reason_node = node.args[2]
            if (
                isinstance(reason_node, ast.Constant)
                and isinstance(reason_node.value, str)
                and reason_node.value not in EVENT_REASONS
            ):
                errors.append(
                    f"{rel}:{node.lineno}: event reason "
                    f"{reason_node.value!r} is not in "
                    f"runtime/metric_catalog.py EVENT_REASONS — catalog it "
                    f"(and document it in docs/observability.md) first"
                )
            continue
        if func.attr not in EMITTERS:
            continue
        if not _is_metrics_receiver(func.value):
            continue
        if not node.args:
            continue
        name = _name_pattern(node.args[0])
        if name is None:
            errors.append(
                f"{rel}:{node.lineno}: non-literal metric name in "
                f".{func.attr}() — the linter (and the catalog) cannot "
                f"see it; use a literal or f-string"
            )
            continue
        if not is_cataloged(name):
            errors.append(
                f"{rel}:{node.lineno}: metric {name!r} (via .{func.attr}()) "
                f"is not in runtime/metric_catalog.py — catalog it (and "
                f"document it in docs/observability.md) first"
            )
    return errors


def lint_decision_vocabulary() -> list[str]:
    """Cross-check the flight recorder's schema and reason slugs against
    the catalog (both directions), without importing jax-heavy modules'
    behavior — plain attribute reads."""
    errors: list[str] = []
    from kubeadmiral_tpu.ops import reasons as RSN
    from kubeadmiral_tpu.runtime.flightrec import DecisionRecord

    slugs = set(RSN.REASON_NAMES.values())
    for missing in sorted(slugs - DECISION_REASONS):
        errors.append(
            f"ops/reasons.py: reason slug {missing!r} is not in "
            f"runtime/metric_catalog.py DECISION_REASONS — catalog it (and "
            f"document it in docs/observability.md) first"
        )
    for stale in sorted(DECISION_REASONS - slugs):
        errors.append(
            f"runtime/metric_catalog.py: DECISION_REASONS entry {stale!r} "
            f"has no ops/reasons.py bit — remove it or add the bit"
        )
    fields = tuple(DecisionRecord.__slots__)
    if fields != FLIGHT_RECORDER_FIELDS:
        errors.append(
            f"runtime/flightrec.py: DecisionRecord fields {fields} != "
            f"catalog FLIGHT_RECORDER_FIELDS {FLIGHT_RECORDER_FIELDS} — "
            f"update the catalog (and docs/observability.md) with the "
            f"record schema"
        )
    # SLO vocabulary (ISSUE 13): the provenance stage order and the
    # evaluator's objective set are catalog-enforced like metric names —
    # the slo_event_to_written_seconds{stage} and slo_burn_rate
    # {objective} label vocabularies must never drift from the docs.
    from kubeadmiral_tpu.runtime import slo as SLO

    if tuple(SLO.STAGES) != SLO_STAGES:
        errors.append(
            f"runtime/slo.py: STAGES {tuple(SLO.STAGES)} != catalog "
            f"SLO_STAGES {SLO_STAGES} — update the catalog (and "
            f"docs/observability.md) with the stage vocabulary"
        )
    evaluator_names = set(SLO.SLOEvaluator().objectives)
    if evaluator_names != set(SLO_OBJECTIVES):
        errors.append(
            f"runtime/slo.py: evaluator objectives "
            f"{sorted(evaluator_names)} != catalog SLO_OBJECTIVES "
            f"{sorted(SLO_OBJECTIVES)} — catalog every objective (and "
            f"document it in docs/observability.md) first"
        )
    return errors


_ROUTE_RE = re.compile(r"^(/metrics|/debug(?:/[a-z_]+)?)/?$")
_DOC_ROUTE_RE = re.compile(r"/debug/[a-z_]+|/metrics\b|/debug(?![/a-z])")


def lint_debug_index() -> list[str]:
    """Three-way /debug surface completeness (ISSUE 17): every route the
    profiling module dispatches must be in DEBUG_INDEX, every
    DEBUG_INDEX entry must actually be dispatched, and the
    docs/observability.md route table must name them all — the
    one-curl discovery surface (GET /debug) can never drift from what
    is served or from what operators read."""
    errors: list[str] = []
    from kubeadmiral_tpu.runtime.profiling import DEBUG_INDEX

    prof = REPO / "kubeadmiral_tpu" / "runtime" / "profiling.py"
    tree = ast.parse(prof.read_text(), filename=str(prof))
    served: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        if not (isinstance(node.left, ast.Name) and node.left.id == "path"):
            continue
        for comp in node.comparators:
            literals = (
                comp.elts if isinstance(comp, ast.Tuple) else [comp]
            )
            for lit in literals:
                if isinstance(lit, ast.Constant) and isinstance(
                    lit.value, str
                ):
                    m = _ROUTE_RE.match(lit.value)
                    if m:
                        served.add(m.group(1))
    served.discard("/debug")  # the index itself

    index = set(DEBUG_INDEX)
    for route in sorted(served - index):
        errors.append(
            f"kubeadmiral_tpu/runtime/profiling.py: route {route!r} is "
            f"dispatched but missing from DEBUG_INDEX — the GET /debug "
            f"index must name every served route"
        )
    for route in sorted(index - served):
        errors.append(
            f"kubeadmiral_tpu/runtime/profiling.py: DEBUG_INDEX names "
            f"{route!r} but no dispatch serves it — stale index entry"
        )

    doc = REPO / "docs" / "observability.md"
    doc_routes: set[str] = set()
    in_table = False
    for line in doc.read_text().splitlines():
        stripped = line.strip()
        if stripped.startswith("| Route |"):
            in_table = True
            continue
        if in_table and not stripped.startswith("|"):
            break
        if in_table:
            doc_routes.update(_DOC_ROUTE_RE.findall(stripped))
    doc_routes.discard("/debug")
    for route in sorted(index - doc_routes):
        errors.append(
            f"docs/observability.md: route table is missing {route!r} "
            f"(in DEBUG_INDEX) — document the route before it ships"
        )
    for route in sorted(doc_routes - index):
        errors.append(
            f"docs/observability.md: route table names {route!r} which "
            f"is not in DEBUG_INDEX — stale docs row"
        )
    return errors


def main() -> int:
    errors: list[str] = list(lint_decision_vocabulary())
    errors.extend(lint_debug_index())
    for root in SCAN_ROOTS:
        path = REPO / root
        files = sorted(path.rglob("*.py")) if path.is_dir() else [path]
        for f in files:
            if not f.exists():
                continue
            try:
                errors.extend(lint_file(f))
            except SyntaxError:
                return 2
    if errors:
        print("\n".join(errors))
        print(f"metrics-lint: {len(errors)} violation(s)", file=sys.stderr)
        return 1
    print("metrics-lint: ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
