#!/usr/bin/env python
"""metrics-lint: fail on metric emissions outside the catalog.

Walks the source tree's ASTs for calls of the Metrics emission surface
(``counter``/``rate``/``store``/``gauge``/``duration``/``histogram``/
``timer``) on a metrics-shaped receiver, extracts the metric-name
argument (f-string interpolations become "*"), and checks every name
against ``kubeadmiral_tpu.runtime.metric_catalog``.  Run as
``make metrics-lint``; part of the default verify path, so a new metric
name must be cataloged (and thereby documented in
docs/observability.md) before it can merge.

Exit status: 0 clean, 1 violations (listed one per line), 2 on a file
that fails to parse.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from kubeadmiral_tpu.runtime.metric_catalog import is_cataloged  # noqa: E402

EMITTERS = {"counter", "rate", "store", "gauge", "duration", "histogram", "timer"}

SCAN_ROOTS = ("kubeadmiral_tpu", "bench.py", "bench_e2e.py")

# The emission receiver must look like a metrics registry: `metrics.x`,
# `self.metrics.x`, `<anything>.metrics.x`, or a local alias `m.x`.
_RECEIVER_NAMES = {"metrics", "m"}


def _is_metrics_receiver(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id in _RECEIVER_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr == "metrics"
    return False


def _name_pattern(node: ast.AST) -> str | None:
    """The metric-name argument as a lintable string; f-string
    interpolations become "*"; non-literal names return None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for value in node.values:
            if isinstance(value, ast.Constant):
                parts.append(str(value.value))
            else:
                parts.append("*")
        return "".join(parts)
    return None


def lint_file(path: Path) -> list[str]:
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as e:
        print(f"{path}: parse error: {e}", file=sys.stderr)
        raise
    errors = []
    rel = path.relative_to(REPO)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr in EMITTERS):
            continue
        if not _is_metrics_receiver(func.value):
            continue
        if not node.args:
            continue
        name = _name_pattern(node.args[0])
        if name is None:
            errors.append(
                f"{rel}:{node.lineno}: non-literal metric name in "
                f".{func.attr}() — the linter (and the catalog) cannot "
                f"see it; use a literal or f-string"
            )
            continue
        if not is_cataloged(name):
            errors.append(
                f"{rel}:{node.lineno}: metric {name!r} (via .{func.attr}()) "
                f"is not in runtime/metric_catalog.py — catalog it (and "
                f"document it in docs/observability.md) first"
            )
    return errors


def main() -> int:
    errors: list[str] = []
    for root in SCAN_ROOTS:
        path = REPO / root
        files = sorted(path.rglob("*.py")) if path.is_dir() else [path]
        for f in files:
            if not f.exists():
                continue
            try:
                errors.extend(lint_file(f))
            except SyntaxError:
                return 2
    if errors:
        print("\n".join(errors))
        print(f"metrics-lint: {len(errors)} violation(s)", file=sys.stderr)
        return 1
    print("metrics-lint: ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
