#!/usr/bin/env python
"""profile capture driver: run scheduling ticks under jax.profiler.

``make profile-smoke`` runs ONE small tick under JAX_PLATFORMS=cpu (the
CI-sized sanity check that the capture machinery works end to end);
``make profile`` runs a config-3-sized world for a few churned ticks on
whatever platform the environment selects.  Both write:

* a ``jax.profiler`` trace directory (TensorBoard profile plugin /
  xprof) under ``KT_PROFILE_DIR`` (default /tmp/kt-jax-profile),
* ``waterfall.json`` next to it — the dispatch ledger's per-tick
  device-time attribution for the captured ticks,

and print exactly one JSON line describing the artifacts.

Knobs: PROFILE_OBJECTS / PROFILE_CLUSTERS (world shape),
PROFILE_TICKS (churned ticks inside the capture, default 2),
KT_PROFILE_DIR (artifact root).  See docs/observability.md
§ Device-time attribution (profiler runbook).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    import numpy as np

    n_objects = int(os.environ.get("PROFILE_OBJECTS", "2048"))
    n_clusters = int(os.environ.get("PROFILE_CLUSTERS", "128"))
    n_ticks = int(os.environ.get("PROFILE_TICKS", "2"))

    sys.path.insert(0, REPO)  # bench.py world builder
    import bench
    from kubeadmiral_tpu.runtime import devprof
    from kubeadmiral_tpu.runtime.metrics import Metrics
    from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

    bench.N_OBJECTS = n_objects
    bench.N_CLUSTERS = n_clusters
    rng = np.random.default_rng(20260804)
    units, clusters, _followers = bench.build_world(rng)

    metrics = Metrics()
    engine = SchedulerEngine(metrics=metrics)
    engine.prewarm(n_objects, n_clusters, wait=True)
    # Cold tick outside the capture: the trace should show steady tick
    # structure, not one giant featurize+upload.
    engine.schedule(units, clusters)

    import jax

    target = os.path.join(
        devprof.profile_dir(),
        time.strftime("%Y%m%d-%H%M%S") + f"-smoke-{os.getpid()}",
    )
    os.makedirs(target, exist_ok=True)
    t0 = time.perf_counter()
    jax.profiler.start_trace(target)
    try:
        ticks = []
        for _ in range(max(1, n_ticks)):
            units = bench.churn(rng, units)
            t1 = time.perf_counter()
            engine.schedule(units, clusters)
            ticks.append(round((time.perf_counter() - t1) * 1e3, 1))
    finally:
        jax.profiler.stop_trace()
    capture_s = time.perf_counter() - t0

    wf = engine.devprof.waterfall(max_ticks=max(1, n_ticks))
    wf_path = os.path.join(target, "waterfall.json")
    with open(wf_path, "w") as fh:
        json.dump(wf, fh, indent=1)
    n_files = sum(len(files) for _, _, files in os.walk(target))
    last = wf["ticks"][-1] if wf.get("ticks") else {}
    print(
        json.dumps(
            {
                "profile_dir": target,
                "waterfall": wf_path,
                "files": n_files,
                "world": f"{n_objects}x{n_clusters}",
                "ticks_ms": ticks,
                "capture_s": round(capture_s, 2),
                "last_tick_device_ms": last.get("device_ms"),
                "last_tick_queue_ms": last.get("queue_ms"),
                "last_tick_records": len(last.get("records", ())),
            }
        )
    )
    print(
        f"# load the trace: tensorboard --logdir {target}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
