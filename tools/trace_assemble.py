#!/usr/bin/env python
"""trace_assemble: merge per-process traces into ONE Chrome trace.

Each process exports spans relative to its own perf_counter epoch —
incomparable across processes — but both the Chrome export
(``runtime/trace.py chrome_trace()``, ``otherData.wall_epoch``) and
every telemetry-spill record (``runtime/telespill.py``, ``wall_epoch``
envelope field) carry the wall-clock instant of that epoch.  This tool
re-anchors every input on the earliest wall epoch seen, assigns each
(instance, pid) its own process lane, and emits one merged trace —
load it in chrome://tracing / ui.perfetto.dev and a manager-side
``dispatch.member_write`` span sits directly above the member
process's ``apiserver.batch`` child, joined by trace id.

Inputs (mix freely):

* a telemetry-spill directory (``KT_TELEMETRY_DIR``) — ``spans``
  records from every instance's segments;
* a Chrome trace JSON file (a saved ``GET /debug/trace`` payload).

Usage::

    python tools/trace_assemble.py --out merged.trace.json \
        /tmp/kt-telemetry manager.trace.json

The runbook ("correlate one slow member write across processes") is in
docs/observability.md § Fleet observatory.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _lane_events_from_spill(directory: str) -> list[dict]:
    """Per-lane raw events from a spill directory's ``spans`` records:
    each event still in its process's epoch-relative microseconds, but
    tagged with the lane's wall_epoch + identity for re-anchoring."""
    from kubeadmiral_tpu.runtime import telespill

    out = []
    for rec in telespill.load_dir(directory, quarantine=False):
        if rec.get("kind") != "spans":
            continue
        wall_epoch = rec.get("wall_epoch")
        instance = rec.get("instance") or f"pid{rec.get('pid')}"
        pid = rec.get("pid")
        for sp in rec.get("spans") or ():
            start = sp.get("start")
            if start is None:
                continue
            end = sp.get("end")
            args = dict(sp.get("args") or {})
            args["span_id"] = sp.get("span_id")
            args["trace_id"] = sp.get("trace_id")
            if sp.get("parent_id") is not None:
                args["parent_id"] = sp.get("parent_id")
            out.append(
                {
                    "name": sp.get("name"),
                    "ph": "X",
                    "ts": round(start * 1e6, 3),
                    "dur": round(((end or start) - start) * 1e6, 3),
                    "tid": sp.get("tid", 0),
                    "args": args,
                    "_lane": (instance, pid),
                    "_wall_epoch": wall_epoch,
                    "_thread_name": sp.get("thread_name"),
                }
            )
    return out


def _lane_events_from_trace(path: str) -> list[dict]:
    with open(path) as fh:
        doc = json.load(fh)
    other = doc.get("otherData") or {}
    wall_epoch = other.get("wall_epoch")
    pid = other.get("pid")
    instance = other.get("instance") or os.path.basename(path)
    out = []
    thread_names: dict[object, str] = {}
    for ev in doc.get("traceEvents") or ():
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            thread_names[ev.get("tid")] = (ev.get("args") or {}).get("name")
            continue
        if ev.get("ph") != "X":
            continue
        out.append(
            {
                "name": ev.get("name"),
                "ph": "X",
                "ts": ev.get("ts", 0.0),
                "dur": ev.get("dur", 0.0),
                "tid": ev.get("tid", 0),
                "args": dict(ev.get("args") or {}),
                "_lane": (instance, ev.get("pid", pid)),
                "_wall_epoch": wall_epoch,
                "_thread_name": None,
            }
        )
    for ev in out:
        ev["_thread_name"] = thread_names.get(ev["tid"])
    return out


def assemble(inputs: list[str]) -> dict:
    """Merge spill directories and Chrome trace files into one trace.

    Lanes without a wall anchor (a pre-anchor trace export) are kept —
    re-anchored as if their epoch were the base — and counted in
    ``summary.unanchored_lanes`` so a silently misaligned lane is
    visible, not invisible."""
    raw: list[dict] = []
    for item in inputs:
        if os.path.isdir(item):
            raw.extend(_lane_events_from_spill(item))
        else:
            raw.extend(_lane_events_from_trace(item))
    anchors = [
        ev["_wall_epoch"] for ev in raw if ev["_wall_epoch"] is not None
    ]
    base = min(anchors) if anchors else 0.0
    lanes: dict[tuple, int] = {}
    lane_anchor: dict[tuple, float] = {}
    unanchored: set[tuple] = set()
    events: list[dict] = []
    thread_names: dict[tuple[int, object], str] = {}
    for ev in raw:
        lane = ev.pop("_lane")
        wall_epoch = ev.pop("_wall_epoch")
        tname = ev.pop("_thread_name")
        if lane not in lanes:
            lanes[lane] = len(lanes) + 1
            lane_anchor[lane] = wall_epoch if wall_epoch is not None else base
            if wall_epoch is None:
                unanchored.add(lane)
        pid = lanes[lane]
        shift_us = (lane_anchor[lane] - base) * 1e6
        ev["pid"] = pid
        ev["ts"] = round(ev["ts"] + shift_us, 3)
        events.append(ev)
        if tname:
            thread_names.setdefault((pid, ev["tid"]), tname)
    for lane, pid in lanes.items():
        instance, real_pid = lane
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": f"{instance} (pid {real_pid})"},
            }
        )
    for (pid, tid), tname in thread_names.items():
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": tname},
            }
        )
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "wall_epoch": base,
            "lanes": {
                "/".join(str(p) for p in lane): pid
                for lane, pid in lanes.items()
            },
        },
    }
    doc["summary"] = summarize(doc)
    doc["summary"]["unanchored_lanes"] = sorted(
        "/".join(str(p) for p in lane) for lane in unanchored
    )
    return doc


def summarize(doc: dict) -> dict:
    """Counts + the cross-process parent/child joins: events in lane A
    whose args.parent_id is the span_id of an event in lane B ≠ A,
    under the same trace id — the propagation acceptance check."""
    spans_by_id: dict[tuple, dict] = {}
    per_lane: dict[int, int] = {}
    x_events = [ev for ev in doc.get("traceEvents") or () if ev.get("ph") == "X"]
    for ev in x_events:
        args = ev.get("args") or {}
        per_lane[ev.get("pid")] = per_lane.get(ev.get("pid"), 0) + 1
        if args.get("span_id") is not None and args.get("trace_id"):
            spans_by_id[(args["trace_id"], args["span_id"])] = ev
    joins = []
    for ev in x_events:
        args = ev.get("args") or {}
        parent_id = args.get("parent_id")
        trace_id = args.get("trace_id")
        if parent_id is None or not trace_id:
            continue
        parent = spans_by_id.get((trace_id, parent_id))
        if parent is None or parent.get("pid") == ev.get("pid"):
            continue
        joins.append(
            {
                "trace_id": trace_id,
                "parent": parent.get("name"),
                "parent_pid": parent.get("pid"),
                "child": ev.get("name"),
                "child_pid": ev.get("pid"),
            }
        )
    return {
        "events": len(x_events),
        "lanes": len(per_lane),
        "events_per_lane": per_lane,
        "cross_process_joins": len(joins),
        "join_examples": joins[:10],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "inputs", nargs="+",
        help="spill directories and/or Chrome trace JSON files",
    )
    parser.add_argument(
        "--out", default="merged.trace.json",
        help="merged Chrome trace output path",
    )
    args = parser.parse_args(argv)
    doc = assemble(args.inputs)
    with open(args.out, "w") as fh:
        json.dump(doc, fh)
    s = doc["summary"]
    print(
        f"trace_assemble: {s['events']} events across {s['lanes']} lanes, "
        f"{s['cross_process_joins']} cross-process joins -> {args.out}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
