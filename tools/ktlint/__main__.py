"""``python -m tools.ktlint`` — see tools/ktlint/__init__.py."""

import sys

from tools.ktlint import main

if __name__ == "__main__":
    sys.exit(main())
