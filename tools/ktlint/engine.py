"""ktlint core: source loading, suppressions, rule protocol, reporting.

ktlint is the repo-specific static analyzer (``make lint``): an
AST-based pass that turns conventions the code reviews kept re-litigating
— AOT/ledger routing of jit sites, the pack-sort sharding rule, donated
-buffer hygiene, the knob catalog, lock discipline — into machine-checked
rules.  See docs/static_analysis.md for the rule catalog and policy.

Design notes:

* Rules are AST-only on the scanned tree — no imports of scanned
  modules, so a fixture file full of deliberate violations (or a
  half-written module) lints without executing.  The one exception is
  the knob rule's catalog, imported from
  ``kubeadmiral_tpu.runtime.knob_catalog`` (dependency-free).
* Suppressions are source comments, same line or the line above::

      # ktlint: ignore[rule-id] reason the invariant doesn't apply here

  The reason is mandatory: a bare ``ignore[rule-id]`` is itself a
  violation (``suppression-format``).  Suppressions are per-line and
  per-rule; there is no file-level or wildcard opt-out.
* Output: human one-per-line (``path:line: [rule] message``) or
  ``--json`` ``{"violations": [...], "summary": {rule: count}}``.  The
  summary always carries every registered rule (zeros included) — it is
  what bench.py embeds in BENCH detail and tools/bench_gate.py gates
  on (a previously-clean rule regressing fails the round).
"""

from __future__ import annotations

import ast
import json
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional, Sequence

REPO = Path(__file__).resolve().parent.parent.parent

SUPPRESS_RE = re.compile(r"#\s*ktlint:\s*ignore\[([a-z0-9*-]+)\]\s*(.*?)\s*$")

# Default tree every rule scans unless it declares its own roots.
DEFAULT_ROOTS: tuple[str, ...] = ("kubeadmiral_tpu",)


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str  # repo-relative, posix
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path,
            "line": self.line, "message": self.message,
        }


@dataclass
class SourceFile:
    path: Path
    rel: str
    text: str
    tree: ast.Module
    # line -> {rule_id: reason}; a suppression comment covers its own
    # line and the line below (the comment-above idiom).
    suppressions: dict[int, dict[str, str]] = field(default_factory=dict)
    bad_suppressions: list[Violation] = field(default_factory=list)


def load_source(path: Path, repo: Path = REPO) -> SourceFile:
    path = Path(path).resolve()
    text = path.read_text()
    tree = ast.parse(text, filename=str(path))
    try:
        rel = path.relative_to(repo).as_posix()
    except ValueError:
        rel = path.as_posix()
    src = SourceFile(path=path, rel=rel, text=text, tree=tree)
    for lineno, line in enumerate(text.splitlines(), start=1):
        m = SUPPRESS_RE.search(line)
        if not m:
            continue
        rule_id, reason = m.group(1), m.group(2)
        if not reason:
            src.bad_suppressions.append(Violation(
                "suppression-format", rel, lineno,
                f"suppression of [{rule_id}] has no written justification "
                f"— `# ktlint: ignore[{rule_id}] <reason>` is mandatory "
                f"(docs/static_analysis.md, suppression policy)",
            ))
            continue
        for covered in (lineno, lineno + 1):
            src.suppressions.setdefault(covered, {})[rule_id] = reason
    return src


class Rule:
    """One rule family.  Subclasses set ``id``/``doc`` and implement
    :meth:`check`; ``roots`` widens the scanned tree beyond the
    package (repo-relative files or directories)."""

    id: str = ""
    doc: str = ""
    roots: tuple[str, ...] = DEFAULT_ROOTS

    def __init__(self) -> None:
        # Denominator stats (sites inspected etc.) so callers can assert
        # the rule actually SAW the tree — a zero-violation result from
        # an AST walk that matched nothing must not read as clean.
        self.stats: dict[str, int] = {}
        # True when check() runs over an explicit file list (fixtures)
        # instead of the rule's full roots; repo-global cross-checks
        # (docs/catalog closure) only make sense on a full scan.
        self.partial: bool = False

    def check(self, files: Sequence[SourceFile]) -> list[Violation]:
        raise NotImplementedError


def collect_files(
    roots: Iterable[str], repo: Path = REPO,
) -> list[SourceFile]:
    files: list[SourceFile] = []
    seen: set[Path] = set()
    for root in roots:
        path = repo / root
        candidates = sorted(path.rglob("*.py")) if path.is_dir() else [path]
        for f in candidates:
            if not f.exists() or f in seen or "__pycache__" in f.parts:
                continue
            seen.add(f)
            files.append(load_source(f, repo))
    return files


def run_rules(
    rules: Sequence[Rule],
    repo: Path = REPO,
    paths: Optional[Sequence[Path]] = None,
) -> tuple[list[Violation], dict[str, int]]:
    """Run ``rules``; returns (violations, summary).  ``paths`` overrides
    each rule's roots with an explicit file set (fixture runs)."""
    cache: dict[tuple[str, ...], list[SourceFile]] = {}
    violations: list[Violation] = []
    summary: dict[str, int] = {r.id: 0 for r in rules}
    summary["suppression-format"] = 0
    bad_suppression_files: set[str] = set()
    for rule in rules:
        rule.partial = paths is not None
        if paths is not None:
            files = [load_source(Path(p), repo) for p in paths]
        else:
            files = cache.get(rule.roots)
            if files is None:
                files = collect_files(rule.roots, repo)
                cache[rule.roots] = files
        for f in files:
            if f.rel not in bad_suppression_files:
                bad_suppression_files.add(f.rel)
                for v in f.bad_suppressions:
                    violations.append(v)
                    summary["suppression-format"] += 1
        for v in rule.check(files):
            suppressed = files_suppression(files, v)
            if suppressed is not None:
                continue
            violations.append(v)
            summary[rule.id] = summary.get(rule.id, 0) + 1
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return violations, summary


def files_suppression(
    files: Sequence[SourceFile], v: Violation,
) -> Optional[str]:
    """The suppression reason covering ``v``, or None."""
    for f in files:
        if f.rel == v.path:
            return f.suppressions.get(v.line, {}).get(v.rule)
    return None


def render_human(violations: Sequence[Violation], summary: dict[str, int]) -> str:
    lines = [v.format() for v in violations]
    total = len(violations)
    if total:
        lines.append(f"ktlint: {total} violation(s)")
    else:
        lines.append("ktlint: ok")
    return "\n".join(lines)


def render_json(violations: Sequence[Violation], summary: dict[str, int]) -> str:
    return json.dumps(
        {
            "violations": [v.as_dict() for v in violations],
            "summary": dict(sorted(summary.items())),
        },
        indent=2,
    )


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    from tools.ktlint.rules import all_rules

    parser = argparse.ArgumentParser(
        prog="ktlint", description="repo-specific static analysis (make lint)"
    )
    parser.add_argument("--json", action="store_true", help="JSON output")
    parser.add_argument(
        "--rule", action="append", default=None,
        help="run only this rule id (repeatable)",
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="explicit files to lint (default: each rule's roots)",
    )
    args = parser.parse_args(argv)
    rules = all_rules()
    if args.rule:
        known = {r.id for r in rules}
        unknown = set(args.rule) - known
        if unknown:
            print(f"ktlint: unknown rule(s) {sorted(unknown)}; "
                  f"known: {sorted(known)}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in args.rule]
    try:
        violations, summary = run_rules(
            rules, paths=args.paths or None
        )
    except SyntaxError as e:
        print(f"ktlint: parse error: {e}", file=sys.stderr)
        return 2
    out = (
        render_json(violations, summary)
        if args.json
        else render_human(violations, summary)
    )
    print(out)
    return 1 if violations else 0
