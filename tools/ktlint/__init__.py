"""ktlint: the repo-specific static analyzer (``make lint``).

Public surface:

* ``python -m tools.ktlint [--json] [--rule ID] [paths...]`` — the CLI
  ``make lint`` runs (and ``make test`` runs ``lint``).
* :func:`run` — programmatic run, returns (violations, summary).
* :func:`summary` — ``{rule-id: violation-count}`` over the full tree;
  what bench.py embeds under ``detail.ktlint`` and
  ``tools/bench_gate.py`` gates on.

See docs/static_analysis.md for the rule catalog and suppression
policy, and tests/test_ktlint.py + tests/fixtures/ktlint/ for the
per-rule known-bad/known-good fixtures.
"""

from __future__ import annotations

import sys
from pathlib import Path

# Run both as `python -m tools.ktlint` from the repo root and as an
# imported helper from bench/tests: the repo root must be importable.
_REPO = Path(__file__).resolve().parent.parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from tools.ktlint.engine import (  # noqa: E402
    Rule,
    SourceFile,
    Violation,
    main,
    run_rules,
)
from tools.ktlint.rules import all_rules, rule_by_id  # noqa: E402


def run(rule_ids=None, paths=None):
    """(violations, summary) for the given rules (default: all)."""
    rules = all_rules()
    if rule_ids is not None:
        rules = [r for r in rules if r.id in set(rule_ids)]
    return run_rules(rules, paths=paths)


def summary() -> dict[str, int]:
    """Full-tree per-rule violation counts (zeros included)."""
    _, counts = run()
    return counts


__all__ = [
    "Rule", "SourceFile", "Violation", "all_rules", "rule_by_id",
    "run", "run_rules", "summary", "main",
]
