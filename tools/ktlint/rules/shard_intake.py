"""Rule shard-intake-coverage: every watch/enqueue intake site in the
federation package must consult the ShardMap before a key costs work.

The sharded control plane (ISSUE 20) runs N engine replicas behind the
jump-hash router; a watch handler that processes keys its replica does
not own double-schedules objects and breaks the disjoint-placement
invariant.  The router is consulted at exactly two boundaries, and a
watch intake site must hit one of them:

* **intake drop** — the handler is wrapped in ``ShardIntake(...)`` (or
  the watch call carries a ``predicate=``), so non-owned events are
  dropped before they cost an enqueue; or
* **worker boundary** — every event the handler accepts is routed
  through ``worker.enqueue`` / ``enqueue_all`` / ``enqueue_many``,
  which filter by the replica's ShardMap snapshot.  The routing check
  is transitive within the handler's class (``_on_policy_event`` →
  ``_enqueue_objects_for_policies`` → ``enqueue_all`` counts).

A handler that neither drops at intake nor routes through a worker
mutates shared state for keys the replica does not own; that is either
a sharding bug or a deliberately control-plane-global (broadcast)
intake — the latter must carry a written
``# ktlint: ignore[shard-intake-coverage] <reason>`` documenting the
broadcast intent, the same way soakharness pins its join controller to
``ShardMap(1, 0)``.
"""

from __future__ import annotations

import ast

from tools.ktlint.engine import Rule, SourceFile, Violation
from tools.ktlint.rules import _astutil as A

RULE_ID = "shard-intake-coverage"

WATCH_METHODS = ("watch", "watch_members")
ENQUEUE_METHODS = ("enqueue", "enqueue_all", "enqueue_many")


def _is_shard_intake(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and A.terminal_name(node.func) == "ShardIntake"


def _routed_methods(cls: ast.ClassDef) -> set[str]:
    """Methods that (transitively, within the class) route work through
    a shard-filtered worker enqueue."""
    meths: dict[str, ast.AST] = {}
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            meths[node.name] = node
    direct: set[str] = set()
    calls: dict[str, set[str]] = {}
    for name, fn in meths.items():
        out: set[str] = set()
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            func = call.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr in ENQUEUE_METHODS:
                direct.add(name)
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                out.add(func.attr)
        calls[name] = out
    routed = set(direct)
    changed = True
    while changed:
        changed = False
        for name in meths:
            if name not in routed and calls[name] & routed:
                routed.add(name)
                changed = True
    return routed


def _handler_arg(call: ast.Call) -> ast.AST | None:
    """The handler passed to ``watch(resource, handler, ...)`` /
    ``watch_members(resource, handler, ...)``."""
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "handler":
            return kw.value
    return None


def _aliased_to_intake(handler: ast.Name, call: ast.Call) -> bool:
    """``intake = ShardIntake(...); host.watch(res, intake)`` — local
    forward alias inside the same enclosing def."""
    for fn in A.enclosing_functions(call):
        for stmt in ast.walk(fn):
            if not isinstance(stmt, ast.Assign):
                continue
            if not _is_shard_intake(stmt.value):
                continue
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name) and tgt.id == handler.id:
                    return True
    return False


class ShardIntakeRule(Rule):
    id = RULE_ID
    doc = (
        "watch/watch_members intake sites in kubeadmiral_tpu/federation "
        "must consult the ShardMap: wrap the handler in ShardIntake(...) "
        "or pass predicate= (intake drop), or route every accepted event "
        "through the shard-filtered worker enqueue family; "
        "control-plane-global (broadcast) intakes need a written "
        "suppression documenting the intent"
    )
    roots = ("kubeadmiral_tpu/federation",)

    def check(self, files: list[SourceFile]) -> list[Violation]:
        violations: list[Violation] = []
        sites = 0
        dropped_at_intake = 0
        worker_routed = 0
        for f in files:
            A.annotate_parents(f.tree)
            routed_by_class: dict[ast.ClassDef, set[str]] = {}
            for call in ast.walk(f.tree):
                if not isinstance(call, ast.Call):
                    continue
                func = call.func
                if not isinstance(func, ast.Attribute):
                    continue
                if func.attr not in WATCH_METHODS:
                    continue
                sites += 1
                if any(kw.arg == "predicate" for kw in call.keywords):
                    dropped_at_intake += 1
                    continue
                handler = _handler_arg(call)
                if handler is None:
                    violations.append(Violation(
                        RULE_ID, f.rel, call.lineno,
                        f"{func.attr}() intake site has no recognizable "
                        f"handler argument — pass the handler positionally "
                        f"(resource, handler) or as handler= so the shard "
                        f"router coverage can be checked",
                    ))
                    continue
                if _is_shard_intake(handler):
                    dropped_at_intake += 1
                    continue
                if isinstance(handler, ast.Name) and _aliased_to_intake(
                        handler, call):
                    dropped_at_intake += 1
                    continue
                if (isinstance(handler, ast.Call)
                        and A.terminal_name(handler.func) == "partial"
                        and handler.args):
                    # functools.partial(self._on_x, ...) — the bound
                    # method is the real handler (follower.py's
                    # owner-identified handler idiom).
                    handler = handler.args[0]
                if (isinstance(handler, ast.Attribute)
                        and isinstance(handler.value, ast.Name)
                        and handler.value.id == "self"):
                    cls = next(
                        (a for a in A.ancestors(call)
                         if isinstance(a, ast.ClassDef)), None)
                    if cls is not None:
                        routed = routed_by_class.get(cls)
                        if routed is None:
                            routed = _routed_methods(cls)
                            routed_by_class[cls] = routed
                        if handler.attr in routed:
                            worker_routed += 1
                            continue
                violations.append(Violation(
                    RULE_ID, f.rel, call.lineno,
                    f"{func.attr}() handler is not shard-checked: wrap it "
                    f"in ShardIntake(...) or pass predicate= to drop "
                    f"non-owned keys at intake, or route every accepted "
                    f"event through the shard-filtered worker enqueue "
                    f"family — a replica processing keys it does not own "
                    f"double-schedules under the sharded control plane; "
                    f"a deliberately broadcast intake needs "
                    f"`# ktlint: ignore[{RULE_ID}] <reason>` "
                    f"(docs/static_analysis.md)",
                ))
        self.stats["watch_sites"] = sites
        self.stats["dropped_at_intake"] = dropped_at_intake
        self.stats["worker_routed"] = worker_routed
        return violations
