"""Rule lock-discipline: declared-shared fields are only mutated under
their declared lock.

Classes annotate cross-thread state in a ``_shared_fields_`` registry
(``{"field": "lockattr"}``; alternates joined with ``|`` — e.g. a
Condition sharing its underlying Lock).  This rule checks every
mutation of ``self.<field>`` inside the class:

* rebinds (``self.f = ...`` / ``self.f += ...``), item stores/deletes
  (``self.f[k] = v``, ``del self.f[k]``), mutator method calls
  (``self.f.append(...)``, ``.pop``, ``.update`` …) and
  ``heapq.heappush/heappop(self.f, ...)``;
* each must be lexically inside ``with self.<lockattr>:`` — or in a
  context the registry's conventions mark as lock-held: ``__init__``
  (pre-publication), a method named ``*_locked``, or a method decorated
  ``@lockcheck.assumes_held("<lockattr>")`` (which the runtime harness
  VERIFIES on entry under KT_LOCKCHECK).

This is the static half of the PR-3 race-class guard
(``runtime/lockcheck.py`` is the runtime half: lock-order inversions +
off-lock rebinds under the thread storm).  It sees container mutations
the runtime ``__setattr__`` guard cannot; the runtime sees dynamic
call paths this rule cannot.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.ktlint.engine import Rule, Violation
from tools.ktlint.rules import _astutil as A

RULE_ID = "lock-discipline"

MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear",
    "update", "setdefault",
}

HEAP_FUNCS = {"heapq.heappush", "heapq.heappop", "heapq.heapify"}


def _shared_fields(cls: ast.ClassDef) -> Optional[dict[str, str]]:
    for stmt in cls.body:
        targets = A.assign_targets(stmt)
        if not any(
            isinstance(t, ast.Name) and t.id == "_shared_fields_"
            for t in targets
        ):
            continue
        value = getattr(stmt, "value", None)
        if not isinstance(value, ast.Dict):
            return None
        out: dict[str, str] = {}
        for k, v in zip(value.keys, value.values):
            if isinstance(k, ast.Constant) and isinstance(
                v, ast.Constant
            ):
                out[str(k.value)] = str(v.value)
        return out
    return None


def _held_locks(node: ast.AST, method: ast.FunctionDef) -> set[str]:
    """Lock attr names whose ``with self.<lock>:`` lexically encloses
    ``node``, plus locks the method context assumes held."""
    held: set[str] = set()
    for anc in A.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                if A.is_self_attr(item.context_expr):
                    held.add(item.context_expr.attr)  # type: ignore
        if anc is method:
            break
    if method.name == "__init__" or method.name.endswith("_locked"):
        held.add("*")
    for deco in method.decorator_list:
        if isinstance(deco, ast.Call) and A.terminal_name(
            deco.func
        ) == "assumes_held":
            for arg in deco.args:
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    held.update(arg.value.split("|"))
    return held


def _satisfied(lock_spec: str, held: set[str]) -> bool:
    if "*" in held:
        return True
    return any(alt in held for alt in lock_spec.split("|"))


class LockDisciplineRule(Rule):
    id = RULE_ID
    doc = __doc__

    def check(self, files):
        violations: list[Violation] = []
        classes = 0
        mutations = 0
        for f in files:
            A.annotate_parents(f.tree)
            for cls in ast.walk(f.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                fields = _shared_fields(cls)
                if fields is None:
                    continue
                if not fields:
                    violations.append(Violation(
                        RULE_ID, f.rel, cls.lineno,
                        f"{cls.name}._shared_fields_ must be a literal "
                        f"dict of field -> lock-attr strings",
                    ))
                    continue
                classes += 1
                for method in cls.body:
                    if not isinstance(
                        method, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        continue
                    for node, field, how in self._mutation_nodes(
                        method, fields
                    ):
                        mutations += 1
                        held = _held_locks(node, method)
                        if _satisfied(fields[field], held):
                            continue
                        violations.append(Violation(
                            RULE_ID, f.rel, node.lineno,
                            f"{cls.name}.{field} is declared shared "
                            f"(lock {fields[field]!r}) but is mutated "
                            f"here ({how}) outside `with self."
                            f"{fields[field].split('|')[0]}:` — the "
                            f"PR-3 race class; hold the lock, or mark "
                            f"the method *_locked / @assumes_held if "
                            f"every caller already does",
                        ))
        self.stats["declared_classes"] = classes
        self.stats["mutation_sites"] = mutations
        return violations

    def _mutation_nodes(self, method, fields):
        for node in ast.walk(method):
            # self.f = ... / self.f += ...
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for t in A.assign_targets(node):
                    if A.is_self_attr(t) and t.attr in fields:
                        yield node, t.attr, "rebind"
                    # self.f[k] = v
                    if isinstance(t, ast.Subscript) and A.is_self_attr(
                        t.value
                    ) and t.value.attr in fields:
                        yield node, t.value.attr, "item store"
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and A.is_self_attr(
                        t.value
                    ) and t.value.attr in fields:
                        yield node, t.value.attr, "item delete"
            elif isinstance(node, ast.Call):
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr in MUTATORS
                    and A.is_self_attr(func.value)
                    and func.value.attr in fields
                ):
                    yield node, func.value.attr, f".{func.attr}()"
                elif A.dotted(func) in HEAP_FUNCS:
                    for arg in node.args[:1]:
                        if A.is_self_attr(arg) and arg.attr in fields:
                            yield node, arg.attr, A.dotted(func)
