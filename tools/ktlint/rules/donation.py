"""Rule donation-discipline: a buffer passed at a ``donate_argnums``
position must not be read after the dispatch in the same scope.

Under donation XLA aliases the donated input's buffer into the
program's outputs — the Python reference still exists but the device
buffer is dead; a later read raises (best case) or, under some
backends, silently observes aliased bytes.  The engine donates every
tick's prev planes (``KT_DONATE``), so the hazard sits on the hottest
dispatch path.

Two passes per module:

1. **Collect donating programs.**  ``jax.jit(fn, donate_argnums=...)``
   sites are walked back through their wrappers (``aot(...)``,
   ``*.wrap(...)``, ``_obs_wrap(...)``) to what the product is bound
   to: ``self.X = ...`` marks attribute X donating; a builder method
   that returns the product (the per-key program-cache idiom) marks the
   METHOD donating, so ``self._narrow_program(fmt, m)(...)`` call sites
   inherit the positions.  ``donate_argnums`` literals, the
   ``(1,) if cond else ()`` pattern, and a local ``donate = ...``
   binding all resolve; an unresolvable spec flags its own violation
   (the analyzer — like the reader — cannot tell what dies).
2. **Check dispatch sites.**  At each call of a donating program, the
   names passed at donated positions (plain names and tuple elements)
   must not be loaded later in the same function body unless rebound
   first.  The walk is lexical (single forward pass by line), which is
   exactly the scope the invariant names.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.ktlint.engine import Rule, Violation
from tools.ktlint.rules import _astutil as A

RULE_ID = "donation-discipline"

WRAPPERS = {"wrap", "aot", "_obs_wrap"}


def _resolve_positions(
    spec: ast.expr, fn_def: Optional[ast.AST],
) -> Optional[set[int]]:
    """Donated argument positions, or None when unresolvable."""
    if isinstance(spec, ast.Constant) and isinstance(spec.value, int):
        return {spec.value}
    if isinstance(spec, ast.Tuple):
        out: set[int] = set()
        for el in spec.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.add(el.value)
            else:
                return None
        return out
    if isinstance(spec, ast.IfExp):
        a = _resolve_positions(spec.body, fn_def)
        b = _resolve_positions(spec.orelse, fn_def)
        if a is None or b is None:
            return None
        return a | b
    if isinstance(spec, ast.Name) and fn_def is not None:
        # A local `donate = ...` binding (last one wins lexically).
        binding = None
        best = -1
        for stmt in ast.walk(fn_def):
            if isinstance(stmt, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == spec.id
                for t in stmt.targets
            ):
                if best < stmt.lineno < spec.lineno:
                    binding = stmt.value
                    best = stmt.lineno
        if binding is not None:
            return _resolve_positions(binding, None)
    return None


def _unwrap_to_binding(jit_call: ast.Call) -> tuple[
    Optional[str], Optional[str], Optional[ast.stmt],
]:
    """(self_attr, local_name, stmt) the (possibly wrapper-nested) jit
    product is bound to."""
    node: ast.AST = jit_call
    while True:
        outer = A.parent(node)
        if isinstance(outer, ast.Call) and (
            A.terminal_name(outer.func) in WRAPPERS
        ):
            node = outer
            continue
        break
    stmt = A.enclosing_statement(node)
    for t in A.assign_targets(stmt):
        if A.is_self_attr(t):
            return t.attr, None, stmt  # type: ignore[union-attr]
        if isinstance(t, ast.Name):
            return None, t.id, stmt
    return None, None, stmt


def _builder_returns(fn_def: ast.FunctionDef, local: str) -> bool:
    """Does the builder method return (an alias of) ``local``?  Follows
    the rewrap idiom `fn = self._obs_wrap(k, fn)`."""
    aliases = {local}
    for stmt in sorted(
        (s for s in ast.walk(fn_def) if isinstance(s, ast.stmt)),
        key=lambda s: s.lineno,
    ):
        if isinstance(stmt, ast.Assign) and isinstance(
            stmt.value, ast.Call
        ):
            if any(
                isinstance(a, ast.Name) and a.id in aliases
                for a in A.call_args(stmt.value)
            ):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        aliases.add(t.id)
        if isinstance(stmt, ast.Return) and isinstance(
            stmt.value, ast.Name
        ) and stmt.value.id in aliases:
            return True
    return False


def _branch_path(node: ast.AST) -> list[tuple[int, str]]:
    """(id(ancestor), arm) pairs for every If/Try ancestor, where arm
    is which field of the ancestor the node sits under."""
    out: list[tuple[int, str]] = []
    cur: ast.AST = node
    while True:
        par = A.parent(cur)
        if par is None:
            break
        if isinstance(par, (ast.If, ast.Try)):
            for arm in ("body", "orelse", "handlers", "finalbody"):
                children = getattr(par, arm, None) or []
                if any(c is cur for c in children):
                    out.append((id(par), arm))
                    break
        cur = par
    return out


def _sibling_branches(a: ast.AST, b: ast.AST) -> bool:
    """True when a and b sit in different arms of the same If/Try —
    alternatives, not sequential."""
    pa = dict(_branch_path(a))
    for anc_id, arm in _branch_path(b):
        other = pa.get(anc_id)
        if other is not None and other != arm:
            return True
    return False


def _donated_arg_names(call: ast.Call, positions: set[int]) -> set[str]:
    names: set[str] = set()
    for p in positions:
        if p < len(call.args):
            arg = call.args[p]
            if isinstance(arg, ast.Name):
                names.add(arg.id)
            elif isinstance(arg, (ast.Tuple, ast.List)):
                for el in arg.elts:
                    if isinstance(el, ast.Name):
                        names.add(el.id)
    return names


class DonationRule(Rule):
    id = RULE_ID
    doc = __doc__

    def check(self, files):
        violations: list[Violation] = []
        dispatch_sites = 0
        for f in files:
            A.annotate_parents(f.tree)
            donating_attrs: dict[str, set[int]] = {}
            donating_builders: dict[str, set[int]] = {}
            # Pass 1: collect.
            for node in ast.walk(f.tree):
                if not (
                    isinstance(node, ast.Call)
                    and A.dotted(node.func) == "jax.jit"
                ):
                    continue
                spec = next(
                    (
                        kw.value for kw in node.keywords
                        if kw.arg == "donate_argnums"
                    ),
                    None,
                )
                if spec is None:
                    continue
                fns = A.enclosing_functions(node)
                positions = _resolve_positions(
                    spec, fns[0] if fns else None
                )
                if positions is None:
                    violations.append(Violation(
                        RULE_ID, f.rel, node.lineno,
                        "donate_argnums is not statically resolvable "
                        "(literal tuple, int, conditional of literals, "
                        "or a local binding of those) — the analyzer "
                        "cannot check post-dispatch reads of what dies "
                        "here",
                    ))
                    continue
                if not positions:
                    continue
                attr, local, _stmt = _unwrap_to_binding(node)
                if attr is not None:
                    donating_attrs[attr] = (
                        donating_attrs.get(attr, set()) | positions
                    )
                elif local is not None and fns:
                    if _builder_returns(fns[0], local):
                        donating_builders[fns[0].name] = (
                            donating_builders.get(fns[0].name, set())
                            | positions
                        )
            if not donating_attrs and not donating_builders:
                continue
            # Pass 2: dispatch sites.
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                positions: Optional[set[int]] = None
                func = node.func
                if A.is_self_attr(func) and func.attr in donating_attrs:
                    positions = donating_attrs[func.attr]
                elif (
                    isinstance(func, ast.Call)
                    and A.is_self_attr(func.func)
                    and func.func.attr in donating_builders
                ):
                    positions = donating_builders[func.func.attr]
                if not positions:
                    continue
                dispatch_sites += 1
                donated = _donated_arg_names(node, positions)
                if not donated:
                    continue
                fns = A.enclosing_functions(node)
                if not fns:
                    continue
                stmt = A.enclosing_statement(node)
                violations.extend(self._reads_after(
                    f, fns[0], stmt, donated, func,
                ))
        self.stats["dispatch_sites"] = dispatch_sites
        return violations

    def _reads_after(self, f, fn_def, dispatch_stmt, donated, func):
        """Loads of ``donated`` names after the dispatch statement,
        before any rebind, in lexical line order.  A read in a SIBLING
        branch of an ancestor if/else (an alternative to the dispatch,
        not its continuation) does not count, and a dispatch that
        itself rebinds the name (``tb = prog(tb, ...)``) kills the
        hazard immediately."""
        out: list[Violation] = []
        start = A.end_line(dispatch_stmt)
        # Names the dispatch statement rebinds from its own result.
        rebound_by_dispatch: set[str] = set()
        for t in A.assign_targets(dispatch_stmt):
            rebound_by_dispatch |= A.name_ids(t)
        events: list[tuple[int, str, str]] = []  # (line, kind, name)
        for node in ast.walk(fn_def):
            if isinstance(node, ast.Name) and node.id in donated:
                if node.lineno <= start:
                    continue
                if _sibling_branches(dispatch_stmt, node):
                    continue
                kind = (
                    "store"
                    if isinstance(node.ctx, (ast.Store, ast.Del))
                    else "load"
                )
                events.append((node.lineno, kind, node.id))
        live = set(donated) - rebound_by_dispatch
        prog = A.dotted(func) or "donating program"
        for line, kind, name in sorted(events):
            if name not in live:
                continue
            if kind == "store":
                live.discard(name)
            else:
                out.append(Violation(
                    RULE_ID, f.rel, line,
                    f"{name!r} was donated to {prog}(...) at line "
                    f"{dispatch_stmt.lineno} — its device buffer is "
                    f"dead; reading it here races the aliased output "
                    f"(rebind it from the dispatch result, or drop "
                    f"donation for this program)",
                ))
                live.discard(name)  # one report per name
        return out
