"""Rule sharding-discipline: order-sensitive device ops must declare a
sharding contract.

GSPMD mis-combines sorts/scans/reshapes along a SHARDED dimension —
the shard-sum miscompile class ``dryrun_multichip`` caught twice (and
once shipped wrong on 11/11 fallback rows, PR 5).  The repo's
convention is the "pack-sort rule": any sort-family op runs with the
axis it orders over whole on every shard.  This rule makes the
convention checkable: every function containing a device sort-family
call (``jnp``/``lax`` ``sort``/``argsort``/``top_k``/``cumsum``/
``cummax``/``cummin``/``argmin``/``argmax``) must sit under (be, or be
lexically nested in) a function decorated with a
``parallel/shardguard.py`` contract — ``@rows_only``, ``@rows_first``,
``@replicated`` or ``@shard_contract(...)`` — naming the layout its
callers must constrain operands to.

Host-side ``numpy`` sorts are exempt (nothing shards them); so are
calls on receivers other than ``jnp``/``jax.numpy``/``lax``/``jax.lax``.
"""

from __future__ import annotations

import ast

from tools.ktlint.engine import Rule, Violation
from tools.ktlint.rules import _astutil as A

RULE_ID = "sharding-discipline"

SORT_FAMILY = {
    "sort", "argsort", "top_k", "approx_max_k", "approx_min_k",
    "cumsum", "cummax", "cummin", "argmin", "argmax",
}

DEVICE_RECEIVERS = {"jnp", "lax", "jax.numpy", "jax.lax"}

CONTRACT_DECORATORS = {
    "rows_only", "rows_first", "replicated", "shard_contract",
}


def _is_device_sort(call: ast.Call) -> bool:
    name = A.dotted(call.func)
    if "." not in name:
        return False
    receiver, _, attr = name.rpartition(".")
    return attr in SORT_FAMILY and receiver in DEVICE_RECEIVERS


def _has_contract(fn: ast.FunctionDef) -> bool:
    return any(
        A.terminal_name(d) in CONTRACT_DECORATORS for d in fn.decorator_list
    )


class ShardingRule(Rule):
    id = RULE_ID
    doc = __doc__

    def check(self, files):
        violations: list[Violation] = []
        sites = 0
        for f in files:
            A.annotate_parents(f.tree)
            for node in ast.walk(f.tree):
                if not (isinstance(node, ast.Call) and _is_device_sort(node)):
                    continue
                sites += 1
                chain = A.enclosing_functions(node)
                if any(_has_contract(fn) for fn in chain):
                    continue
                op = A.dotted(node.func)
                where = (
                    f"in {chain[0].name}()" if chain else "at module level"
                )
                violations.append(Violation(
                    RULE_ID, f.rel, node.lineno,
                    f"{op} {where} has no sharding contract — a sharded "
                    f"operand axis would shard-sum silently under GSPMD; "
                    f"declare @rows_only/@rows_first/@replicated "
                    f"(parallel/shardguard.py) on the enclosing function "
                    f"and constrain its callers to match",
                ))
        self.stats["sort_sites"] = sites
        return violations
