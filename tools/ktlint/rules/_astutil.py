"""Shared AST helpers for ktlint rules: parent links, dotted names,
enclosing-scope walks, simple forward alias tracking."""

from __future__ import annotations

import ast
from typing import Iterator, Optional


def annotate_parents(tree: ast.AST) -> None:
    """Attach ``._kt_parent`` to every node (idempotent)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._kt_parent = node  # type: ignore[attr-defined]


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_kt_parent", None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def dotted(node: ast.AST) -> str:
    """``jax.lax.sort`` for an Attribute chain, ``sort`` for a Name;
    "" for anything else (calls, subscripts)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def terminal_name(node: ast.AST) -> str:
    """The last segment of a Name/Attribute (decorator matching)."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def enclosing_functions(node: ast.AST) -> list[ast.FunctionDef]:
    """Innermost-first chain of enclosing function defs."""
    out = []
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(anc)
    return out


def enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    for anc in ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc
    return None


def enclosing_statement(node: ast.AST) -> ast.stmt:
    """The statement node containing ``node`` (node itself if a stmt)."""
    cur: ast.AST = node
    while not isinstance(cur, ast.stmt):
        nxt = parent(cur)
        if nxt is None:
            raise ValueError("node outside any statement")
        cur = nxt
    return cur  # type: ignore[return-value]


def is_self_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    """``self.<attr>`` (any attr when attr is None)."""
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and (attr is None or node.attr == attr)
    )


def call_args(call: ast.Call) -> list[ast.expr]:
    return list(call.args) + [kw.value for kw in call.keywords]


def assign_targets(stmt: ast.stmt) -> list[ast.expr]:
    if isinstance(stmt, ast.Assign):
        return list(stmt.targets)
    if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        return [stmt.target]
    return []


def name_ids(expr: ast.expr) -> set[str]:
    """Plain Name ids in an expression (tuples flattened)."""
    out: set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            out.add(node.id)
    return out


def end_line(node: ast.AST) -> int:
    return getattr(node, "end_lineno", None) or node.lineno
