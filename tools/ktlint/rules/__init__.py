"""ktlint rule registry.  Add a rule: implement ``engine.Rule`` in a
module here, register it below, give it a fixtures pair under
``tests/fixtures/ktlint/`` and a docs row in docs/static_analysis.md."""

from __future__ import annotations

from tools.ktlint.engine import Rule


def all_rules() -> list[Rule]:
    from tools.ktlint.rules.aot_ledger import AotLedgerRule
    from tools.ktlint.rules.donation import DonationRule
    from tools.ktlint.rules.knobs import KnobCatalogRule
    from tools.ktlint.rules.locks import LockDisciplineRule
    from tools.ktlint.rules.shard_intake import ShardIntakeRule
    from tools.ktlint.rules.sharding import ShardingRule

    return [
        AotLedgerRule(),
        ShardingRule(),
        ShardIntakeRule(),
        DonationRule(),
        KnobCatalogRule(),
        LockDisciplineRule(),
    ]


def rule_by_id(rule_id: str) -> Rule:
    for rule in all_rules():
        if rule.id == rule_id:
            return rule
    raise KeyError(rule_id)
