"""Rule knob-catalog: every ``KT_*`` knob is declared, read, and
documented — zero orphans in either direction.

``runtime/knob_catalog.py`` is the single source of truth (sibling of
``metric_catalog.py``).  Four checks:

1. every literal ``KT_*`` name passed to a call (``os.environ.get``,
   ``os.getenv``, ``setdefault``, the ``_env_float``/``_env_int``
   helpers — ANY call, so helper renames can't dodge the rule) or used
   as an ``environ`` subscript must be cataloged;
2. every exact ``KT_*`` token in ``docs/*.md`` must be cataloged
   (``KT_FOO_*`` wildcards document a family, not an entry);
3. every catalog entry must be read somewhere in code (no dead knobs);
4. every catalog entry must appear in its declared docs anchor file.

Scanned code roots include the bench/CI drivers and
``__graft_entry__.py`` — knobs read only by tooling still bind
operators.  Internal subprocess sentinels (leading underscore,
``_KT_*``) are exempt by convention.
"""

from __future__ import annotations

import ast
import re

from tools.ktlint.engine import REPO, Rule, Violation
from tools.ktlint.rules import _astutil as A

RULE_ID = "knob-catalog"

KNOB_RE = re.compile(r"^KT_[A-Z0-9_]+$")
# Docs tokens: a trailing `*` (with or without a joining underscore,
# `KT_RETRY*` / `KT_RETRY_*`) marks a family wildcard.
DOCS_TOKEN_RE = re.compile(r"\b(KT_[A-Z0-9_]*[A-Z0-9])(_?\*)?")

CODE_ROOTS = (
    "kubeadmiral_tpu", "bench.py", "bench_e2e.py", "tools",
    "__graft_entry__.py", "tpu_capture.py",
)

CATALOG_PATH = "kubeadmiral_tpu/runtime/knob_catalog.py"


def _load_catalog():
    from kubeadmiral_tpu.runtime.knob_catalog import KNOBS

    return KNOBS


def _literal_knobs(call: ast.Call):
    for arg in A.call_args(call):
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if KNOB_RE.match(arg.value):
                yield arg.value, arg.lineno


class KnobCatalogRule(Rule):
    id = RULE_ID
    doc = __doc__
    roots = CODE_ROOTS

    def check(self, files):
        knobs = _load_catalog()
        violations: list[Violation] = []
        # knob -> first (rel, line) read site.
        reads: dict[str, tuple[str, int]] = {}
        for f in files:
            if f.rel == CATALOG_PATH:
                continue  # the declarations themselves
            A.annotate_parents(f.tree)
            for node in ast.walk(f.tree):
                found: list[tuple[str, int]] = []
                if isinstance(node, ast.Call):
                    found = list(_literal_knobs(node))
                elif isinstance(node, ast.Subscript) and (
                    A.terminal_name(node.value) in ("environ",)
                ):
                    sl = node.slice
                    if isinstance(sl, ast.Constant) and isinstance(
                        sl.value, str
                    ) and KNOB_RE.match(sl.value):
                        found = [(sl.value, node.lineno)]
                for name, line in found:
                    reads.setdefault(name, (f.rel, line))
                    if name not in knobs:
                        violations.append(Violation(
                            RULE_ID, f.rel, line,
                            f"env knob {name!r} is not in runtime/"
                            f"knob_catalog.py — catalog it (type/default/"
                            f"doc anchor) and document it before it ships",
                        ))
        if self.partial:
            # Fixture/explicit-file run: per-site checks only — the
            # docs/catalog closure is a property of the full tree.
            self.stats["knob_reads"] = len(reads)
            return violations
        # Docs scan.
        docs_exact: dict[str, tuple[str, int]] = {}
        wildcards: list[str] = []
        for md in sorted((REPO / "docs").glob("*.md")):
            rel = md.relative_to(REPO).as_posix()
            for lineno, line in enumerate(
                md.read_text().splitlines(), start=1
            ):
                for m in DOCS_TOKEN_RE.finditer(line):
                    token, star = m.group(1), m.group(2)
                    if star:
                        wildcards.append(token)
                    else:
                        docs_exact.setdefault(token, (rel, lineno))
        for token, (rel, lineno) in sorted(docs_exact.items()):
            if token not in knobs:
                violations.append(Violation(
                    RULE_ID, rel, lineno,
                    f"docs name env knob {token!r} which is not in "
                    f"runtime/knob_catalog.py — stale docs or an "
                    f"undeclared knob",
                ))
        # Catalog closure: read somewhere + documented in anchor.
        anchor_text: dict[str, str] = {}
        for name, spec in sorted(knobs.items()):
            if name not in reads:
                violations.append(Violation(
                    RULE_ID, CATALOG_PATH, 1,
                    f"cataloged knob {name!r} is read nowhere in code — "
                    f"dead entry; remove it or wire the read",
                ))
            anchor = spec.anchor
            text = anchor_text.get(anchor)
            if text is None:
                anchor_file = REPO / "docs" / anchor
                text = anchor_file.read_text() if anchor_file.exists() else ""
                anchor_text[anchor] = text
            documented = name in docs_exact or any(
                name.startswith(w) for w in wildcards
            )
            if not documented or (text and name not in text and not any(
                name.startswith(w) and w in text for w in wildcards
            )):
                violations.append(Violation(
                    RULE_ID, CATALOG_PATH, 1,
                    f"cataloged knob {name!r} is not documented in "
                    f"docs/{anchor} (its declared anchor) — add the "
                    f"operator-facing row",
                ))
        self.stats["knob_reads"] = len(reads)
        self.stats["docs_tokens"] = len(docs_exact)
        return violations
