"""Rule aot-ledger-coverage: every ``jax.jit`` product routes through
``AotStore.wrap`` AND the dispatch ledger's ``_obs_wrap``.

Generalizes tests/test_aot_coverage.py's hand-rolled source enumeration
(which covered ``scheduler/engine.py`` only) to the whole package: a
jitted program a builder forgets to wrap silently escapes warm-boot
failover (no export) and /debug/waterfall (no device-time attribution)
— the exact bug class the replan/score-only/tiebreak kernels nearly
shipped with.

A jit site is AOT-ROUTED when the ``jax.jit(...)`` call is an argument
of a ``*.wrap(...)`` / ``aot(...)`` call, directly or through local
name flow inside the same function (``fn = jax.jit(...); fn =
self._aot.wrap(key, fn)``).  It is LEDGER-ROUTED when the product (or
an alias, or the ``self.<attr>`` it lands on) is passed to
``_obs_wrap`` — anywhere in the same class, because ``_build_programs``
assigns and ``_instrument_programs`` wraps.  ``@jax.jit`` decorators
can never be routed and always flag (suppress with a written reason
when the function is an oracle/test entry point the engine re-traces
via ``__wrapped__``).
"""

from __future__ import annotations

import ast

from tools.ktlint.engine import Rule, SourceFile, Violation
from tools.ktlint.rules import _astutil as A

RULE_ID = "aot-ledger-coverage"


def _is_jit(node: ast.AST) -> bool:
    return A.dotted(node) in ("jax.jit",)


def _is_wrap_call(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr == "wrap":
        return True
    if isinstance(func, ast.Name) and func.id == "aot":
        return True
    return False


def _is_obs_call(call: ast.Call) -> bool:
    return A.terminal_name(call.func) == "_obs_wrap"


def _flow(
    fn_def: ast.AST, start_stmt: ast.stmt, seeds: set[str],
) -> tuple[bool, bool, set[str]]:
    """Forward alias walk from ``seeds`` (the jit product's names)
    through the enclosing def: returns (aot_routed, obs_routed,
    self_attrs) where self_attrs are ``self.X`` attributes the product
    (or a wrapped alias) is stored into."""
    aot_ok = False
    obs_ok = False
    aliases = set(seeds)
    self_attrs: set[str] = set()
    stmts = sorted(
        (s for s in ast.walk(fn_def) if isinstance(s, ast.stmt)),
        key=lambda s: s.lineno,
    )
    for stmt in stmts:
        if stmt.lineno < start_stmt.lineno:
            continue
        for call in ast.walk(stmt):
            if not isinstance(call, ast.Call):
                continue
            hits = any(
                isinstance(a, ast.Name) and a.id in aliases
                for a in A.call_args(call)
            )
            if not hits:
                continue
            if _is_wrap_call(call):
                aot_ok = True
            if _is_obs_call(call):
                obs_ok = True
            # Propagate through any single-call assignment:
            # fn = self._obs_wrap("k", fn) keeps `fn` an alias.
            outer = A.parent(call)
            if isinstance(outer, ast.Assign):
                for t in outer.targets:
                    if isinstance(t, ast.Name):
                        aliases.add(t.id)
                    elif A.is_self_attr(t):
                        self_attrs.add(t.attr)  # type: ignore[union-attr]
                    elif isinstance(t, ast.Subscript) and A.is_self_attr(
                        t.value
                    ):
                        pass  # program-cache store; routing already decided
    return aot_ok, obs_ok, self_attrs


def _class_obs_attrs(cls: ast.ClassDef) -> set[str]:
    """``self.X`` attribute names the class passes to ``_obs_wrap``
    anywhere (the _build_programs / _instrument_programs split)."""
    out: set[str] = set()
    for call in ast.walk(cls):
        if isinstance(call, ast.Call) and _is_obs_call(call):
            for a in A.call_args(call):
                if A.is_self_attr(a):
                    out.add(a.attr)  # type: ignore[union-attr]
    return out


class AotLedgerRule(Rule):
    id = RULE_ID
    doc = __doc__

    def check(self, files):
        violations: list[Violation] = []
        sites = 0
        for f in files:
            A.annotate_parents(f.tree)
            for node in ast.walk(f.tree):
                # @jax.jit decorators.
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    for deco in node.decorator_list:
                        target = (
                            deco.func if isinstance(deco, ast.Call) else deco
                        )
                        if _is_jit(target) or (
                            isinstance(deco, ast.Call)
                            and A.terminal_name(deco.func) == "partial"
                            and any(_is_jit(a) for a in deco.args)
                        ):
                            sites += 1
                            violations.append(Violation(
                                RULE_ID, f.rel, deco.lineno,
                                f"@jax.jit on {node.name}() cannot route "
                                f"through AotStore.wrap/_obs_wrap — jit at "
                                f"the dispatch site instead, or suppress "
                                f"with the reason this program is outside "
                                f"the engine's dispatch surface",
                            ))
                if not (isinstance(node, ast.Call) and _is_jit(node.func)):
                    continue
                sites += 1
                aot_ok = False
                obs_ok = False
                # Directly nested in a wrap()/aot() call?
                for anc in A.ancestors(node):
                    if isinstance(anc, ast.Call):
                        if _is_wrap_call(anc):
                            aot_ok = True
                        if _is_obs_call(anc):
                            obs_ok = True
                    if isinstance(anc, ast.stmt):
                        break
                stmt = A.enclosing_statement(node)
                targets = A.assign_targets(stmt)
                seeds = {
                    t.id for t in targets if isinstance(t, ast.Name)
                }
                attr_targets = {
                    t.attr for t in targets if A.is_self_attr(t)
                }
                fns = A.enclosing_functions(node)
                flow_attrs: set[str] = set()
                if fns and (seeds or not (aot_ok and obs_ok)):
                    fa, fo, flow_attrs = _flow(fns[0], stmt, seeds)
                    aot_ok = aot_ok or fa
                    obs_ok = obs_ok or fo
                attr_targets |= flow_attrs
                if not obs_ok and attr_targets:
                    cls = A.enclosing_class(node)
                    if cls is not None and (
                        attr_targets & _class_obs_attrs(cls)
                    ):
                        obs_ok = True
                if not aot_ok:
                    violations.append(Violation(
                        RULE_ID, f.rel, node.lineno,
                        "jax.jit product does not route through "
                        "AotStore.wrap — warm-boot failover cannot "
                        "preload it (scheduler/aot.py)",
                    ))
                if not obs_ok:
                    violations.append(Violation(
                        RULE_ID, f.rel, node.lineno,
                        "jax.jit product does not route through "
                        "_obs_wrap — the dispatch ledger cannot "
                        "attribute its device time (runtime/devprof.py)",
                    ))
        self.stats["jit_sites"] = sites
        return violations
