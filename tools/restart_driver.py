#!/usr/bin/env python
"""Kill-matrix driver for the crash-recovery harness (tests/test_restart.py).

One deterministic world, three processes:

* ``victim``  — runs tick 1 (cold) and tick 2 (churn) with per-tick
  durable snapshots, then starts tick 3 and SIGKILLs ITSELF at the
  requested phase (``KT_RESTART_KILL_PHASE``): ``featurize``,
  ``dispatch`` (mid device program), ``fetch`` (mid device->host read),
  ``snapshot-write`` (mid payload write, torn temp file),
  ``snapshot-rename`` (payload complete, rename not performed),
  ``dispatch-flush`` (tick + snapshot complete, killed mid member
  flush).  Self-SIGKILL at the phase makes the cut deterministic — no
  parent timing race.
* ``successor`` — fresh process over the same directories: restores the
  newest valid snapshot, rebuilds the FINAL (tick 3) world from the
  shared seed, runs one tick to convergence, and writes an artifact
  with its placements, flight-recorder reason counts, restore outcome,
  AOT stats and persistent-cache counters.
* ``reference`` — fresh process, no snapshots, runs ticks 1..3
  uninterrupted and writes the same artifact shape.

The harness asserts successor.placements == reference.placements and
successor.reason_counts == reference.reason_counts, bit-identical —
whatever phase the victim died in.

Env: ``KT_RESTART_DIR`` (workdir; snapshots under <dir>/snapshots,
artifacts as JSON), ``KT_RESTART_OBJECTS``/``KT_RESTART_CLUSTERS``
(world shape), ``KT_RESTART_PREWARM=1`` (run the prewarm ladder —
exports/loads AOT programs when KT_AOT is on).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys

import numpy as np

SEED = 20260804


def build_world(n: int, c: int):
    from kubeadmiral_tpu.models import types as T

    rng = np.random.default_rng(SEED)
    clusters = [
        T.ClusterState(
            name=f"m-{j:03d}",
            labels={"region": ("us", "eu", "ap")[j % 3], "tier": str(j % 2)},
            taints=(T.Taint("dedicated", "batch", "NoSchedule"),)
            if j % 7 == 0
            else (),
            allocatable=T.parse_resources({"cpu": "64", "memory": "256Gi"}),
            available=T.parse_resources(
                {"cpu": f"{int(rng.integers(8, 60))}", "memory": "128Gi"}
            ),
            api_resources=frozenset({"apps/v1/Deployment"}),
        )
        for j in range(c)
    ]
    units = [
        T.SchedulingUnit(
            gvk="apps/v1/Deployment",
            namespace=f"ns-{i % 7}",
            name=f"w-{i:05d}",
            scheduling_mode=T.MODE_DIVIDE if i % 4 else "Duplicate",
            desired_replicas=int(rng.integers(1, 60)) if i % 4 else None,
            resource_request=T.parse_resources(
                {"cpu": f"{int(rng.integers(0, 6)) * 250}m"}
            ),
            tolerations=(T.Toleration(key="dedicated", operator="Exists"),)
            if i % 3 == 0
            else (),
            max_clusters=int(rng.integers(1, 5)) if i % 5 == 0 else None,
        )
        for i in range(n)
    ]
    return units, clusters


def churn(units, round_no: int):
    """Deterministic ~4% churn per round (same function in every
    process, so victim / successor / reference worlds line up)."""
    rng = np.random.default_rng(SEED + round_no)
    out = list(units)
    for i in rng.integers(0, len(units), max(1, len(units) // 25)):
        su = units[int(i)]
        out[int(i)] = dataclasses.replace(
            su,
            desired_replicas=(su.desired_replicas or 1) + int(rng.integers(1, 9)),
        )
    return out


def world_at(tick: int, n: int, c: int):
    units, clusters = build_world(n, c)
    for r in range(1, tick):
        units = churn(units, r)
    return units, clusters


def make_stack(workdir: str):
    from kubeadmiral_tpu.runtime.flightrec import get_default
    from kubeadmiral_tpu.runtime.metrics import Metrics
    from kubeadmiral_tpu.runtime.snapshot import SnapshotManager, SnapshotStore
    from kubeadmiral_tpu.scheduler.engine import SchedulerEngine
    from kubeadmiral_tpu.transport.breaker import BreakerRegistry

    metrics = Metrics()
    engine = SchedulerEngine(mesh=None, metrics=metrics)
    breakers = BreakerRegistry(metrics=metrics)
    store = SnapshotStore(os.path.join(workdir, "snapshots"), metrics=metrics)
    mgr = SnapshotManager(
        engine, store, every=1, breakers=breakers, flightrec=get_default()
    )
    return engine, metrics, breakers, store, mgr


def artifact(engine, metrics, results, units, extra: dict) -> dict:
    from kubeadmiral_tpu.runtime.flightrec import get_default

    placements = {
        u.key: {
            cl: (None if reps is None else int(reps))
            for cl, reps in sorted(r.clusters.items())
        }
        for u, r in zip(units, results)
    }
    rec = get_default()
    reason_counts = {}
    for u in units:
        record = rec.lookup(u.key)
        if record is not None:
            reason_counts[u.key] = [int(x) for x in record.reason_counts]
    snap = metrics.snapshot()
    counters = {
        k: v
        for k, v in snap["counters"].items()
        if k.startswith(("engine_persistent_cache_total", "engine_aot_programs_total",
                         "engine_snapshot_total"))
    }
    return {
        "placements": placements,
        "reason_counts": reason_counts,
        "counters": counters,
        "aot": dict(engine._aot.stats),
        **extra,
    }


def install_kill(engine, phase: str) -> None:
    def die(*_a, **_k):
        os.kill(os.getpid(), 9)

    if phase == "featurize":
        engine._featurize_chunk = die
    elif phase == "dispatch":
        # Kill with the program call in flight: the tick was dispatched
        # but its results never observed.
        tick_c, tick_d = engine._tick_compact, engine._tick

        def kill_after_dispatch_c(*a):
            tick_c(*a)
            os.kill(os.getpid(), 9)

        def kill_after_dispatch_d(*a):
            tick_d(*a)
            os.kill(os.getpid(), 9)

        engine._tick_compact = kill_after_dispatch_c
        engine._tick = kill_after_dispatch_d
    elif phase == "fetch":
        engine._read_np = die
    elif phase == "snapshot-write":
        os.environ["KT_SNAPSHOT_KILL"] = "mid-write"
    elif phase == "snapshot-rename":
        os.environ["KT_SNAPSHOT_KILL"] = "pre-rename"
    elif phase == "dispatch-flush":
        pass  # installed at the sink below
    else:
        raise SystemExit(f"unknown kill phase {phase!r}")


def flush_placements(results, units, kill: bool) -> None:
    """A member-flush stand-in: stage one write per scheduled object
    into a BatchSink over an in-process member and flush; with ``kill``
    the member client SIGKILLs the process mid-batch — the
    ``dispatch-flush`` phase of the matrix."""
    from kubeadmiral_tpu.federation.dispatch import BatchSink
    from kubeadmiral_tpu.testing.fakekube import FakeKube

    member = FakeKube("member-durable")

    class KillingKube:
        def __init__(self, inner, after: int):
            self._inner = inner
            self._after = after
            self._seen = 0

        def batch(self, ops):
            self._seen += len(ops)
            if kill and self._seen >= self._after:
                os.kill(os.getpid(), 9)
            return self._inner.batch(ops)

    client = KillingKube(member, after=max(1, len(units) // 2))
    sink = BatchSink(lambda _c: client)
    for u, r in zip(units, results):
        if not r.clusters:
            continue
        sink.submit(
            "m-000",
            {
                "verb": "create",
                "resource": "v1/configmaps",
                "object": {
                    "apiVersion": "v1", "kind": "ConfigMap",
                    "metadata": {"namespace": u.namespace, "name": u.name},
                    "data": {k: str(v) for k, v in sorted(r.clusters.items())},
                },
            },
            lambda _res: None,
        )
    sink.flush()


def main() -> int:
    mode = sys.argv[1]
    workdir = os.environ["KT_RESTART_DIR"]
    n = int(os.environ.get("KT_RESTART_OBJECTS", "192"))
    c = int(os.environ.get("KT_RESTART_CLUSTERS", "10"))
    prewarm = os.environ.get("KT_RESTART_PREWARM") == "1"
    os.makedirs(workdir, exist_ok=True)

    engine, metrics, breakers, store, mgr = make_stack(workdir)
    if prewarm:
        engine.prewarm(n, c, wait=True)

    if mode == "victim":
        phase = os.environ.get("KT_RESTART_KILL_PHASE", "")
        units, clusters = world_at(1, n, c)
        engine.schedule(units, clusters)
        open(os.path.join(workdir, "tick1.done"), "w").write("1")
        units = churn(units, 1)
        engine.schedule(units, clusters)
        open(os.path.join(workdir, "tick2.done"), "w").write("1")
        # A breaker opened pre-crash: the successor must keep skipping
        # this member instead of probing it fresh.
        breakers.for_member("m-001").record_failure(timeout=True)
        mgr.snapshot()  # re-persist with the open breaker riding along
        if phase:
            install_kill(engine, phase)
        units = churn(units, 2)
        results = engine.schedule(units, clusters)
        open(os.path.join(workdir, "tick3.done"), "w").write("1")
        flush_placements(results, units, kill=(phase == "dispatch-flush"))
        # Reaching here means the kill never fired — the harness treats
        # a 0 exit from a victim as a matrix failure.
        return 0

    if mode == "successor":
        restore_result = mgr.restore()
        units, clusters = world_at(3, n, c)
        results = engine.schedule(units, clusters)
        doc = artifact(
            engine, metrics, results, units,
            {
                "restore": restore_result,
                "restore_info": engine.restore_info,
                "breaker_m001": breakers.for_member("m-001").state,
                "breaker_allows_m001": breakers.allow(
                    "m-001", consume_probe=False
                ),
                "fetch_paths": dict(engine.fetch_stats),
                "quarantined": sorted(
                    f for f in os.listdir(os.path.join(workdir, "snapshots"))
                    if f.endswith(".quarantined")
                ),
            },
        )
        out = os.environ.get("KT_RESTART_ARTIFACT", "successor.json")
        with open(os.path.join(workdir, out), "w") as fh:
            json.dump(doc, fh)
        return 0

    if mode == "reference":
        units, clusters = world_at(1, n, c)
        engine.schedule(units, clusters)
        units = churn(units, 1)
        engine.schedule(units, clusters)
        units = churn(units, 2)
        results = engine.schedule(units, clusters)
        doc = artifact(engine, metrics, results, units, {"restore": "none"})
        with open(os.path.join(workdir, "reference.json"), "w") as fh:
            json.dump(doc, fh)
        return 0

    raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    raise SystemExit(main())
