"""Store/notify microbench — the in-process hot path, standalone.

Measures what bench_e2e can only infer from stage splits: raw store
writes/s through the direct verbs and the columnar ``batch`` verb, and
the watch fan-out cost per event with a controller-fleet-sized watcher
population (each watcher computing the metadata-change trigger
signature, the way federate/scheduler/override do at the watch
boundary).  Both KT_STORE_COALESCE modes run side by side, so a store
regression shows up here — seconds, one process — before it shows up
as an e2e sync-stage regression.

Emits one raw-JSON artifact line (save as ``BENCH_STORE_rNN.json``);
``tools/bench_gate.py`` gates writes/s (floor) and notify fan-out
µs/event (ceiling) against the best same-platform prior.

Usage: ``make bench-store`` (or ``python tools/store_bench.py``).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N_OBJECTS = int(os.environ.get("BENCH_STORE_OBJECTS", "2000"))
N_ROUNDS = int(os.environ.get("BENCH_STORE_ROUNDS", "5"))
N_WATCHERS = int(os.environ.get("BENCH_STORE_WATCHERS", "12"))
CHUNK = int(os.environ.get("BENCH_STORE_CHUNK", "200"))
RESOURCE = "apps/v1/deployments"


def _obj(i: int, replicas: int = 1) -> dict:
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": f"web-{i:05d}",
            "namespace": "default",
            "labels": {"app": "web", "tier": "bench"},
            "annotations": {"bench/round": "0"},
        },
        "spec": {
            "replicas": replicas,
            "template": {"spec": {"containers": [{"name": "c", "image": "img"}]}},
        },
    }


class _SigWatcher:
    """A controller-shaped watcher: computes the metadata-change trigger
    signature of every delivered object (what federate/scheduler/
    override do first thing in their handlers) and counts events."""

    def __init__(self, sig_fn):
        self.sig_fn = sig_fn
        self.events = 0
        self.sig = 0

    def __call__(self, event: str, obj: dict) -> None:
        self.events += 1
        self.sig ^= self.sig_fn(obj)


class _BatchSigWatcher(_SigWatcher):
    """Same controller shape, advertising the coalesced-delivery
    protocol: one call per committed flush."""

    def __init__(self, sig_fn):
        super().__init__(sig_fn)
        self.kt_batch = self._on_batch
        self.flushes = 0

    def _on_batch(self, events) -> None:
        self.flushes += 1
        for event, obj in events:
            self(event, obj)


def _bench_direct(fk_module, sig_fn) -> dict:
    """Per-op verbs, per-event delivery: create + update_status rounds."""
    store = fk_module.FakeKube("bench")
    watchers = [_SigWatcher(sig_fn) for _ in range(N_WATCHERS)]
    for w in watchers:
        store.watch(RESOURCE, w, replay=False)
    t0 = time.perf_counter()
    for i in range(N_OBJECTS):
        store.create(RESOURCE, _obj(i))
    for r in range(N_ROUNDS):
        for i in range(N_OBJECTS):
            store.update_status(
                RESOURCE,
                {
                    "metadata": {"name": f"web-{i:05d}", "namespace": "default"},
                    "status": {"readyReplicas": r},
                },
            )
    dt = time.perf_counter() - t0
    writes = N_OBJECTS * (1 + N_ROUNDS)
    events = sum(w.events for w in watchers)
    return {
        "writes": writes,
        "seconds": round(dt, 4),
        "writes_per_s": round(writes / dt, 1),
        "notify_us_per_event": round(dt / events * 1e6, 3) if events else None,
        "events_delivered": events,
    }


def _bench_batch(fk_module, sig_fn, batch_watchers: bool) -> dict:
    """The bulk verb in CHUNK-sized flushes — the shape sync's coalesced
    member writes take."""
    store = fk_module.FakeKube("bench")
    cls = _BatchSigWatcher if batch_watchers else _SigWatcher
    watchers = [cls(sig_fn) for _ in range(N_WATCHERS)]
    for w in watchers:
        store.watch(RESOURCE, w, replay=False)
    ops = [
        {"verb": "create", "resource": RESOURCE, "object": _obj(i)}
        for i in range(N_OBJECTS)
    ]
    for r in range(N_ROUNDS):
        ops.extend(
            {
                "verb": "update_status",
                "resource": RESOURCE,
                "object": {
                    "metadata": {"name": f"web-{i:05d}", "namespace": "default"},
                    "status": {"readyReplicas": r},
                },
            }
            for i in range(N_OBJECTS)
        )
    t0 = time.perf_counter()
    for i in range(0, len(ops), CHUNK):
        results = store.batch(ops[i : i + CHUNK])
        bad = [r for r in results if r["code"] not in (200, 201)]
        assert not bad, bad[:3]
    dt = time.perf_counter() - t0
    events = sum(w.events for w in watchers)
    return {
        "writes": len(ops),
        "seconds": round(dt, 4),
        "writes_per_s": round(len(ops) / dt, 1),
        "notify_us_per_event": round(dt / events * 1e6, 3) if events else None,
        "events_delivered": events,
        "flushes": sum(getattr(w, "flushes", 0) for w in watchers),
    }


def main() -> None:
    from kubeadmiral_tpu.bench_support import bench_platform_detail
    from kubeadmiral_tpu.federation.common import metadata_change_sig

    results: dict[str, dict] = {}
    for mode, env in (("coalesced", "1"), ("legacy", "0")):
        # Stores resolve the knob at construction, so both modes run in
        # one process, one artifact.
        os.environ["KT_STORE_COALESCE"] = env
        from kubeadmiral_tpu.testing import fakekube as fk

        results[mode] = {
            "direct": _bench_direct(fk, metadata_change_sig),
            "batch": _bench_batch(
                fk, metadata_change_sig, batch_watchers=(mode == "coalesced")
            ),
        }
    os.environ.pop("KT_STORE_COALESCE", None)

    # Bit-identity cross-check rides the bench: both modes delivered the
    # same event count and the same XOR of trigger signatures.
    for kind in ("direct", "batch"):
        a, b = results["coalesced"][kind], results["legacy"][kind]
        assert a["events_delivered"] == b["events_delivered"], (kind, a, b)

    coalesced = results["coalesced"]["batch"]
    print(
        json.dumps(
            {
                "metric": "store_batch_writes_per_sec",
                "value": coalesced["writes_per_s"],
                "unit": "writes/s",
                "detail": {
                    **bench_platform_detail(),
                    "objects": N_OBJECTS,
                    "rounds": N_ROUNDS,
                    "watchers": N_WATCHERS,
                    "chunk": CHUNK,
                    "notify_us_per_event": coalesced["notify_us_per_event"],
                    "modes": results,
                },
            }
        )
    )
    print(
        f"# store: coalesced batch {coalesced['writes_per_s']:.0f} w/s, "
        f"legacy batch {results['legacy']['batch']['writes_per_s']:.0f} w/s, "
        f"direct {results['coalesced']['direct']['writes_per_s']:.0f} w/s",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
