"""Benchmark: batched TPU scheduling tick vs the native sequential scheduler.

Configs (BASELINE.md; select with BENCH_CONFIG, override shapes with
BENCH_OBJECTS / BENCH_CLUSTERS):

  3 (default) 10k mixed Deployment/StatefulSet x 500 clusters —
     taint/affinity masks, static+dynamic weights, capacity feedback.
  4  50k x 2k — dynamic-weight rebalancing with status-aggregation
     feedback: every object carries current placements and avoids
     disruption, capacity caps arrive from auto-migration.
  5  100k x 5k — multi-resource (cpu/mem/gpu) bin-pack scoring plus a
     follower-scheduling dependency DAG (10% followers take the union
     of their leaders' placements after the tick).

Baseline: the native C++ sequential scheduler
(kubeadmiral_tpu/native/seqsched.cpp), a compiled re-statement of the
reference's in-process per-object control flow (reference:
pkg/controllers/scheduler/core/generic_scheduler.go via
framework/runtime plugin loops + util/planner/planner.go),
differentially tested against the Python oracle.  The Go toolchain is
absent in this image, so g++ -O3 stands in for Go: same algorithm, same
performance class.  It consumes the already-featurized arrays, so the
baseline is NOT charged for featurization — only the batched path pays
host encoding in its tick time.

Prints exactly one JSON line:
  {"metric", "value", "unit", "vs_baseline", "detail": {...}}

Platform resilience (the round-3 lesson: a wedged TPU relay zeroed the
round's evidence): the launcher probes the chip in a SUBPROCESS with a
timeout and retries with backoff — the single-tenant tunneled chip can
be wedged by a stale claim for minutes.  On persistent unavailability
the bench re-execs itself on CPU and emits the same JSON artifact with
"platform": "cpu-fallback" (+ the probe error), exit code 0.  A bench
run must degrade, never crash.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

def ktlint_summary():
    """Per-rule static-analysis violation counts embedded in the BENCH
    artifact (detail.ktlint): tools/bench_gate.py fails a round where a
    previously-clean rule regresses, so an invariant break cannot ride
    in on a green perf number (ISSUE 14).  Never fails the bench
    itself — a broken analyzer reports as {"error": ...}."""
    try:
        from tools.ktlint import summary

        return summary()
    except Exception as e:  # pragma: no cover - defensive
        return {"error": str(e)}


CONFIG = os.environ.get("BENCH_CONFIG", "3")
SHAPES = {"3": (10_000, 500), "4": (50_000, 2_000), "5": (100_000, 5_000)}
N_OBJECTS, N_CLUSTERS = SHAPES.get(CONFIG, SHAPES["3"])
N_OBJECTS = int(os.environ.get("BENCH_OBJECTS", N_OBJECTS))
N_CLUSTERS = int(os.environ.get("BENCH_CLUSTERS", N_CLUSTERS))
TICKS = int(os.environ.get("BENCH_TICKS", 3))
CHUNK = int(os.environ.get("BENCH_CHUNK", 4096))


def build_world(rng):
    from kubeadmiral_tpu.models.types import (
        AutoMigrationSpec,
        ClusterAffinity,
        ClusterState,
        CLUSTER_RESOURCES_MOST,
        MODE_DIVIDE,
        PreferredSchedulingTerm,
        SelectorRequirement,
        SelectorTerm,
        SchedulingUnit,
        Taint,
        TAINT_TOLERATION,
        Toleration,
        parse_resources,
    )

    gvks = ("apps/v1/Deployment", "apps/v1/StatefulSet")
    regions = ("us", "eu", "ap")
    gpu = CONFIG == "5"
    clusters = []
    for j in range(N_CLUSTERS):
        cpu = int(rng.integers(32, 512))
        mem_gi = int(rng.integers(128, 2048))
        free_frac = float(rng.uniform(0.1, 0.9))
        alloc = {"cpu": str(cpu), "memory": f"{mem_gi}Gi"}
        avail = {
            "cpu": f"{int(cpu * free_frac * 1000)}m",
            "memory": f"{int(mem_gi * free_frac)}Gi",
        }
        if gpu and j % 3 == 0:
            n_gpu = int(rng.integers(4, 64))
            alloc["nvidia.com/gpu"] = str(n_gpu)
            avail["nvidia.com/gpu"] = str(int(n_gpu * free_frac))
        clusters.append(
            ClusterState(
                name=f"member-{j:05d}",
                labels={
                    "region": regions[j % 3],
                    "zone": f"z{j % 17}",
                    "tier": str(j % 4),
                },
                taints=(Taint("dedicated", "batch", "NoSchedule"),)
                if j % 11 == 0
                else (),
                allocatable=parse_resources(alloc),
                available=parse_resources(avail),
                api_resources=frozenset(gvks),
            )
        )
    names = [c.name for c in clusters]

    affinities = [None] + [
        ClusterAffinity(
            required=(
                SelectorTerm(
                    match_expressions=(
                        SelectorRequirement("region", "In", (regions[k],)),
                    )
                ),
            ),
            preferred=(
                PreferredSchedulingTerm(
                    weight=30,
                    preference=SelectorTerm(
                        match_expressions=(
                            SelectorRequirement("tier", "In", ("0", "1")),
                        )
                    ),
                ),
            ),
        )
        for k in range(3)
    ] + [None]

    # Config 4: steady-state rebalance — objects carry current
    # placements (as if read back from status aggregation) and avoid
    # disruption; auto-migration capacity estimates cap some clusters.
    steady = CONFIG == "4"
    # Config 5: bin-pack profile (MostAllocated replaces the default
    # spreading scores) and gpu requests on a third of the fleet.
    binpack_scores = (TAINT_TOLERATION, CLUSTER_RESOURCES_MOST)

    units = []
    followers = []
    for i in range(N_OBJECTS):
        if CONFIG == "5" and i % 10 == 9:
            followers.append(i)  # placement = union of leaders, post-tick
        divide = i % 4 != 0
        request = {
            "cpu": f"{int(rng.integers(0, 8)) * 250}m",
            "memory": f"{int(rng.integers(0, 16)) * 256}Mi",
        }
        if gpu and i % 3 == 0:
            request["nvidia.com/gpu"] = str(int(rng.integers(1, 4)))
        current = {}
        if steady:
            spread = int(rng.integers(1, 6))
            picks = rng.integers(0, N_CLUSTERS, spread)
            reps = int(rng.integers(1, 40))
            current = {names[int(p)]: reps for p in picks}
        units.append(
            SchedulingUnit(
                gvk=gvks[i % 2],
                namespace=f"ns-{i % 97}",
                name=f"workload-{i:06d}",
                scheduling_mode=MODE_DIVIDE if divide else "Duplicate",
                desired_replicas=int(rng.integers(1, 100)) if divide else None,
                resource_request=parse_resources(request),
                current_clusters=current,
                tolerations=(Toleration(key="dedicated", operator="Exists"),)
                if i % 3 == 0
                else (),
                affinity=affinities[i % len(affinities)],
                max_clusters=int(rng.integers(1, 20)) if i % 5 == 0 else None,
                avoid_disruption=steady or bool(i % 2),
                enabled_scores=binpack_scores if CONFIG == "5" else None,
                auto_migration=AutoMigrationSpec(
                    estimated_capacity={
                        names[int(rng.integers(0, N_CLUSTERS))]: int(
                            rng.integers(0, 50)
                        )
                    }
                )
                if i % 7 == 0
                else None,
            )
        )
    return units, clusters, followers


def follower_index(followers):
    """Follower scheduling: placement = union of the leaders' clusters
    (reference: pkg/controllers/follower/controller.go:95-521 writes
    spec.follows so follower placement covers its leaders).  Bench
    models each follower following its 3 preceding leaders; the union
    itself is the engine-side incremental capability (ops/follower.py),
    driven by the tick's changed-row set."""
    from kubeadmiral_tpu.ops.follower import FollowerIndex

    return FollowerIndex({i: range(max(0, i - 3), i) for i in followers})


def churn(rng, units, fraction=0.01):
    """Steady-state tick workload: ~1% of objects changed since the last
    tick (new replica counts / requests), the rest untouched — what a
    live control plane's re-tick looks like after trigger dedupe."""
    import dataclasses

    out = list(units)
    n = max(1, int(len(units) * fraction))
    for i in rng.integers(0, len(units), n):
        su = units[int(i)]
        out[int(i)] = dataclasses.replace(
            su,
            desired_replicas=(su.desired_replicas or 1) + int(rng.integers(1, 9)),
        )
    return out


def time_batched(rng, units, clusters, followers):
    from kubeadmiral_tpu.runtime.metrics import Metrics
    from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

    # A real registry (not the null default): the engine's labeled
    # series — stage histograms, compile-cache and fetch-path counters —
    # are embedded in the BENCH artifact below, so the perf trajectory
    # and a live /metrics scrape share one vocabulary
    # (runtime/metric_catalog.py).
    metrics = Metrics()
    engine = SchedulerEngine(chunk_size=CHUNK, metrics=metrics)
    fidx = follower_index(followers) if followers else None
    # Pre-warm exactly as the production manager does at start
    # (ControllerManager.run): the ladder's tick/gather programs compile
    # (or load from the persistent cache) BEFORE the first real tick.
    # Timed separately so the cold tick below reflects what a prewarmed
    # control plane actually pays.
    t_warm = time.perf_counter()
    engine.prewarm(
        N_OBJECTS,
        N_CLUSTERS,
        scalar_resources=("nvidia.com/gpu",) if CONFIG == "5" else (),
        wait=True,
    )
    prewarm_s = time.perf_counter() - t_warm
    # Cold tick: featurizes from scratch, uploads everything, fetches
    # everything — against prewarmed programs.
    dispatches0 = engine.dispatches_total
    feat_rows0 = dict(engine.featurize_rows)
    t_cold = time.perf_counter()
    engine.schedule(units, clusters, follower_index=fidx)
    cold_ms = (time.perf_counter() - t_cold) * 1e3
    cold_dispatches = engine.dispatches_total - dispatches0
    cold_featurize_ms = round(engine.timings["featurize"] * 1e3, 1)
    cold_feat_rows = {
        k: engine.featurize_rows[k] - feat_rows0[k] for k in feat_rows0
    }
    # One churned tick outside the timing loop (first sub-batch shapes).
    units = churn(rng, units)
    engine.schedule(units, clusters, follower_index=fidx)
    # No-op tick: byte-identical world — the engine's trigger-skip path.
    t_noop = time.perf_counter()
    engine.schedule(units, clusters, follower_index=fidx)
    noop_ms = (time.perf_counter() - t_noop) * 1e3

    # Timed ticks: full-batch revalidation with 1% churn.  Same work
    # semantics as the sequential baseline (every object re-decided
    # against current cluster state), exercised through the incremental
    # patch + on-device delta-fetch machinery.
    detail = {"featurize": 0.0, "device": 0.0, "fetch": 0.0, "decode": 0.0}
    fetch_bytes0 = engine.fetch_bytes_total
    overflow_t0 = engine.overflow_rows_total
    feat_rows0 = dict(engine.featurize_rows)
    # Optional jax.profiler capture around the timed ticks
    # (KT_PROFILE_TICKS=N, artifact under KT_PROFILE_DIR): what
    # tpu_capture.py uses to grab one on-chip trace per window.
    profile_ticks = int(os.environ.get("KT_PROFILE_TICKS", "0") or 0)
    profile_dir = None
    timed_tick_ids = []
    tick_walls = []
    t0 = time.perf_counter()
    for i in range(TICKS):
        if profile_ticks and i == 0:
            from kubeadmiral_tpu.runtime import devprof as _devprof

            import jax as _jax

            profile_dir = os.path.join(
                _devprof.profile_dir(),
                time.strftime("%Y%m%d-%H%M%S") + f"-bench-c{CONFIG}",
            )
            os.makedirs(profile_dir, exist_ok=True)
            _jax.profiler.start_trace(profile_dir)
        t_tick = time.perf_counter()
        units = churn(rng, units)
        results = engine.schedule(units, clusters, follower_index=fidx)
        tick_walls.append(time.perf_counter() - t_tick)
        timed_tick_ids.append(engine.last_tick_id)
        for stage, secs in engine.timings.items():
            detail[stage] = detail.get(stage, 0.0) + secs
        if profile_ticks and i + 1 == min(profile_ticks, TICKS):
            import jax as _jax

            _jax.profiler.stop_trace()
            profile_ticks = 0
    dt = (time.perf_counter() - t0) / TICKS
    tick_fetch_bytes = (engine.fetch_bytes_total - fetch_bytes0) / TICKS
    tick_overflow_rows = (engine.overflow_rows_total - overflow_t0) / TICKS
    steady_feat_rows = {
        k: round((engine.featurize_rows[k] - feat_rows0[k]) / TICKS, 1)
        for k in feat_rows0
    }
    placed = sum(1 for r in results if r.clusters)

    # Drift tick: one cluster's resources changed — every row must be
    # revalidated on device (the full-dispatch path with delta fetch).
    import dataclasses

    drifted = list(clusters)
    drifted[0] = dataclasses.replace(
        drifted[0],
        available={k: max(0, v // 2) for k, v in drifted[0].available.items()},
    )
    drift_dispatches0 = engine.dispatches_total
    drift_upload0 = dict(engine.upload_bytes)
    drift_overflow0 = engine.overflow_rows_total
    drift_feat0 = dict(engine.featurize_rows)
    t_drift = time.perf_counter()
    engine.schedule(units, drifted, follower_index=fidx)
    drift_ms = (time.perf_counter() - t_drift) * 1e3
    drift_feat_rows = {
        k: engine.featurize_rows[k] - drift_feat0[k] for k in drift_feat0
    }
    drift_stage = {k: round(v * 1e3, 1) for k, v in engine.timings.items()}
    drift_dispatches = engine.dispatches_total - drift_dispatches0
    drift_upload = {
        k: engine.upload_bytes[k] - drift_upload0.get(k, 0)
        for k in engine.upload_bytes
    }

    # Device-time attribution (ISSUE 8): decompose the host stage
    # timers into per-program device occupancy + queue wait from the
    # dispatch ledger (runtime/devprof.py).  reconcile_pct compares the
    # summed device_ms against the host-measured device stage — the
    # acceptance check that the attribution is measuring the same
    # physics the stage timers do (steady ticks reconcile tightly; the
    # drift tick's queue_ms is the measured dispatch-backpressure number
    # PR 7 could only infer).
    drift_tick_id = engine.last_tick_id

    def _attr(summaries):
        merged = {"device_ms": 0.0, "queue_ms": 0.0, "records": 0,
                  "stage_device_ms": 0.0, "by_program": {}}
        for s in summaries:
            if not s or s.get("records") is None:
                continue
            merged["device_ms"] += s.get("device_ms", 0.0)
            merged["queue_ms"] += s.get("queue_ms", 0.0)
            merged["records"] += s.get("records", 0)
            merged["stage_device_ms"] += (s.get("stage_ms") or {}).get(
                "device", 0.0
            )
            for kind, slot in (s.get("by_program") or {}).items():
                dst = merged["by_program"].setdefault(
                    kind, {"n": 0, "device_ms": 0.0, "queue_ms": 0.0}
                )
                dst["n"] += slot["n"]
                dst["device_ms"] += slot["device_ms"]
                dst["queue_ms"] += slot["queue_ms"]
        for k in ("device_ms", "queue_ms", "stage_device_ms"):
            merged[k] = round(merged[k], 1)
        for slot in merged["by_program"].values():
            slot["device_ms"] = round(slot["device_ms"], 1)
            slot["queue_ms"] = round(slot["queue_ms"], 1)
        if merged["stage_device_ms"]:
            merged["reconcile_pct"] = round(
                100.0 * merged["device_ms"] / merged["stage_device_ms"], 1
            )
        return merged

    ledger = engine.devprof
    steady_attr = _attr(
        [ledger.tick_summary(t) for t in timed_tick_ids]
    )
    drift_attr = _attr([ledger.tick_summary(drift_tick_id)])
    drift_wf = ledger.waterfall(tick=drift_tick_id, max_records=160)
    device_attr = {
        "enabled": ledger.enabled,
        "steady": steady_attr,
        "drift": drift_attr,
        "waterfall_drift": (
            drift_wf["ticks"][-1] if drift_wf.get("ticks") else None
        ),
    }
    if profile_dir is not None:
        device_attr["profile_dir"] = profile_dir

    detail = {k: round(v / TICKS * 1e3, 1) for k, v in detail.items()}
    # Per-tick throughput series + median: the gate floors the MEDIAN
    # round-to-round (one slow outlier tick — GC pause, first sub-batch
    # compile — can no longer sink or save a round the way the mean
    # could), while the full series stays in the artifact for forensics.
    tick_rates = sorted(N_OBJECTS / w for w in tick_walls)
    mid = len(tick_rates) // 2
    median_rate = (
        tick_rates[mid]
        if len(tick_rates) % 2
        else (tick_rates[mid - 1] + tick_rates[mid]) / 2.0
    )
    detail["objs_per_sec_series"] = [
        round(N_OBJECTS / w, 1) for w in tick_walls
    ]
    detail["objs_per_sec_median"] = round(median_rate, 1)
    detail["device_attr"] = device_attr
    detail["drift_tick_ms"] = round(drift_ms, 1)
    # ISSUE 4: the drift-path stage breakdown + dispatch counts +
    # host->device byte split, so the full-revalidation win (and the
    # proof that ONLY cluster planes crossed the link) is a number.
    detail["drift_stage_ms"] = drift_stage
    detail["drift_dispatches"] = drift_dispatches
    detail["drift_upload_bytes"] = drift_upload
    detail["drift_gate"] = dict(engine.drift_stats)
    # ISSUE 11: unified-survivor shape accounting (padding_ratio is the
    # number the one-stream dispatch exists to push toward 1.0) + the
    # per-phase stale-repair split (drift must stay 0 under eager
    # churn-tick repair).
    detail["survivor_kernel"] = {
        "rows": engine.survivor_stats["rows"],
        "groups": engine.survivor_stats["groups"],
        "padding_ratio": round(
            engine.survivor_stats["padded_rows"]
            / max(1, engine.survivor_stats["rows"]),
            3,
        ),
        "fallback_rows": engine.survivor_stats["fallback_rows"],
    }
    detail["stale_repair_rows"] = dict(engine.stale_repair_rows)
    detail["cold_dispatches"] = cold_dispatches
    detail["upload_bytes"] = dict(engine.upload_bytes)
    # c6 memory census, live half (ISSUE 12): the ACTUAL device bytes of
    # the resident working set at this config, per plane family and per
    # device — runtime/census.py projects the same inventory to 1M x 10k
    # (bench --scenario census) and validates its model against numbers
    # like these.
    detail["resident_bytes"] = engine.resident_state_bytes()
    detail["cold_tick_ms"] = round(cold_ms, 1)
    detail["prewarm_s"] = round(prewarm_s, 1)
    detail["featurize_cold_ms"] = cold_featurize_ms
    # Per-phase featurization attribution (ISSUE 10): featurize_ms +
    # rows featurized {full|delta} per phase, so the 2.8s c5 full
    # rebuild can never silently return to the steady/drift path
    # (counters prove full rebuilds only on cold/topology change;
    # tools/bench_gate.py gates the drift/churn featurize_ms).
    detail["featurize_attr"] = {
        "cold": {"ms": cold_featurize_ms, "rows": cold_feat_rows},
        "steady": {
            # detail["featurize"] is already the per-tick average ms.
            "ms": detail["featurize"],
            "rows": steady_feat_rows,
        },
        "drift": {
            "ms": drift_stage.get("featurize", 0.0),
            "rows": drift_feat_rows,
        },
    }
    detail["noop_tick_ms"] = round(noop_ms, 1)
    # Fetch wire telemetry (ISSUE 3): the per-timed-tick transfer volume
    # the packed export exists to shrink, plus the format and the
    # K-overflow fallback count for the whole run.
    detail["fetch_format"] = engine.fetch_format
    detail["fetch_bytes"] = round(tick_fetch_bytes)
    detail["fetch_bytes_run_total"] = engine.fetch_bytes_total
    detail["fetch_overflow_rows"] = engine.overflow_rows_total
    # Per-phase engine_fetch_overflow_rows_total deltas (ISSUE 7): the
    # adaptive-K hysteresis/widen-once escape is judged by these, and
    # bench-gate surfaces them so a K-policy regression is visible.
    detail["fetch_overflow_rows_tick"] = round(tick_overflow_rows, 1)
    detail["drift_overflow_rows"] = (
        engine.overflow_rows_total - drift_overflow0
    )
    # Narrow solve (ISSUE 5): candidate width, certified-vs-fallback row
    # split for the whole run.  The per-phase wall split (gate_wait /
    # overflow_fetch / narrow_fallback sub-phases) rides stage_ms /
    # drift_stage_ms above via engine.timings.
    detail["narrow"] = {
        "enabled": engine.narrow,
        "m": engine.narrow_last_m,
        "rows": engine.narrow_stats["rows"],
        "fallback_rows": engine.narrow_stats["fallback"],
    }
    detail["cache"] = dict(engine.cache_stats)
    detail["fetch_paths"] = dict(engine.fetch_stats)
    detail["program_shapes"] = sorted(map(list, engine.program_shapes))
    # The engine's live telemetry for the whole run, in catalog
    # vocabulary: counters + gauges verbatim, histograms as sum/count
    # (the per-stage means are recoverable as sum/count).
    snap = metrics.snapshot()
    detail["telemetry"] = {
        "counters": snap["counters"],
        "gauges": snap["gauges"],
        "histograms": {
            key: {"sum": round(h["sum"], 4), "count": h["count"]}
            for key, h in snap["histograms"].items()
        },
    }
    # The units/results of the LAST timed tick: the parity check runs
    # the sequential baseline over this exact world.
    return dt, placed, detail, units, results


def run_churn_scenario() -> None:
    """--scenario churn_rate: sustained-churn streaming benchmark.

    Injects object arrivals/updates (plus periodic single-member
    capacity drift) into a StreamingScheduler during steady operation
    and measures what the always-on pipeline sustains: every slab flush
    re-decides the WHOLE world through the engine's incremental paths,
    so the headline value is objects-revalidated/s — directly comparable
    to the steady-tick objects/s metric — with the event ingest rate and
    event->placement-visible latency p50/p99 in detail.

    Knobs: BENCH_CHURN_SECONDS (measurement window, default 10),
    BENCH_CHURN_RATE (events/s; 0 = saturate, the default),
    BENCH_CHURN_ARRIVALS (fraction of events that are NEW objects,
    default 0.25), BENCH_CHURN_DRIFT_EVERY (capacity-drift event every
    N flushes, 0 = off, default 10), KT_SLAB_ROWS / KT_SLAB_AGE_MS
    (slab watermarks).  ``make bench-churn`` runs this at a small config
    inside the tier-1 time budget and writes BENCH_CHURN_r<n>.json for
    tools/bench_gate.py."""
    import dataclasses

    from kubeadmiral_tpu.runtime.metrics import Metrics
    from kubeadmiral_tpu.scheduler.engine import SchedulerEngine
    from kubeadmiral_tpu.scheduler.streaming import StreamingScheduler

    rng = np.random.default_rng(20260729)
    units, clusters, _followers = build_world(rng)
    names = [c.name for c in clusters]
    metrics = Metrics()
    engine = SchedulerEngine(chunk_size=CHUNK, metrics=metrics)
    t_warm = time.perf_counter()
    engine.prewarm(
        N_OBJECTS,
        N_CLUSTERS,
        scalar_resources=("nvidia.com/gpu",) if CONFIG == "5" else (),
        wait=True,
    )
    prewarm_s = time.perf_counter() - t_warm
    stream = StreamingScheduler(engine, clusters, units, metrics=metrics)
    t_cold = time.perf_counter()
    stream.flush()  # cold tick
    cold_ms = (time.perf_counter() - t_cold) * 1e3
    # Warm the streaming shapes: one churn slab + one capacity drift.
    for i in rng.integers(0, len(units), max(1, len(units) // 100)):
        su = units[int(i)]
        stream.offer(
            dataclasses.replace(
                su, desired_replicas=(su.desired_replicas or 1) + 1
            )
        )
    stream.flush()
    stream.update_cluster(
        dataclasses.replace(
            clusters[1],
            available={
                k: max(0, int(v * 0.9)) for k, v in clusters[1].available.items()
            },
        )
    )
    stream.flush()

    duration = float(os.environ.get("BENCH_CHURN_SECONDS", "10"))
    rate = float(os.environ.get("BENCH_CHURN_RATE", "0"))
    arrivals_frac = float(os.environ.get("BENCH_CHURN_ARRIVALS", "0.25"))
    drift_every = int(os.environ.get("BENCH_CHURN_DRIFT_EVERY", "10"))

    def make_event(seq: int):
        if rng.random() < arrivals_frac:
            return T_unit_arrival(rng, seq, names)
        i = int(rng.integers(0, len(units)))
        su = units[i]
        return dataclasses.replace(
            su,
            desired_replicas=(su.desired_replicas or 1)
            + int(rng.integers(1, 9)),
        )

    flushes0 = stream.flushes
    rows0 = stream.rows_flushed
    events = 0
    drifts = 0
    seq = 0
    overflow0 = engine.overflow_rows_total
    feat_rows0 = dict(engine.featurize_rows)
    stage_totals: dict[str, float] = {}
    lat0 = len(stream.latencies)
    last_flushes = stream.flushes
    t0 = time.perf_counter()
    deadline = t0 + duration
    credit = 0.0
    t_prev = t0
    while True:
        now = time.perf_counter()
        if now >= deadline:
            break
        if rate > 0:
            credit += (now - t_prev) * rate
            t_prev = now
            burst = int(min(credit, stream.slab_rows))
            credit -= burst
        else:
            burst = stream.slab_rows
        for _ in range(burst):
            stream.offer(make_event(seq))
            seq += 1
            events += 1
        if rate > 0 and burst == 0:
            time.sleep(0.001)
        if stream.pump() is not None:
            for stage, secs in engine.timings.items():
                stage_totals[stage] = stage_totals.get(stage, 0.0) + secs
            if (
                drift_every
                and (stream.flushes - last_flushes) >= 0
                and stream.flushes % drift_every == 0
            ):
                j = int(rng.integers(0, len(clusters)))
                base = stream.clusters[j]
                stream.update_cluster(
                    dataclasses.replace(
                        base,
                        available={
                            k: max(1, int(v * float(rng.uniform(0.6, 1.0))))
                            for k, v in base.available.items()
                        },
                    )
                )
                drifts += 1
    if stream.pending():
        stream.flush()
        for stage, secs in engine.timings.items():
            stage_totals[stage] = stage_totals.get(stage, 0.0) + secs
    elapsed = time.perf_counter() - t0
    flushes = stream.flushes - flushes0
    rows = stream.rows_flushed - rows0
    world = len(stream.units)
    lat = np.asarray(list(stream.latencies)[lat0:], float) * 1e3
    value = world * flushes / elapsed if elapsed > 0 else 0.0

    from kubeadmiral_tpu.bench_support import bench_platform_detail

    detail = {
        "config": CONFIG,
        "scenario": "churn_rate",
        **bench_platform_detail(),
        "world_rows": world,
        "flushes": flushes,
        "events": events,
        "events_per_sec": round(events / elapsed, 1) if elapsed else 0.0,
        "rows_flushed": rows,
        "capacity_drifts": drifts,
        "elapsed_s": round(elapsed, 2),
        "latency_ms_p50": round(float(np.percentile(lat, 50)), 2)
        if lat.size
        else None,
        "latency_ms_p99": round(float(np.percentile(lat, 99)), 2)
        if lat.size
        else None,
        "latency_ms_max": round(float(lat.max()), 2) if lat.size else None,
        "slab_rows": stream.slab_rows,
        "slab_age_ms": stream.slab_age_ms,
        "flush_triggers": dict(stream.flush_stats),
        "stage_totals_ms": {
            k: round(v * 1e3, 1) for k, v in stage_totals.items()
        },
        # Featurization attribution (ISSUE 10): per-flush featurize cost
        # (GATED by tools/bench_gate.py once a prior round carries it)
        # and the rows-featurized split — a sustained-churn run must
        # move delta rows only (full rows here mean the O(changed)
        # contract regressed mid-stream).
        "featurize_per_flush_ms": round(
            stage_totals.get("featurize", 0.0) * 1e3 / flushes, 2
        )
        if flushes
        else None,
        "featurize_rows": {
            k: engine.featurize_rows[k] - feat_rows0[k] for k in feat_rows0
        },
        "drift_gate": dict(engine.drift_stats),
        # ISSUE 11: the unified-kernel shape block carried in every
        # BENCH_CHURN artifact (bench-gate surfaces it), plus the
        # stale-repair phase split proving drift ticks see zero.
        "survivor_kernel": {
            "rows": engine.survivor_stats["rows"],
            "groups": engine.survivor_stats["groups"],
            "padding_ratio": round(
                engine.survivor_stats["padded_rows"]
                / max(1, engine.survivor_stats["rows"]),
                3,
            ),
            "fallback_rows": engine.survivor_stats["fallback_rows"],
        },
        "stale_repair_rows": dict(engine.stale_repair_rows),
        "fetch_overflow_rows": engine.overflow_rows_total - overflow0,
        "narrow": {
            "enabled": engine.narrow,
            "m": engine.narrow_last_m,
            "rows": engine.narrow_stats["rows"],
            "fallback_rows": engine.narrow_stats["fallback"],
        },
        "prewarm_s": round(prewarm_s, 1),
        "cold_tick_ms": round(cold_ms, 1),
    }
    result = {
        "metric": f"churn_objs_per_sec_{N_OBJECTS}x{N_CLUSTERS}",
        "value": round(value, 1),
        "unit": "objects/s",
        "detail": detail,
    }
    print(json.dumps(result))
    print(
        f"# churn_rate config {CONFIG}: {value:.0f} obj/s revalidated "
        f"({events} events, {flushes} flushes, {drifts} drifts) in "
        f"{elapsed:.1f}s; latency p50={detail['latency_ms_p50']}ms "
        f"p99={detail['latency_ms_p99']}ms",
        file=sys.stderr,
    )
    _save_churn_artifact(result)


def _memory_sample() -> dict:
    """Process peak RSS + live device-buffer bytes at the call point —
    the restart scenario samples both boots so the AOT no-donation
    trade (preloaded programs keep un-donated prev buffers alive) is a
    measured number per round, not a docs note."""
    import resource

    import jax

    ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # ru_maxrss is KiB on Linux, bytes on macOS.
    peak_mb = ru / 1024.0 if sys.platform != "darwin" else ru / (1024.0**2)
    try:
        dev_bytes = int(
            sum(getattr(a, "nbytes", 0) for a in jax.live_arrays())
        )
    except Exception:
        dev_bytes = None
    return {
        "peak_rss_mb": round(peak_mb, 1),
        "device_buffer_bytes": dev_bytes,
    }


def run_restart_scenario() -> None:
    """--scenario restart: the restart-to-first-tick SLO benchmark.

    Phase 1 (this process = the COLD boot): prewarm the ladder (tracing
    AND exporting every program into the AOT manifest under
    KT_COMPILE_CACHE_DIR), run the cold tick, persist a durable engine
    snapshot.  Phase 2 (a fresh subprocess = the WARM replacement): AOT
    manifest + persistent compile cache replace the trace ladder, the
    snapshot restores the engine's prev planes, and the first converged
    tick rides the no-op replay gate — ``restart_to_first_tick_ms``
    measures engine construction through that first parity-exact tick.

    The warm child asserts bit-exact parity against the cold run's
    placement fingerprints; the artifact (BENCH_RESTART_r<n>.json) is
    GATED by tools/bench_gate.py (value ceiling vs best prior
    same-platform round) with snapshot size / write-ms informational."""
    import subprocess
    import tempfile

    from kubeadmiral_tpu.runtime.metrics import Metrics
    from kubeadmiral_tpu.runtime.snapshot import SnapshotManager, SnapshotStore
    from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

    warm = os.environ.get("KT_RESTART_WARM") == "1"
    workdir = os.environ.get("KT_RESTART_BENCH_DIR")
    if workdir is None:
        if warm:
            raise SystemExit("KT_RESTART_WARM=1 requires KT_RESTART_BENCH_DIR")
        workdir = tempfile.mkdtemp(prefix="kt-bench-restart-")
        # Fresh AOT manifest root for this round: the COLD measurement
        # must trace the ladder (a prior round's manifest would make it
        # silently warm), while the XLA persistent cache stays ambient —
        # cold boots have always benefited from it, the trace ladder is
        # what they re-pay.  Must be set before the engine constructs.
        os.environ["KT_AOT_DIR"] = os.path.join(workdir, "aot")

    rng = np.random.default_rng(20260729)
    units, clusters, followers = build_world(rng)
    names = [c.name for c in clusters]
    fidx = follower_index(followers) if followers else None

    metrics = Metrics()
    t_boot = time.perf_counter()
    engine = SchedulerEngine(chunk_size=CHUNK, metrics=metrics)
    store = SnapshotStore(os.path.join(workdir, "snapshots"), metrics=metrics)
    mgr = SnapshotManager(engine, store, every=1)

    if warm:
        restore = mgr.restore()
        # Background AOT preload, exactly like the production manager's
        # non-blocking prewarm: the first converged tick does not need
        # the ladder — a fresh-snapshot resume is ZERO device dispatches
        # (the no-op replay gate), a stale one traces at most the gate
        # programs — so restart-to-first-tick must not wait on it.
        # warm_ready_ms (ladder fully preloaded, steady-state-capable)
        # is reported alongside.
        t_warm = time.perf_counter()
        warm_thread = engine.prewarm(
            N_OBJECTS, N_CLUSTERS,
            scalar_resources=("nvidia.com/gpu",) if CONFIG == "5" else (),
            wait=False,
        )
        t_tick = time.perf_counter()
        results = engine.schedule(units, clusters, follower_index=fidx)
        tick_ms = (time.perf_counter() - t_tick) * 1e3
        total_ms = (time.perf_counter() - t_boot) * 1e3
        warm_thread.join()
        ready_ms = (time.perf_counter() - t_boot) * 1e3
        prewarm_s = time.perf_counter() - t_warm
        cold_fp = np.load(os.path.join(workdir, "cold_fp.npy"))
        got_fp = _fingerprint_results(results, names)
        mism = int((got_fp != cold_fp).any(axis=1).sum())
        print(json.dumps({
            "restart_to_first_tick_ms": round(total_ms, 1),
            "warm_ready_ms": round(ready_ms, 1),
            "warm_prewarm_s": round(prewarm_s, 2),
            "warm_tick_ms": round(tick_ms, 1),
            "restore": restore,
            "restore_info": engine.restore_info,
            "fetch_paths": dict(engine.fetch_stats),
            "aot": dict(engine._aot.stats),
            "parity": mism == 0,
            "parity_mismatches": mism,
            # ROADMAP loose end (ISSUE 11): the AOT no-donation memory
            # cost, measured at the fully-preloaded point (AOT-compiled
            # programs drop prev-buffer donation, so a warm boot holds
            # more live device state than a cold one).
            **_memory_sample(),
        }))
        return

    # -- cold boot (parent) ----------------------------------------------
    t_warmup = time.perf_counter()
    engine.prewarm(
        N_OBJECTS, N_CLUSTERS,
        scalar_resources=("nvidia.com/gpu",) if CONFIG == "5" else (),
        wait=True,
    )
    prewarm_s = time.perf_counter() - t_warmup
    t_cold = time.perf_counter()
    results = engine.schedule(units, clusters, follower_index=fidx)
    cold_tick_ms = (time.perf_counter() - t_cold) * 1e3
    cold_boot_ms = prewarm_s * 1e3 + cold_tick_ms
    np.save(
        os.path.join(workdir, "cold_fp.npy"),
        _fingerprint_results(results, names),
    )
    snapshot_bytes = store.last_bytes
    snapshot_write_ms = round(store.last_write_s * 1e3, 1)
    cold_mem = _memory_sample()

    env = dict(os.environ)
    env["KT_RESTART_WARM"] = "1"
    env["KT_RESTART_BENCH_DIR"] = workdir
    child = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--scenario", "restart"],
        env=env, capture_output=True, text=True,
        timeout=int(os.environ.get("KT_RESTART_TIMEOUT_S", "3600")),
    )
    # ROADMAP's "multi-chip failover needs its own AOT story": measure
    # the N-device warm boot explicitly.  Exports pin topology, so the
    # meshed engine runs AOT in live-trace-only mode — this number is
    # the trace-ladder cost a multi-device replacement actually pays
    # (XLA compiles still hit the ambient persistent cache), with the
    # honest AOT stats (loaded=0, traced>0) alongside.
    multidev = _multidev_restart_probe()
    if child.returncode != 0:
        raise SystemExit(
            f"warm-restart child failed rc={child.returncode}:\n"
            f"{child.stdout}\n{child.stderr}"
        )
    warm_doc = json.loads(child.stdout.strip().splitlines()[-1])

    from kubeadmiral_tpu.bench_support import bench_platform_detail

    value = warm_doc["restart_to_first_tick_ms"]
    ratio_pct = round(100.0 * value / cold_boot_ms, 1) if cold_boot_ms else None
    detail = {
        "config": CONFIG,
        "scenario": "restart",
        **bench_platform_detail(),
        "cold_boot_ms": round(cold_boot_ms, 1),
        "cold_prewarm_s": round(prewarm_s, 2),
        "cold_tick_ms": round(cold_tick_ms, 1),
        "warm_vs_cold_pct": ratio_pct,
        "snapshot_bytes": snapshot_bytes,
        "snapshot_write_ms": snapshot_write_ms,
        "cold_aot": dict(engine._aot.stats),
        **{k: warm_doc[k] for k in (
            "warm_ready_ms", "warm_prewarm_s", "warm_tick_ms", "restore",
            "restore_info", "fetch_paths", "aot", "parity",
            "parity_mismatches",
        )},
        # The measured N-device warm-boot cost (None only when probing
        # was disabled via KT_RESTART_MULTIDEV=0).
        "multidevice": multidev,
        # Warm-vs-cold memory cost of the AOT preload path (ROADMAP
        # loose end; docs/operations.md § Restart & failover runbook).
        "memory": {
            "cold_peak_rss_mb": cold_mem["peak_rss_mb"],
            "cold_device_buffer_bytes": cold_mem["device_buffer_bytes"],
            "warm_peak_rss_mb": warm_doc.get("peak_rss_mb"),
            "warm_device_buffer_bytes": warm_doc.get(
                "device_buffer_bytes"
            ),
        },
    }
    result = {
        "metric": f"restart_to_first_tick_ms_{N_OBJECTS}x{N_CLUSTERS}",
        "value": value,
        "unit": "ms",
        "detail": detail,
    }
    print(json.dumps(result))
    print(
        f"# restart config {CONFIG}: warm {value:.0f}ms vs cold "
        f"{cold_boot_ms:.0f}ms ({ratio_pct}%); aot={warm_doc['aot']} "
        f"restore={warm_doc['restore_info']} parity={warm_doc['parity']}",
        file=sys.stderr,
    )
    _save_round_artifact(result, "BENCH_RESTART")


def _multidev_restart_probe():
    """Boot a meshed engine in a forced-N-device subprocess (the
    ``--xla_force_host_platform_device_count`` mechanism the dryrun and
    tier-1 multidevice tests use) and measure prewarm + first tick — the
    restart story at N>1, where AOT is live-trace-only by design.
    KT_RESTART_MULTIDEV picks N (default 4; 0/1 disables).  Probe
    failures degrade to an error record, never fail the round."""
    import re as _re
    import subprocess

    n = int(os.environ.get("KT_RESTART_MULTIDEV", "4") or 0)
    if n <= 1:
        return None
    code = (
        "import json, time\n"
        "import numpy as np\n"
        "t0 = time.perf_counter()\n"
        "from kubeadmiral_tpu.scheduler.engine import SchedulerEngine\n"
        "from kubeadmiral_tpu.runtime.census import _census_world\n"
        "units, clusters = _census_world(np.random.default_rng(7), 2048, 128)\n"
        "eng = SchedulerEngine()\n"
        "assert eng.mesh is not None, 'expected an auto mesh'\n"
        "t1 = time.perf_counter()\n"
        "eng.prewarm(len(units), len(clusters), wait=True)\n"
        "prewarm_s = time.perf_counter() - t1\n"
        "t2 = time.perf_counter()\n"
        "eng.schedule(units, clusters)\n"
        "print(json.dumps({\n"
        "    'device_count': int(eng.mesh.devices.size),\n"
        "    'warm_boot_ms': round((time.perf_counter() - t0) * 1e3, 1),\n"
        "    'prewarm_s': round(prewarm_s, 2),\n"
        "    'first_tick_ms': round((time.perf_counter() - t2) * 1e3, 1),\n"
        "    'pipeline_depth': eng.pipeline_depth,\n"
        "    'aot': dict(eng._aot.stats),\n"
        "}))\n"
    )
    env = dict(os.environ)
    flag = f"--xla_force_host_platform_device_count={n}"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = _re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, flags
        )
    else:
        flags = f"{flags} {flag}".strip()
    env["XLA_FLAGS"] = flags
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never claim the chip
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env,
            capture_output=True, text=True,
            timeout=int(os.environ.get("KT_RESTART_TIMEOUT_S", "3600")),
        )
        if proc.returncode != 0:
            return {
                "error": f"rc={proc.returncode}",
                "stderr": proc.stderr[-500:],
            }
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001 — probe must not fail the round
        return {"error": str(e)}


def run_census_scenario() -> None:
    """--scenario census: the c6 memory census (ISSUE 12).

    Three steps, one artifact (BENCH_CENSUS_r<n>.json):

    1. **Validate** the analytic model against a LIVE engine at a small
       shape (actual device buffer bytes vs projection — a model that
       can't predict 8k x 256 has no business predicting 1M x 10k).
    2. **Project** the resident-plane inventory at the census shape
       (KT_CENSUS_OBJECTS x KT_CENSUS_CLUSTERS, default 1M x 10k) on
       KT_CENSUS_DEVICES devices (default 4).
    3. **Decide** compress-or-shard against KT_HBM_BUDGET_GB: the
       resolved configuration (f16 score plane engaged and/or the
       minimum objects-axis device count) must be under budget —
       tools/bench_gate.py FAILS the round when it is not, and the
       validation error exceeds tolerance fails too."""
    import jax

    from kubeadmiral_tpu.bench_support import bench_platform_detail
    from kubeadmiral_tpu.runtime import census

    b = int(os.environ.get("KT_CENSUS_OBJECTS", "1000000"))
    c = int(os.environ.get("KT_CENSUS_CLUSTERS", "10000"))
    n_dev = int(os.environ.get("KT_CENSUS_DEVICES", "4"))
    budget = census.hbm_budget_bytes()
    t0 = time.perf_counter()
    validation = census.validate(
        int(os.environ.get("KT_CENSUS_VALIDATE_OBJECTS", "8192")),
        int(os.environ.get("KT_CENSUS_VALIDATE_CLUSTERS", "256")),
    )
    decision = census.decide(b, c, n_dev, budget)
    # The resolved configuration: what the census tells the operator to
    # RUN — compression engaged unless everything fits as-is, device
    # count raised to the minimum that fits when sharding is the verdict.
    resolved = census.project(
        b, c, decision["min_devices"],
        score_f16=decision["verdict"] != "fits",
    )
    resolved_over = resolved["per_device"] > budget
    value = resolved["per_device"]
    detail = {
        "scenario": "census",
        **bench_platform_detail(),
        "census_shape": f"{b}x{c}",
        "requested_devices": n_dev,
        "budget_gb": round(budget / (1 << 30), 2),
        "decision": {
            k: decision[k]
            for k in (
                "verdict", "per_device_i32", "per_device_f16",
                "min_devices", "reasons_i16_would_save",
            )
        },
        "resolved": resolved,
        "over_budget": bool(resolved_over),
        "validation": validation,
        "census_wall_s": round(time.perf_counter() - t0, 1),
        "local_device_count": int(jax.device_count()),
    }
    result = {
        "metric": f"resident_bytes_per_device_{b}x{c}",
        "value": value,
        "unit": "bytes",
        "detail": detail,
    }
    print(json.dumps(result))
    print(
        f"# census {b}x{c}: verdict={decision['verdict']} "
        f"per_device={value / (1 << 30):.2f}GiB @"
        f"{decision['min_devices']}dev (budget "
        f"{budget / (1 << 30):.0f}GiB, requested {n_dev}dev: "
        f"i32 {decision['per_device_i32'] / (1 << 30):.2f} / f16 "
        f"{decision['per_device_f16'] / (1 << 30):.2f}GiB); model err "
        f"{validation['prev_planes_err_pct']}%",
        file=sys.stderr,
    )
    _save_round_artifact(result, "BENCH_CENSUS")


def _soak_schedule():
    """The soak's deterministic script, sized by the KT_SOAK_* knobs
    (docs/operations.md); every role (parent, oracle, victim,
    successor) derives the identical schedule from the inherited env."""
    from kubeadmiral_tpu.testing.soakharness import SoakSchedule

    return SoakSchedule(
        rounds=int(os.environ.get("KT_SOAK_ROUNDS", "10") or 10),
        arrivals_per_round=int(os.environ.get("KT_SOAK_ARRIVALS", "6") or 6),
        kill_round=int(os.environ.get("KT_SOAK_KILL_ROUND", "5") or 5),
    )


def _soak_observatory():
    """Install the full observability stack a production manager would
    run — SLO recorder, tenant ledger, telemetry timeline — sharing one
    Metrics registry (the timeline samples it; /debug would serve it)."""
    from kubeadmiral_tpu.runtime import slo as slo_mod
    from kubeadmiral_tpu.runtime import tenancy, timeline
    from kubeadmiral_tpu.runtime.metrics import Metrics

    m = Metrics()
    rec = slo_mod.reset_default()
    ledger = tenancy.TenantLedger(metrics=m)
    tenancy.set_default(ledger)
    tl = timeline.Timeline(metrics=m)
    timeline.set_default(tl)
    return m, rec, ledger, tl


def _soak_red_outside(timeline_doc: dict, windows: list) -> list:
    """Every raw-tier slo_red sample > 0 whose timestamp is not covered
    by a declared injection window (t1 None = open at process death =
    covered through +inf).  Raw tier: each bucket is one sample at its
    own instant, so a point's time IS the red instant — coarser tiers'
    MAX-merge would smear a red sample across a whole bucket."""
    out = []
    slack = 0.25
    raw = (timeline_doc.get("tiers") or {}).get("raw") or {}
    for key, series in sorted((raw.get("series") or {}).items()):
        if not key.startswith("slo_red{"):
            continue
        for t, v in series.get("points") or []:
            if v <= 0:
                continue
            covered = any(
                w["t0"] - slack
                <= t
                <= (w["t1"] if w["t1"] is not None else float("inf")) + slack
                for w in windows
            )
            if not covered:
                out.append({"series": key, "t": round(t, 3), "value": v})
    return out


def _soak_scheduled(tenants_doc: dict) -> int:
    return sum(
        t.get("scheduled", 0)
        for t in (tenants_doc.get("tenants") or {}).values()
    )


def _soak_spiller(workdir: str, instance: str, metrics, tl):
    """The role's crash-durable telemetry spiller (runtime/telespill.py)
    over the soak workdir — explicit per-round spill_now, no thread, so
    what survives a SIGKILL is deterministic: everything through the
    last completed round.  Returns None when KT_SPILL=0 (the A/B
    overhead arm — the gate then falls back to the state-file
    timelines)."""
    from kubeadmiral_tpu.runtime import telespill

    spiller = telespill.TelemetrySpiller(
        directory=os.path.join(workdir, "telemetry"),
        instance=instance, metrics=metrics, timeline=tl, interval_s=0,
    )
    return spiller if spiller.enabled else None


def _soak_spill_recover(spill_dir: str) -> dict:
    """Per-instance telemetry recovered from spill segments, re-anchored
    on the WALL clock: every record envelope carries (wall, mono) at
    spill time, so each process's monotonic timeline points and fault
    windows map onto the one clock the merged gate evaluates on.

    {instance: {"series": {key: {"kind", "points": [[wall_t, v]]}},
                "offset": wall - mono, "records": n,
                "first_wall": .., "last_wall": ..}}
    """
    from kubeadmiral_tpu.runtime import telespill

    instances: dict[str, dict] = {}
    for rec in telespill.load_dir(spill_dir, quarantine=False):
        name = rec.get("instance")
        wall = rec.get("wall")
        mono = rec.get("mono")
        if name is None or wall is None:
            continue
        inst = instances.setdefault(
            name,
            {
                "series": {}, "offset": None, "records": 0,
                "first_wall": wall, "last_wall": wall,
            },
        )
        inst["records"] += 1
        inst["first_wall"] = min(inst["first_wall"], wall)
        inst["last_wall"] = max(inst["last_wall"], wall)
        if rec.get("kind") != "timeline" or mono is None:
            continue
        offset = wall - mono
        inst["offset"] = offset
        for key, series in (rec.get("series") or {}).items():
            entry = inst["series"].setdefault(
                key, {"kind": series.get("kind"), "points": []}
            )
            for t, v in series.get("points") or ():
                entry["points"].append([t + offset, v])
    for inst in instances.values():
        for entry in inst["series"].values():
            entry["points"].sort()
    return instances


def _soak_merged_red_outside(
    spill: dict, victim_windows: list, succ_windows: list
) -> list:
    """Red-outside-windows over the ONE merged victim+successor
    timeline recovered from spill — both processes' slo_red samples and
    both processes' injection windows on the shared wall clock.  A
    window the victim died inside (t1 None) closes at the victim's last
    spill instant: past its death the victim asserts nothing, and the
    successor's own windows must cover the successor's reds."""
    victim = spill.get("victim") or {}
    succ = spill.get("successor") or {}
    merged: dict[str, dict] = {}
    for inst in (victim, succ):
        for key, series in (inst.get("series") or {}).items():
            entry = merged.setdefault(
                key, {"kind": series.get("kind"), "points": []}
            )
            entry["points"].extend(series["points"])
    for entry in merged.values():
        entry["points"].sort()
    windows = []
    death = victim.get("last_wall")
    for w in victim_windows:
        t1 = w["t1"] if w["t1"] is not None else None
        windows.append(
            {
                "t0": w["t0"] + victim["offset"],
                "t1": t1 + victim["offset"] if t1 is not None else death,
            }
        )
    for w in succ_windows:
        windows.append(
            {
                "t0": w["t0"] + succ["offset"],
                "t1": w["t1"] + succ["offset"]
                if w["t1"] is not None else None,
            }
        )
    doc = {"tiers": {"raw": {"series": merged}}}
    return _soak_red_outside(doc, windows)


# The failover gap (last victim spill -> first successor spill) rides
# on subprocess spawn + full package import + snapshot restore; 60s is
# a generous machine-variance bound that still catches a wedged or
# never-started successor.
_SOAK_GAP_BOUND_S = 60.0


def _soak_failover_gap(spill: dict) -> dict | None:
    """The observable failover gap, recovered purely from spill: the
    wall-clock distance between the victim's last surviving record and
    the successor's first.  None when either side spilled nothing."""
    victim = spill.get("victim") or {}
    succ = spill.get("successor") or {}
    if victim.get("last_wall") is None or succ.get("first_wall") is None:
        return None
    gap = succ["first_wall"] - victim["last_wall"]
    return {
        "gap_s": round(gap, 3),
        "bound_s": _SOAK_GAP_BOUND_S,
        "bounded": 0.0 <= gap <= _SOAK_GAP_BOUND_S,
        "victim_last_wall": round(victim["last_wall"], 3),
        "successor_first_wall": round(succ["first_wall"], 3),
    }


def _soak_shardmap():
    """The child's shard scope for the sharded soak (KT_SOAK_SHARDS>1):
    the victim/successor pair runs as shard 0, one PEER replica per
    remaining shard runs every round uninterrupted, and the oracle stays
    unsharded (no ``_KT_SOAK_SHARD`` → None even when the knob is set,
    so the oracle's world is the full-keyspace reference)."""
    count = int(os.environ.get("KT_SOAK_SHARDS", "1") or 1)
    index = os.environ.get("_KT_SOAK_SHARD")
    if count <= 1 or index is None:
        return None
    from kubeadmiral_tpu.federation import shardmap

    return shardmap.ShardMap(count, int(index))


def _soak_scope(sm):
    import contextlib

    if sm is None:
        return contextlib.nullcontext()
    from kubeadmiral_tpu.federation import shardmap

    return shardmap.scoped(sm)


def _soak_child_exit() -> None:
    """Exit a soak child without interpreter teardown: XLA's worker
    threads intermittently corrupt the glibc heap during normal exit
    (observed as ``double free`` / ``free(): invalid pointer`` aborts
    AFTER the child's JSON is fully flushed).  The child's work is on
    stdout and its spill segments are closed by then, so skip teardown
    the same way the victim's SIGKILL does."""
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)


def run_soak_scenario() -> None:
    """--scenario soak: the all-stressors-at-once gated soak.

    Four processes, one deterministic :class:`SoakSchedule`
    (testing/soakharness.py): the ORACLE runs every round with no
    faults and no restart; the VICTIM runs rounds 0..kill_round with a
    flapping member, a hard-down member, arrival churn and capacity
    drift all active, dumps its fleet + telemetry after every round,
    then SIGKILLs itself; the SUCCESSOR restores the victim's fleet
    dump + engine snapshot and finishes the remaining rounds under the
    same faults.  The PARENT (this process) asserts the successor's
    final placements are bit-identical to the oracle's, evaluates
    "burn-rate evaluator never red outside a declared injection
    window" from both recorded timelines, and emits the gated
    SOAK_r<n>.json artifact (tools/bench_gate.py gate_soak)."""
    import signal
    import subprocess
    import tempfile

    # Chaos-grade SLO windows (the bench_e2e chaos stage's settings):
    # freshness must notice a hard-down member within ~1s and the burn
    # windows must decay within the post-recovery settle.  Children
    # inherit these via the environment.
    os.environ.setdefault("KT_SLO_FRESHNESS_S", "1.0")
    os.environ.setdefault("KT_SLO_WINDOWS_S", "3,10")
    role = os.environ.get("_KT_SOAK_ROLE", "")
    workdir = os.environ.get("_KT_SOAK_DIR", "")
    sched = _soak_schedule()
    state_path = os.path.join(workdir, "soak_state.json") if workdir else ""

    if role == "oracle":
        from kubeadmiral_tpu.testing.soakharness import SoakHarness

        m, rec, ledger, tl = _soak_observatory()
        h = SoakHarness(sched, metrics=m)
        h.attach_timeline(tl)
        t0 = time.perf_counter()
        for r in range(sched.rounds):
            h.run_round(r, faults=False)
        h.finish()
        print(json.dumps({
            "fingerprint": h.fingerprint(),
            "elapsed_s": round(time.perf_counter() - t0, 3),
        }))
        _soak_child_exit()
        return

    if role == "victim":
        from kubeadmiral_tpu.runtime.snapshot import (
            SnapshotManager,
            SnapshotStore,
        )
        from kubeadmiral_tpu.testing.soakharness import SoakHarness

        m, rec, ledger, tl = _soak_observatory()
        sm = _soak_shardmap()
        with _soak_scope(sm):
            h = SoakHarness(sched, metrics=m)
        snap_dir = os.path.join(workdir, "snapshots")
        if sm is not None:
            # Per-shard snapshot artifacts (ISSUE 20): keyed by shard
            # directory AND stamped with (count, index, epoch) so the
            # successor refuses a snapshot from the wrong shard.
            from kubeadmiral_tpu.runtime.snapshot import shard_snapshot_store

            store = shard_snapshot_store(snap_dir, sm, metrics=m)
        else:
            store = SnapshotStore(snap_dir, metrics=m)
        SnapshotManager(h.scheduler.engine, store, every=1, shard=sm)
        h.attach_timeline(tl)
        spiller = _soak_spiller(workdir, "victim", m, tl)
        t0 = time.perf_counter()
        for r in range(sched.kill_round + 1):
            h.run_round(r, faults=True)
            state = {
                "round": r,
                "elapsed_s": round(time.perf_counter() - t0, 3),
                "windows": h.windows,
                "timeline": tl.to_doc(),
                "tenants": ledger.summary(),
                "fleet": h.fleet.dump(),
            }
            tmp = state_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(state, fh)
            os.replace(tmp, state_path)
            # Crash-durability contract: the spill after round r is what
            # the SIGKILL below must not be able to take away.
            if spiller is not None:
                spiller.spill_now()
        # SIGKILL mid-fault-window: no atexit, no snapshot flush, no
        # window close — the successor and the gate must cope with the
        # state exactly as the last completed round left it.
        sys.stdout.flush()
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)
        return  # unreachable

    if role == "successor":
        from kubeadmiral_tpu.runtime.snapshot import (
            SnapshotManager,
            SnapshotStore,
        )
        from kubeadmiral_tpu.testing.fakekube import ClusterFleet
        from kubeadmiral_tpu.testing.soakharness import SoakHarness

        with open(state_path) as fh:
            state = json.load(fh)
        fleet = ClusterFleet.restore(state["fleet"])
        m, rec, ledger, tl = _soak_observatory()
        sm = _soak_shardmap()
        with _soak_scope(sm):
            h = SoakHarness(sched, metrics=m, fleet=fleet)
        snap_dir = os.path.join(workdir, "snapshots")
        if sm is not None:
            from kubeadmiral_tpu.runtime.snapshot import shard_snapshot_store

            store = shard_snapshot_store(snap_dir, sm, metrics=m)
        else:
            store = SnapshotStore(snap_dir, metrics=m)
        mgr = SnapshotManager(h.scheduler.engine, store, every=1, shard=sm)
        restored = mgr.restore()
        h.attach_timeline(tl)
        spiller = _soak_spiller(workdir, "successor", m, tl)
        if spiller is not None:
            # First record at takeover (not after a full round): the
            # parent's failover gap measures restore time, not round
            # time on top.
            tl.sample_now()
            spiller.spill_now()
        t0 = time.perf_counter()
        for r in range(state["round"] + 1, sched.rounds):
            h.run_round(r, faults=True)
            if spiller is not None:
                spiller.spill_now()
        h.finish()
        if spiller is not None:
            spiller.stop()  # final spill + segment close
        print(json.dumps({
            "fingerprint": h.fingerprint(),
            "windows": h.windows,
            "timeline": tl.to_doc(),
            "tenants": ledger.summary(),
            "slo": rec.summary(slowest=0),
            "restore": restored,
            "elapsed_s": round(time.perf_counter() - t0, 3),
        }))
        _soak_child_exit()
        return

    if role == "peer":
        # A sharded-soak replica that is NOT the failover victim: runs
        # every round with the same faults, never killed — the survivor
        # half of the "union of shards matches the oracle" check.
        from kubeadmiral_tpu.testing.soakharness import SoakHarness

        m, rec, ledger, tl = _soak_observatory()
        with _soak_scope(_soak_shardmap()):
            h = SoakHarness(sched, metrics=m)
        h.attach_timeline(tl)
        t0 = time.perf_counter()
        for r in range(sched.rounds):
            h.run_round(r, faults=True)
        h.finish()
        print(json.dumps({
            "fingerprint": h.fingerprint(),
            "elapsed_s": round(time.perf_counter() - t0, 3),
        }))
        _soak_child_exit()
        return

    # -- parent: orchestrate oracle -> victim -> SIGKILL -> successor ----
    workdir = tempfile.mkdtemp(prefix="kt-bench-soak-")
    shard_count = int(os.environ.get("KT_SOAK_SHARDS", "1") or 1)
    victim_shard = 0 if shard_count > 1 else None

    def spawn(child_role: str, shard=None) -> subprocess.CompletedProcess:
        env = dict(os.environ)
        env["_KT_SOAK_ROLE"] = child_role
        env["_KT_SOAK_DIR"] = workdir
        env["BENCH_SCENARIO"] = "soak"
        if shard is not None:
            env["_KT_SOAK_SHARD"] = str(shard)
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True, text=True, env=env, timeout=1200,
        )

    def parse(proc: subprocess.CompletedProcess, who: str) -> dict:
        if proc.returncode != 0:
            raise SystemExit(
                f"soak {who} failed rc={proc.returncode}:\n"
                + proc.stderr[-4000:]
            )
        return json.loads(proc.stdout.strip().splitlines()[-1])

    oracle = parse(spawn("oracle"), "oracle")
    peers = [
        parse(spawn("peer", shard=i), f"peer-{i}")
        for i in range(1, shard_count)
    ]
    victim_proc = spawn("victim", shard=victim_shard)
    if victim_proc.returncode != -signal.SIGKILL:
        raise SystemExit(
            f"soak victim expected SIGKILL, got rc={victim_proc.returncode}:\n"
            + victim_proc.stderr[-4000:]
        )
    state_path = os.path.join(workdir, "soak_state.json")
    with open(state_path) as fh:
        victim = json.load(fh)
    succ = parse(spawn("successor", shard=victim_shard), "successor")

    oracle_fp = oracle["fingerprint"]
    succ_fp = succ["fingerprint"]
    if shard_count > 1:
        # Union of the N shards' placements (successor carries shard 0
        # through the failover) vs the unsharded oracle — after
        # asserting each replica stayed inside its own slice of the
        # ring and no key was claimed twice.
        from kubeadmiral_tpu.federation import shardmap
        from kubeadmiral_tpu.utils.hashing import stable_json_hash

        union: dict = {}
        parts = [(0, succ_fp)] + [
            (i, peers[i - 1]["fingerprint"]) for i in range(1, shard_count)
        ]
        for i, fp in parts:
            sm = shardmap.ShardMap(shard_count, i)
            for key, val in fp["placements"].items():
                assert sm.owns(key), f"shard {i} wrote non-owned key {key}"
                assert key not in union, f"key {key} claimed by two shards"
                union[key] = val
        succ_fp = {
            "objects": len(union),
            "hash": stable_json_hash(union),
            "placements": union,
        }
    oracle_match = (
        succ_fp["hash"] == oracle_fp["hash"]
        and succ_fp["placements"] == oracle_fp["placements"]
    )
    mismatched = sorted(
        k
        for k in set(oracle_fp["placements"]) | set(succ_fp["placements"])
        if oracle_fp["placements"].get(k) != succ_fp["placements"].get(k)
    )
    # The gate's red-outside evaluation runs on the ONE merged
    # victim+successor timeline recovered from the crash-durable spill
    # (both processes' samples and windows on the shared wall clock) —
    # the victim's side is what actually survived the SIGKILL, not what
    # it promised in its state file.  KT_SPILL=0 (the overhead A/B arm)
    # falls back to the per-process state-file timelines.
    spill = _soak_spill_recover(os.path.join(workdir, "telemetry"))
    failover = _soak_failover_gap(spill)
    if (
        (spill.get("victim") or {}).get("offset") is not None
        and (spill.get("successor") or {}).get("offset") is not None
    ):
        red_source = "spill-merged"
        red_outside = _soak_merged_red_outside(
            spill, victim["windows"], succ["windows"]
        )
    else:
        red_source = "state-fallback"
        red_outside = _soak_red_outside(
            victim["timeline"], victim["windows"]
        ) + _soak_red_outside(succ["timeline"], succ["windows"])

    scheduled = _soak_scheduled(victim["tenants"]) + _soak_scheduled(
        succ["tenants"]
    )
    elapsed = victim["elapsed_s"] + succ["elapsed_s"]
    rate = scheduled / max(elapsed, 1e-9)
    p99_s = (
        (succ["slo"].get("stages") or {}).get("total") or {}
    ).get("p99_s")
    tl_stats = {
        k: succ["timeline"].get(k)
        for k in (
            "samples_total", "approx_bytes", "dropped_buckets_total",
            "provider_errors_total", "sample_seconds_total",
        )
    }
    from kubeadmiral_tpu.bench_support import bench_platform_detail

    result = {
        "metric": (
            f"soak_objs_per_sec_{sched.rounds}r"
            f"x{sched.arrivals_per_round}a"
        ),
        "value": round(rate, 1),
        "unit": "objects/s",
        "detail": {
            **bench_platform_detail(),
            "rounds": sched.rounds,
            "kill_round": sched.kill_round,
            "arrivals_per_round": sched.arrivals_per_round,
            "shards": shard_count,
            "peer_objects": [p["fingerprint"]["objects"] for p in peers],
            "objects": succ_fp["objects"],
            "scheduled_total": scheduled,
            "elapsed_s": round(elapsed, 3),
            "oracle_match": oracle_match,
            "mismatched_keys": mismatched[:20],
            "red_outside_windows": red_outside,
            "red_outside_source": red_source,
            "failover": failover,
            "spill": {
                name: {
                    "records": inst["records"],
                    "timeline_series": len(inst["series"]),
                }
                for name, inst in sorted(spill.items())
            },
            "windows": {
                "victim": victim["windows"],
                "successor": succ["windows"],
            },
            "restore": succ["restore"],
            "victim_rounds": victim["round"] + 1,
            "event_p99_ms": round(p99_s * 1e3, 1)
            if p99_s is not None
            else None,
            "timeline": tl_stats,
            "tenants": succ["tenants"],
            "ktlint": ktlint_summary(),
        },
    }
    print(json.dumps(result))
    print(
        f"# soak: {sched.rounds} rounds (kill@{sched.kill_round}), "
        f"{succ_fp['objects']} objects, {scheduled} scheduled in "
        f"{elapsed:.1f}s -> {rate:.0f} obj/s; oracle_match={oracle_match} "
        f"red_outside={len(red_outside)} ({red_source}) "
        f"failover_gap={failover['gap_s'] if failover else None}s "
        f"restore={succ['restore']} "
        f"event_p99={result['detail']['event_p99_ms']}ms",
        file=sys.stderr,
    )
    _save_round_artifact(result, "SOAK")


def _save_round_artifact(result: dict, prefix: str) -> None:
    """Persist a scenario result as <prefix>_r<n>.json (next free round
    number) so tools/bench_gate.py can compare rounds."""
    import re as _re

    root = os.path.dirname(os.path.abspath(__file__))
    rounds = [
        int(m.group(1))
        for f in os.listdir(root)
        if (m := _re.match(rf"{prefix}_r(\d+)\.json$", f))
    ]
    path = os.path.join(
        root, f"{prefix}_r{max(rounds, default=0) + 1:02d}.json"
    )
    with open(path, "w") as fh:
        json.dump({"rc": 0, "parsed": result}, fh, indent=1)
    print(f"# restart artifact: {os.path.basename(path)}", file=sys.stderr)


def T_unit_arrival(rng, seq: int, names) -> object:
    """A fresh arriving object (the streaming scheduler places it in a
    placeholder slot)."""
    from kubeadmiral_tpu.models.types import (
        MODE_DIVIDE,
        SchedulingUnit,
        parse_resources,
    )

    divide = seq % 3 != 0
    return SchedulingUnit(
        gvk="apps/v1/Deployment",
        namespace=f"arrivals-{seq % 13}",
        name=f"arrival-{seq:07d}",
        scheduling_mode=MODE_DIVIDE if divide else "Duplicate",
        desired_replicas=int(rng.integers(1, 50)) if divide else None,
        resource_request=parse_resources(
            {
                "cpu": f"{int(rng.integers(0, 8)) * 250}m",
                "memory": f"{int(rng.integers(0, 16)) * 256}Mi",
            }
        ),
        max_clusters=int(rng.integers(1, 20)) if seq % 5 == 0 else None,
    )


def _save_churn_artifact(result: dict) -> None:
    """Persist the scenario result as BENCH_CHURN_r<n>.json (next free
    round number) so tools/bench_gate.py can compare rounds."""
    import re as _re

    root = os.path.dirname(os.path.abspath(__file__))
    rounds = [
        int(m.group(1))
        for f in os.listdir(root)
        if (m := _re.match(r"BENCH_CHURN_r(\d+)\.json$", f))
    ]
    path = os.path.join(
        root, f"BENCH_CHURN_r{max(rounds, default=0) + 1:02d}.json"
    )
    with open(path, "w") as fh:
        json.dump({"rc": 0, "parsed": result}, fh, indent=1)
    print(f"# churn artifact: {os.path.basename(path)}", file=sys.stderr)


def _fingerprint_native(sel, rep, cnt) -> np.ndarray:
    """Per-row placement fingerprint of a native output chunk:
    (n selected, Σcol, Σcol², Σreplicas, Σreplicas·(col+1)) — position-
    and value-sensitive, so it catches any per-object divergence in the
    selected set or the per-cluster replica assignment."""
    c = sel.shape[1]
    cols = np.arange(c, dtype=np.int64)
    selb = sel.astype(np.int64)
    cntb = cnt.astype(np.int64)
    return np.stack(
        [
            selb.sum(1),
            (selb * cols).sum(1),
            (selb * cols * cols).sum(1),
            (rep * cntb).sum(1),
            (rep * cntb * (cols + 1)).sum(1),
        ],
        axis=1,
    )


def _fingerprint_results(results, names) -> np.ndarray:
    """The same fingerprint computed from the batched tick's decoded
    ScheduleResults."""
    name_idx = {n: i for i, n in enumerate(names)}
    out = np.zeros((len(results), 5), np.int64)
    for i, r in enumerate(results):
        n = s = s2 = rs = rc = 0
        for cname, repv in r.clusters.items():
            ci = name_idx[cname]
            n += 1
            s += ci
            s2 += ci * ci
            if repv is not None:
                rs += repv
                rc += repv * (ci + 1)
        out[i] = (n, s, s2, rs, rc)
    return out


def time_native_baseline(units, clusters):
    """The compiled sequential scheduler over the full batch, fed
    pre-featurized, pre-marshalled arrays (neither featurization nor
    numpy dtype conversion is charged to it).  Also returns the per-row
    placement fingerprints for the batched-vs-native parity check
    (computed outside the timed window)."""
    from kubeadmiral_tpu.native import load as native_load
    from kubeadmiral_tpu.native.seqsched import prepare, run
    from kubeadmiral_tpu.scheduler.featurize import featurize

    if native_load() is None:
        return None, 0, None
    # Stream chunk by chunk (featurize+prepare excluded from the timed
    # window): materializing every dense chunk up front would hold
    # ~250 MB x chunks in RAM at the 100k x 5k config.
    total = 0.0
    placed = 0
    view = None
    fingerprints = []
    for start in range(0, len(units), CHUNK):
        chunk = units[start : start + CHUNK]
        fb = featurize(chunk, clusters, view=view)
        view = fb.view
        prepared = prepare(fb.inputs)
        t0 = time.perf_counter()
        out = run(prepared)
        total += time.perf_counter() - t0
        placed += int((out[0].sum(axis=1) > 0).sum())
        fingerprints.append(_fingerprint_native(*out))
    return total, placed, np.concatenate(fingerprints)


def parity_check(results, native_fps, names, followers) -> dict:
    """Batched-vs-native placement parity at the full bench shape
    (VERDICT r4 #4): per-object selected set + per-cluster replica
    assignment must agree.  Follower rows are excluded — their placement
    is the post-schedule leader union, which only the batched path
    applies (the reference's follower controller does it outside the
    scheduler too)."""
    got = _fingerprint_results(results, names)
    mask = np.ones(len(results), bool)
    if followers:
        mask[np.asarray(followers)] = False
    rows = np.nonzero(mask)[0]
    agree = (got[mask] == native_fps[mask]).all(axis=1)
    mismatches = int((~agree).sum())
    out = {
        "parity": mismatches == 0,
        "parity_rows_checked": int(mask.sum()),
        "parity_mismatches": mismatches,
    }
    if mismatches:
        # Diagnosis sample: which rows, and how the (count, idx-sum,
        # idx-sq-sum, replica-sum, replica-dot) fingerprints differ.
        bad = rows[~agree][:8]
        out["parity_sample"] = [
            {
                "row": int(r),
                "got": [int(v) for v in got[r]],
                "want": [int(v) for v in native_fps[r]],
            }
            for r in bad
        ]
    return out


def time_python_oracle(units, clusters, sample=200):
    from kubeadmiral_tpu.bench_support import sequential_schedule

    t0 = time.perf_counter()
    sequential_schedule(units[:sample], clusters)
    return (time.perf_counter() - t0) / sample


def main():
    from kubeadmiral_tpu.runtime.gctune import tune_gc_for_service

    tune_gc_for_service()
    scenario = os.environ.get("BENCH_SCENARIO", "")
    if "--scenario" in sys.argv:
        scenario = sys.argv[sys.argv.index("--scenario") + 1]
    if scenario == "churn_rate":
        run_churn_scenario()
        return
    if scenario == "restart":
        run_restart_scenario()
        return
    if scenario == "census":
        run_census_scenario()
        return
    if scenario == "soak":
        run_soak_scenario()
        return
    if scenario:
        raise SystemExit(f"unknown bench scenario {scenario!r}")
    rng = np.random.default_rng(20260729)
    units, clusters, followers = build_world(rng)

    tick_seconds, placed, detail, final_units, final_results = time_batched(
        rng, units, clusters, followers
    )
    # Baseline runs over the exact world the batched path last decided
    # (the final churned tick), so placements are directly comparable.
    native_seconds, native_placed, native_fps = time_native_baseline(
        final_units, clusters
    )

    batched_rate = N_OBJECTS / tick_seconds
    if native_seconds is not None:
        native_rate = N_OBJECTS / native_seconds
        vs = batched_rate / native_rate
        detail["native_baseline_ms"] = round(native_seconds * 1e3, 1)
    else:  # no toolchain: fall back to the (slower) python oracle
        per_obj = time_python_oracle(units, clusters)
        native_rate = 1.0 / per_obj
        vs = batched_rate / native_rate
        detail["native_baseline_ms"] = None

    from kubeadmiral_tpu.bench_support import bench_platform_detail

    parity = (
        parity_check(
            final_results,
            native_fps,
            [c.name for c in clusters],
            followers,
        )
        if native_fps is not None
        else {"parity": None}
    )

    telemetry = detail.pop("telemetry", None)
    device_attr = detail.pop("device_attr", None)
    objs_series = detail.pop("objs_per_sec_series", None)
    objs_median = detail.pop("objs_per_sec_median", None)
    fetch_format = detail.pop("fetch_format", None)
    fetch_bytes = detail.pop("fetch_bytes", None)
    fetch_bytes_run = detail.pop("fetch_bytes_run_total", None)
    fetch_overflow = detail.pop("fetch_overflow_rows", None)
    narrow = detail.pop("narrow", None)
    result = {
        "metric": f"objects_scheduled_per_sec_{N_OBJECTS}x{N_CLUSTERS}",
        "value": round(batched_rate, 1),
        "unit": "objects/s",
        "vs_baseline": round(vs, 2),
        "detail": {
            "config": CONFIG,
            **bench_platform_detail(),
            "tick_ms": round(tick_seconds * 1e3, 1),
            "objs_per_sec_series": objs_series,
            "objs_per_sec_median": objs_median,
            "fetch_format": fetch_format,
            "fetch_bytes": fetch_bytes,
            "fetch_bytes_run_total": fetch_bytes_run,
            "fetch_overflow_rows": fetch_overflow,
            "narrow": narrow,
            "stage_ms": detail,
            "device_attr": device_attr,
            "telemetry": telemetry,
            "ktlint": ktlint_summary(),
            "baseline": "native-seqsched(g++ -O3)"
            if native_seconds is not None
            else "python-oracle",
            "baseline_objects_per_sec": round(native_rate, 1),
            "placed": placed,
            "native_placed": native_placed,
            **parity,
        },
    }
    print(json.dumps(result))
    print(
        f"# config {CONFIG}: tick={tick_seconds * 1e3:.0f}ms for "
        f"{N_OBJECTS}x{N_CLUSTERS} ({placed} placed) -> {batched_rate:.0f} obj/s; "
        f"stages(ms)={detail}; native sequential "
        f"{native_rate:.0f} obj/s ({native_placed} placed)",
        file=sys.stderr,
    )


if __name__ == "__main__":
    from kubeadmiral_tpu.bench_support import run_resilient

    run_resilient(main, __file__)
