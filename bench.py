"""Benchmark: batched TPU scheduling tick vs the sequential in-process scheduler.

Workload: BASELINE.md config #3 shape — a mixed Deployment/StatefulSet
batch with taint/affinity masks, static+dynamic weights and capacity
feedback, scheduled against taint/label-heterogeneous member clusters.

Baseline: the sequential per-object reference implementation
(kubeadmiral_tpu.ops.pipeline_oracle.schedule_one) — a faithful
re-statement of the reference's in-process scheduler control flow
(pkg/controllers/scheduler, one object at a time through
Filter -> Score -> Select -> planner).  It is timed on a sample and
extrapolated; vs_baseline = batched throughput / sequential throughput.

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

N_OBJECTS = int(__import__("os").environ.get("BENCH_OBJECTS", 10_000))
N_CLUSTERS = int(__import__("os").environ.get("BENCH_CLUSTERS", 500))
ORACLE_SAMPLE = 400
TICKS = 3


def build_world(rng):
    from kubeadmiral_tpu.models.types import (
        AutoMigrationSpec,
        ClusterAffinity,
        ClusterState,
        MODE_DIVIDE,
        PreferredSchedulingTerm,
        SelectorRequirement,
        SelectorTerm,
        SchedulingUnit,
        Taint,
        Toleration,
        parse_resources,
    )

    gvks = ("apps/v1/Deployment", "apps/v1/StatefulSet")
    regions = ("us", "eu", "ap")
    clusters = []
    for j in range(N_CLUSTERS):
        cpu = int(rng.integers(32, 512))
        mem_gi = int(rng.integers(128, 2048))
        free_frac = float(rng.uniform(0.1, 0.9))
        clusters.append(
            ClusterState(
                name=f"member-{j:05d}",
                labels={
                    "region": regions[j % 3],
                    "zone": f"z{j % 17}",
                    "tier": str(j % 4),
                },
                taints=(Taint("dedicated", "batch", "NoSchedule"),)
                if j % 11 == 0
                else (),
                allocatable=parse_resources(
                    {"cpu": str(cpu), "memory": f"{mem_gi}Gi"}
                ),
                available=parse_resources(
                    {
                        "cpu": f"{int(cpu * free_frac * 1000)}m",
                        "memory": f"{int(mem_gi * free_frac)}Gi",
                    }
                ),
                api_resources=frozenset(gvks),
            )
        )

    affinities = [None] + [
        ClusterAffinity(
            required=(
                SelectorTerm(
                    match_expressions=(
                        SelectorRequirement("region", "In", (regions[k],)),
                    )
                ),
            ),
            preferred=(
                PreferredSchedulingTerm(
                    weight=30,
                    preference=SelectorTerm(
                        match_expressions=(
                            SelectorRequirement("tier", "In", ("0", "1")),
                        )
                    ),
                ),
            ),
        )
        for k in range(3)
    ] + [None]

    units = []
    for i in range(N_OBJECTS):
        divide = i % 4 != 0
        units.append(
            SchedulingUnit(
                gvk=gvks[i % 2],
                namespace=f"ns-{i % 97}",
                name=f"workload-{i:06d}",
                scheduling_mode=MODE_DIVIDE if divide else "Duplicate",
                desired_replicas=int(rng.integers(1, 100)) if divide else None,
                resource_request=parse_resources(
                    {
                        "cpu": f"{int(rng.integers(0, 8)) * 250}m",
                        "memory": f"{int(rng.integers(0, 16)) * 256}Mi",
                    }
                ),
                tolerations=(Toleration(key="dedicated", operator="Exists"),)
                if i % 3 == 0
                else (),
                affinity=affinities[i % len(affinities)],
                max_clusters=int(rng.integers(1, 20)) if i % 5 == 0 else None,
                avoid_disruption=bool(i % 2),
                auto_migration=AutoMigrationSpec(
                    estimated_capacity={
                        f"member-{int(rng.integers(0, N_CLUSTERS)):05d}": int(
                            rng.integers(0, 50)
                        )
                    }
                )
                if i % 7 == 0
                else None,
            )
        )
    return units, clusters


def time_batched(units, clusters):
    from kubeadmiral_tpu.scheduler.engine import SchedulerEngine

    engine = SchedulerEngine(chunk_size=4096)
    engine.schedule(units, clusters)  # warm the compile caches at full shape
    t0 = time.perf_counter()
    for _ in range(TICKS):
        results = engine.schedule(units, clusters)
    dt = (time.perf_counter() - t0) / TICKS
    placed = sum(1 for r in results if r.clusters)
    return dt, placed


def time_sequential_via_oracle(units, clusters):
    from kubeadmiral_tpu.bench_support import sequential_schedule

    sample = units[:ORACLE_SAMPLE]
    t0 = time.perf_counter()
    sequential_schedule(sample, clusters)
    dt = time.perf_counter() - t0
    return dt / len(sample)


def main():
    rng = np.random.default_rng(20260729)
    units, clusters = build_world(rng)

    tick_seconds, placed = time_batched(units, clusters)
    per_obj_seq = time_sequential_via_oracle(units, clusters)

    batched_rate = N_OBJECTS / tick_seconds
    seq_rate = 1.0 / per_obj_seq
    result = {
        "metric": f"objects_scheduled_per_sec_{N_OBJECTS}x{N_CLUSTERS}",
        "value": round(batched_rate, 1),
        "unit": "objects/s",
        "vs_baseline": round(batched_rate / seq_rate, 2),
    }
    print(json.dumps(result))
    print(
        f"# tick={tick_seconds * 1e3:.1f}ms for {N_OBJECTS} objects x "
        f"{N_CLUSTERS} clusters ({placed} placed); sequential reference "
        f"{seq_rate:.1f} obj/s (sampled {ORACLE_SAMPLE})",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
